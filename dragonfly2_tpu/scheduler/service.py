"""Scheduler service: the AnnouncePeer stream and resource RPCs.

Reference: scheduler/service/service_v2.go — AnnouncePeer bidi stream
dispatching on typed requests (:84), handleRegisterPeerRequest (:991),
handleDownloadPiece{Finished,Failed} (:1291-1455), handleResource (:1457,
get/create host+task+peer), downloadTaskBySeedPeer (:1504, back-to-source
dedup via seed triggering), plus StatPeer/StatTask/AnnounceHost/LeaveHost.

Stream protocol (drpc "Scheduler.AnnouncePeer"):
  open_body: {host:{...}, peer_id, task_id, url, tag, application, digest,
              filters, header, priority, range, is_seed}
  client → server: register | download_started | piece_finished |
                   piece_failed | reschedule | download_finished |
                   download_failed
  server → client: empty_task | normal_task{task, parents} |
                   need_back_source{reason} | schedule_failed{reason}
"""

from __future__ import annotations

import asyncio

from dragonfly2_tpu.pkg import aio, dflog
from dragonfly2_tpu.pkg import cluster as clusterlib
from dragonfly2_tpu.pkg import fleet as fleetlib
from dragonfly2_tpu.pkg import flight as flightlib
from dragonfly2_tpu.pkg import podlens as podlenslib
from dragonfly2_tpu.pkg import slo as slolib
from dragonfly2_tpu.pkg.errors import Code, DfError
from dragonfly2_tpu.pkg.fsm import TransitionError
from dragonfly2_tpu.pkg.piece import PieceInfo, SizeScope
from dragonfly2_tpu.pkg.types import HostType
from dragonfly2_tpu.proto import reportcodec
from dragonfly2_tpu.rpc import RpcContext, ServerStream
from dragonfly2_tpu.scheduler.config import SchedulerConfig
from dragonfly2_tpu.scheduler.resource import (
    Host,
    HostManager,
    Peer,
    PeerManager,
    PeerState,
    Task,
    TaskManager,
    TaskState,
)
from dragonfly2_tpu.scheduler.scheduling import Scheduling
from dragonfly2_tpu.scheduler.scheduling import stripe as stripe_mod
from dragonfly2_tpu.scheduler.scheduling.scheduling import ScheduleResult
from dragonfly2_tpu.scheduler.seed_client import SeedPeerClientPool

log = dflog.get("scheduler.service")

from dragonfly2_tpu.pkg import metrics  # noqa: E402

REGISTER_SCOPE_COUNT = metrics.counter(
    "scheduler_register_size_scope_total",
    "Peer registrations by task size scope shortcut", ("scope",))

PARENT_PICK_COUNT = metrics.counter(
    "scheduler_parent_picks_total",
    "Scheduled parent handouts by ICI locality: intra (same tpu_slice), "
    "cross (different slices), unlabeled (either end without coordinates)",
    ("locality",))

STRIPE_HANDOUT_COUNT = metrics.counter(
    "scheduler_stripe_handouts_total",
    "Striped-broadcast plan deliveries: striped (handout carried a stripe) "
    "or reshuffle (membership-change push to a live slice member)",
    ("kind",))

PARENT_DEMOTION_COUNT = metrics.counter(
    "scheduler_parent_quarantine_total",
    "Hosts entering scheduler-side quarantine from typed piece_failed "
    "reports, by tipping reason", ("reason",))

PEER_REREGISTER_COUNT = metrics.counter(
    "scheduler_peer_reregister_total",
    "Terminal peers replaced by a fresh registration (announce-stream "
    "recovery after a drop)")

REPORT_BATCH_COUNT = metrics.counter(
    "scheduler_report_batches_total",
    "Ingested piece-report batches (piece_finished counts as a batch of "
    "one), by wire encoding: packed (proto/reportcodec columns) or dict "
    "(legacy per-piece PIECE maps)", ("encoding",))

INGEST_BATCH_PIECES = metrics.histogram(
    "scheduler_ingest_batch_pieces",
    "Pieces per ingested report batch — how well the announce wire "
    "coalesces under load (1 = idle single-piece latency path)",
    buckets=(1, 2, 4, 8, 16, 32, 64, 128, 256, 1024))

STATE_REBUILT_COUNT = metrics.counter(
    "scheduler_state_rebuilt_peers_total",
    "Peers whose Task/Peer state this scheduler rebuilt without having "
    "watched the download: resume-carrying re-registrations after a "
    "failover/restart, and durable-snapshot restores at boot",
    ("source",))

# Chaos fabric hook (pkg/chaos site ``sched.announce``): severs/stalls
# the server side of announce streams so failover paths can be driven
# deterministically. None unless chaos.enable() arms it — the hot loop
# pays one ``is not None`` check.
_chaos = None


class SchedulerService:
    def __init__(self, config: SchedulerConfig | None = None):
        self.config = config or SchedulerConfig()
        gc = self.config.gc
        self.hosts = HostManager(ttl=gc.host_ttl)
        self.tasks = TaskManager(ttl=gc.task_ttl)
        self.peers = PeerManager(ttl=gc.peer_ttl)
        self.scheduling = Scheduling(self.config.scheduling)
        self.seed_clients = SeedPeerClientPool()
        from dragonfly2_tpu.scheduler.resource.persistentcache import (
            PersistentCacheResource,
        )

        self.persistent = PersistentCacheResource(self.config.persistent_cache_db)
        # Pod-level flight aggregation: per-host phase attribution from
        # piece-report timings + quarantine correlation, served at
        # /debug/pod/<task_id> (scheduler/server wires it into the
        # MetricsServer).
        self.pod_flight = flightlib.PodAggregator()
        # Fleet observatory (pkg/fleet): bounded cluster time-series +
        # cross-task host scorecards + scheduling decision audit log, fed
        # from the report paths below and served at /debug/fleet* by the
        # scheduler's MetricsServer. The scorecard straggler flag feeds
        # an advisory filter into scheduling._is_candidate.
        fc = self.config.fleet
        self.fleet: "fleetlib.FleetObservatory | None" = None
        if fc.enabled:
            self.fleet = fleetlib.FleetObservatory(
                bucket_s=fc.bucket_s, buckets=fc.buckets,
                decision_cap=fc.decision_cap, max_hosts=fc.scorecard_hosts,
                straggler_z=fc.straggler_z,
                min_serve_samples=fc.min_serve_samples,
                min_population=fc.min_population,
                sampler=self._fleet_gauges,
                config_snapshot={
                    "seed_peer_enabled": self.config.seed_peer_enabled,
                    "cluster_id": self.config.cluster_id,
                    "scheduling": {
                        "algorithm": self.config.scheduling.algorithm,
                        "candidate_parent_limit":
                            self.config.scheduling.candidate_parent_limit,
                        "retry_interval":
                            self.config.scheduling.retry_interval,
                        "stripe_min_slice_peers":
                            self.config.scheduling.stripe_min_slice_peers,
                    },
                    "gc": {"peer_ttl": gc.peer_ttl, "task_ttl": gc.task_ttl,
                           "host_ttl": gc.host_ttl},
                })
            if fc.straggler_filter:
                self.scheduling.wire_fleet(self.fleet)
        # Pod lens (pkg/podlens): per-host clock alignment from announce
        # round-trip samples + the bounded store of shipped flight
        # digests, merged on demand into /debug/pod/<task>/timeline.
        plc = self.config.podlens
        self.pod_lens: "podlenslib.PodLens | None" = None
        if plc.enabled:
            self.pod_lens = podlenslib.PodLens(
                max_tasks=plc.max_tasks,
                clock_estimator=podlenslib.ClockEstimator(
                    max_hosts=plc.clock_hosts))
        # SLO engine (pkg/slo): continuous burn rates over the fleet
        # time-series + pod completions, served at /debug/slo.
        self.slo: "slolib.SLOEngine | None" = None
        if plc.enabled and plc.slo_enabled:
            self.slo = slolib.SLOEngine(
                series=self.fleet.series if self.fleet else None,
                max_completions=plc.max_completions)
        # Tenant QoS plane (dragonfly2_tpu/qos): per-tenant burn-rate book
        # fed from shipped flights; its snapshot rides the manager
        # keepalive so job admission can push back on a burning tenant.
        # Always on — it is a handful of bounded deques, and handout
        # deprioritization should not depend on the pod lens being up.
        from dragonfly2_tpu.qos import TenantBurnBook

        self.tenant_burn = TenantBurnBook()
        self.scheduling.wire_qos(self.tenant_burn.throttled)
        self._tenant_admission_state: dict[str, str] = {}
        # Scheduler HA (crash recovery): durable bounded snapshot of live
        # task/peer/host state, restored at boot so a restarted scheduler
        # serves correct parent sets and stripe plans before every host
        # has re-announced; live resume re-registrations converge to the
        # same state (scheduler/resource/snapshot.py).
        self.snapshot = None
        if self.config.ha.enabled:
            from dragonfly2_tpu.scheduler.resource.snapshot import (
                SnapshotStore,
            )

            self.snapshot = SnapshotStore(
                self.config.ha.snapshot_db
                or self.config.persistent_cache_db)
            restored = self.restore_from_snapshot()
            if restored:
                log.info("state restored from snapshot", **restored)
        # Cluster control tower (pkg/cluster): a bounded fleet frame —
        # time-series rollup since last ship, SLO burn, straggler /
        # quarantined sets, decision-kind deltas — rides every manager
        # keepalive next to tenant_burn (manager_payload below).
        self.frame_builder: "clusterlib.FrameBuilder | None" = None
        if self.fleet is not None:
            self.frame_builder = clusterlib.FrameBuilder(
                self.fleet, slo=self.slo,
                hostname=self.config.hostname,
                quarantined=self._quarantined_hosts,
                max_bytes=self.config.fleet.frame_max_bytes)

    def _quarantined_hosts(self) -> list:
        return [h.id for h in self.hosts.all() if h.quarantined()]

    def manager_payload(self) -> dict:
        """Everything the scheduler piggybacks on its manager keepalive:
        the tenant burn-book snapshot (job admission) plus the cluster
        fleet frame. Frame build failures are logged and dropped — a
        telemetry bug must never stall the liveness wire."""
        out = self.tenant_burn_payload()
        if self.frame_builder is not None:
            try:
                frame = self.frame_builder.build()
                if frame is not None:
                    out["fleet_frame"] = frame
            except Exception:
                log.warning("fleet frame build failed", exc_info=True)
        return out

    def tenant_burn_payload(self) -> dict:
        """Keepalive piggyback for the manager's admission controller:
        {"tenant_burn": {tenant: {burn, state, completions}}}. Breach
        transitions (either direction) are recorded in the fleet decision
        log as ``admission`` decisions with the TENANT as subject —
        transition-only, so the log stays bounded while /debug/fleet/
        decisions?kind=admission shows when and why each tenant's jobs
        started (and stopped) being pushed back."""
        snap = self.tenant_burn.snapshot()
        for tenant, info in snap.items():
            prev = self._tenant_admission_state.get(tenant)
            state = info["state"]
            if state != prev and "breach" in (state, prev):
                if self.fleet is not None:
                    self.fleet.note_admission(
                        tenant,
                        decision="deny" if state == "breach" else "restore",
                        burn=info["burn"], source="burn_book")
            self._tenant_admission_state[tenant] = state
        return {"tenant_burn": snap}

    def _fleet_gauges(self) -> dict:
        """Gauge sample for the fleet time-series. O(hosts+peers+tasks)
        scans — called at bucket rotation (amortized once per bucket_s)
        and on /debug/fleet snapshots, never per event."""
        hc = self.hosts.counts()
        return {
            "hosts_total": hc["total"],
            "hosts_seed": hc["seed"],
            "hosts_quarantined": hc["quarantined"],
            "peers_running": sum(1 for p in self.peers.all()
                                 if not p.is_done()),
            "tasks_active": sum(1 for t in self.tasks.all()
                                if t.fsm.current == TaskState.RUNNING),
            "straggler_hosts": len(
                self.fleet.scorecards._stragglers) if self.fleet else 0,
        }

    # ------------------------------------------------------------------ #
    # HA: durable snapshot save/restore (scheduler/resource/snapshot.py)
    # ------------------------------------------------------------------ #

    def snapshot_flush(self) -> dict | None:
        """Write the bounded live-state snapshot (periodic GC-style task
        in scheduler/server.py + once at stop)."""
        if self.snapshot is None:
            return None
        ha = self.config.ha
        return self.snapshot.save(
            self.hosts.all(), self.tasks.all(), self.peers.all(),
            max_tasks=ha.max_tasks, max_peers=ha.max_peers)

    def restore_from_snapshot(self) -> dict | None:
        """Rebuild Host/Task/Peer objects from the snapshot rows. Piece
        metadata rebuilds through the SAME apply path live resume
        re-registration uses, so snapshot load and re-registration are one
        code path and converge by construction (property-tested)."""
        if self.snapshot is None:
            return None
        data = self.snapshot.load()
        if not data["peers"] and not data["tasks"]:
            return None
        for hw in data["hosts"]:
            host = self.hosts.load_or_store(
                Host(
                    hw.get("id", "unknown"),
                    hostname=hw.get("hostname", ""), ip=hw.get("ip", ""),
                    port=hw.get("port", 0),
                    upload_port=hw.get("upload_port", 0),
                    host_type=HostType(hw.get("type", 0)),
                    idc=hw.get("idc", ""), location=hw.get("location", ""),
                    tpu_slice=hw.get("tpu_slice", ""),
                    tpu_worker_index=hw.get("tpu_worker_index", -1),
                ))
            host.touch()
        for tr in data["tasks"]:
            task = self.tasks.load_or_store(Task(
                tr["task_id"], url=tr["url"], tag=tr["tag"],
                application=tr["application"], digest=tr["digest"],
                back_to_source_limit=self.config.scheduling.back_to_source_count,
                range_header=tr["range_header"],
            ))
            task.update_lengths(tr["content_length"], tr["piece_size"],
                                tr["total_piece_count"])
            task.fsm.restore(tr["state"])
        restored_peers = 0
        for pr in data["peers"]:
            task = self.tasks.load(pr["task_id"])
            host = self.hosts.load(pr["host_id"])
            if task is None or host is None:
                continue
            peer = self.peers.load_or_store(Peer(
                pr["peer_id"], task, host,
                is_seed=bool(pr["is_seed"]), priority=pr["priority"],
                range_header=pr["range_header"],
            ))
            peer.fsm.restore(pr["state"])
            peer.pod_broadcast = bool(pr["pod_broadcast"])
            self._apply_resume_pieces(task, peer, pr["piece_nums"])
            restored_peers += 1
            STATE_REBUILT_COUNT.labels("snapshot").inc()
        return {"hosts": len(data["hosts"]), "tasks": len(data["tasks"]),
                "peers": restored_peers}

    def _apply_resume_pieces(self, task: Task, peer: Peer,
                             piece_nums) -> int:
        """Idempotently install a re-announced landed-piece bitset: the
        peer's finished set plus task piece metadata computed from the
        task geometry (digests arrive via the idempotent re-report that
        follows — the duplicate path backfills them)."""
        added = 0
        ps = task.piece_size
        cl = task.content_length
        for num in piece_nums:
            num = int(num)
            if num in peer.finished_pieces:
                continue
            peer.finished_pieces.add(num)
            added += 1
            if ps > 0 and num not in task.pieces:
                offset = num * ps
                size = ps if cl < 0 else max(0, min(ps, cl - offset))
                task.store_piece(PieceInfo(
                    piece_num=num, range_start=offset, range_size=size))
        if added:
            peer.touch()
            task.touch()
        return added

    # ------------------------------------------------------------------ #
    # resource resolution (reference handleResource :1457)
    # ------------------------------------------------------------------ #

    def _resolve(self, open_body: dict) -> tuple[Host, Task, Peer]:
        h = open_body.get("host") or {}
        host = self.hosts.load_or_store(
            Host(
                h.get("id") or h.get("hostname", "unknown"),
                hostname=h.get("hostname", ""),
                ip=h.get("ip", ""),
                port=h.get("port", 0),
                upload_port=h.get("upload_port", 0),
                host_type=HostType(h.get("type", 0)),
                idc=h.get("idc", ""),
                location=h.get("location", ""),
                tpu_slice=h.get("tpu_slice", ""),
                tpu_worker_index=h.get("tpu_worker_index", -1),
            )
        )
        # Keep ports fresh: a daemon restart re-announces with new ports.
        host.port = h.get("port", host.port)
        host.upload_port = h.get("upload_port", host.upload_port)

        task_for_digest = self.tasks.load(open_body["task_id"])
        if (task_for_digest is not None and not task_for_digest.digest
                and open_body.get("digest")):
            # Backfill: a later registrant may know the content digest the
            # first one didn't — it guards the tiny inline-content cache.
            task_for_digest.digest = open_body["digest"]
        if (task_for_digest is not None and not task_for_digest.tenant
                and open_body.get("tenant")):
            # Same backfill posture for the QoS attribution tag: the first
            # registrant's tenant wins, later ones fill an empty slot.
            task_for_digest.tenant = open_body["tenant"]

        task = self.tasks.load_or_store(
            Task(
                open_body["task_id"],
                url=open_body.get("url", ""),
                tag=open_body.get("tag", ""),
                application=open_body.get("application", ""),
                digest=open_body.get("digest", ""),
                filtered_query_params=open_body.get("filters") or [],
                header=open_body.get("header") or {},
                back_to_source_limit=self.config.scheduling.back_to_source_count,
                range_header=open_body.get("range", ""),
                tenant=open_body.get("tenant", ""),
            )
        )
        stale = self.peers.load(open_body["peer_id"])
        if stale is not None and stale.fsm.current in (PeerState.FAILED,
                                                       PeerState.LEAVE):
            # Announce-stream recovery: the daemon's stream died mid-task
            # (scheduler restart, net blip) and _on_stream_gone failed the
            # peer. The SAME peer id re-registering is the conductor
            # reconnecting — replace the terminal record with a fresh one;
            # its completed pieces re-arrive via the recovery re-report
            # (idempotent application) so it becomes a usable parent again.
            self.peers.delete(stale.id)
            PEER_REREGISTER_COUNT.inc()
            if self.fleet is not None:
                self.fleet.note_register(reconnect=True)
            log.info("terminal peer re-registered", peer=stale.id[:24],
                     prior_state=stale.fsm.current)
        peer = self.peers.load_or_store(
            Peer(
                open_body["peer_id"],
                task,
                host,
                is_seed=bool(open_body.get("is_seed")),
                priority=open_body.get("priority", 3),
                range_header=open_body.get("range", ""),
                disable_back_source=bool(open_body.get("disable_back_source")),
            )
        )
        if open_body.get("pod_broadcast"):
            # Sticky across re-announces: once a peer declared the task a
            # pod broadcast it stays a stripe member until it leaves.
            peer.pod_broadcast = True
        return host, task, peer

    # ------------------------------------------------------------------ #
    # AnnouncePeer stream (reference service_v2.go:84)
    # ------------------------------------------------------------------ #

    async def announce_peer(self, stream: ServerStream, ctx: RpcContext) -> None:
        open_body = stream.open_body or {}
        if not open_body.get("task_id") or not open_body.get("peer_id"):
            raise DfError(Code.BadRequest, "task_id and peer_id required")
        host, task, peer = self._resolve(open_body)
        peer.announce_stream = stream
        if self.fleet is not None:
            self.fleet.note_register()
        log.info("announce peer", peer=peer.id[:24], task=task.id[:16],
                 host=host.id, seed=peer.is_seed)
        try:
            while True:
                msg = await stream.recv()
                if msg is None:
                    break
                if _chaos is not None and await _chaos.on_frame(
                        "sched.announce", peer.id) == "drop":
                    # Scheduler-side stream sever: from the daemon's view
                    # its announce stream just died mid-download — the
                    # failover/recovery machinery must take over.
                    break
                await self._dispatch(msg, task, peer)
                if peer.is_done():
                    break
        finally:
            peer.announce_stream = None
            self._on_stream_gone(task, peer)

    async def _dispatch(self, msg: dict, task: Task, peer: Peer) -> None:
        kind = msg.get("type", "")
        if kind == "register":
            await self._handle_register(task, peer, msg)
        elif kind == "download_started":
            self._handle_download_started(msg, task, peer)
        elif kind == "piece_finished":
            self._handle_piece_finished(msg, task, peer)
        elif kind == "pieces_finished":
            self._handle_pieces_finished(msg, task, peer)
        elif kind == "piece_failed":
            self._handle_piece_failed(msg, task, peer)
        elif kind == "reschedule":
            await self._handle_reschedule(msg, task, peer)
        elif kind == "download_finished":
            self._handle_download_finished(msg, task, peer)
        elif kind == "download_failed":
            self._handle_download_failed(msg, task, peer)
        else:
            log.warning("unknown announce message", kind=kind, peer=peer.id[:24])

    # -- register (reference handleRegisterPeerRequest :991) --------------

    @staticmethod
    def _stamped(msg: dict) -> dict:
        """Every register/reschedule ANSWER carries the scheduler's
        anchored wall clock: the daemon brackets the round trip with its
        own t0/t1 stamps and the triple becomes a clock-alignment sample
        (pkg/podlens.ClockEstimator) shipped back inside the flight
        digest — no extra RPC, the announce stream IS the time source."""
        msg["sched_wall"] = flightlib.anchored_wall()
        # Capability negotiation rides the same piggyback: this flag
        # tells the conductor the scheduler decodes packed piece-report
        # batches and resume bitmaps (proto/reportcodec). The daemon
        # re-learns it from every reconnect answer, so failover to an
        # older scheduler downgrades the wire automatically.
        msg["packed_reports"] = True
        return msg

    async def _handle_register(self, task: Task, peer: Peer,
                               msg: dict | None = None) -> None:
        # Failover / restart re-registration: the register carries the
        # daemon's full resume state, or the peer object is a ghost this
        # scheduler restored from its snapshot (already RUNNING, stream
        # only now attached). Either way the peer holds landed bytes and
        # live parent sync streams — rebuild state and answer normal_task,
        # never demote it to origin.
        # Seeds stay on the reference path: a seed re-announcing a
        # complete store rides the need_back_source answer into the
        # conductor's announce-only fast path, which re-reports every
        # piece WITH digests — strictly more information than the bitset.
        resume = (msg or {}).get("resume")
        if (resume is not None and not peer.is_seed) \
                or peer.fsm.current in (PeerState.RUNNING,
                                        PeerState.BACK_TO_SOURCE):
            await self._handle_resume_register(task, peer, resume or {})
            return

        # Empty-content shortcut (reference registerEmptyTask).
        if task.content_length == 0:
            peer.fsm.event("register_empty")
            peer.fsm.event("download_succeeded")
            REGISTER_SCOPE_COUNT.labels("empty").inc()
            await peer.announce_stream.send(
                self._stamped({"type": "empty_task"}))
            return

        # Size-scope shortcuts (reference service_v1.go:885-996): once the
        # task has succeeded somewhere, tiny content is inlined in the
        # register response and single-piece tasks get one direct parent —
        # no announce-stream scheduling machinery for either.
        if not peer.is_seed and task.state == TaskState.SUCCEEDED:
            scope = task.size_scope()
            if (scope == SizeScope.TINY
                    and len(task.direct_piece) == task.content_length):
                if not self._verify_direct_piece(task, task.direct_piece):
                    # A newly-learned digest contradicts the cached inline
                    # content: drop the poisoned cache and fall through to
                    # normal registration (a fresh fetch re-verifies).
                    log.warning("cached tiny piece failed digest, dropped",
                                task=task.id[:16])
                    task.direct_piece = b""
                else:
                    peer.fsm.event("register_tiny")
                    peer.fsm.event("download_succeeded")
                    REGISTER_SCOPE_COUNT.labels("tiny").inc()
                    await peer.announce_stream.send(self._stamped({
                        "type": "tiny_task", "task": task.to_wire(),
                        "content": task.direct_piece}))
                    return
            if scope == SizeScope.SMALL and await self._register_small(task, peer):
                REGISTER_SCOPE_COUNT.labels("small").inc()
                return

        peer.fsm.event("register_normal")
        REGISTER_SCOPE_COUNT.labels("normal").inc()

        # Seed peers and solo first-comers go straight to origin; everyone
        # else gets parents (back-to-source dedup: ~1 origin fetch per task).
        if peer.is_seed:
            self._mark_task_running(task)
            self._to_back_source(task, peer, "seed peer registration")
            await peer.announce_stream.send(self._stamped(
                {"type": "need_back_source", "reason": "seed peer",
                 "task": task.to_wire()}))
            return

        seeding = False
        if task.fsm.current == TaskState.PENDING or not task.has_available_peer():
            seeding = await self._maybe_trigger_seed(task, peer)
            if not seeding:
                if peer.disable_back_source:
                    # The peer refuses origin; hold it in the schedule loop
                    # waiting for a parent to appear instead of demoting it.
                    await self._schedule_and_send(
                        task, peer,
                        patience=self.config.scheduling.no_source_patience)
                    return
                if task.can_back_to_source():
                    self._mark_task_running(task)
                    self._to_back_source(task, peer, "first peer, no seed")
                    await peer.announce_stream.send(self._stamped(
                        {"type": "need_back_source", "reason": "first peer",
                         "task": task.to_wire()}))
                    return
                # Out of back-source budget and nothing running: fail fast.
                self._fail_peer(peer)
                await peer.announce_stream.send(self._stamped(
                    {"type": "schedule_failed",
                     "reason": "no sources available"}))
                return

        # While a seed is actively fetching, hold the peer in the schedule
        # loop instead of demoting it to a redundant origin fetch.
        patience = 30.0 if seeding else 0.0
        await self._schedule_and_send(task, peer, patience=patience)

    async def _handle_resume_register(self, task: Task, peer: Peer,
                                      resume: dict) -> None:
        """Rebuild Task/Peer state from a resume-carrying re-registration
        (scheduler failover/restart — the server half of the conductor's
        announce recovery). The answer is ALWAYS normal_task: a peer that
        re-announced landed pieces is itself a parent candidate the pod
        needs, its remainder keeps flowing from the sync streams it never
        lost, and a back-source demotion here would re-fetch bytes the pod
        already holds. An empty parent list is fine — the conductor keeps
        its live parents, and membership-change pushes top it up as the
        rest of the pod re-registers."""
        task.update_lengths(
            resume.get("content_length", -1),
            resume.get("piece_size", 0),
            resume.get("total_piece_count", -1),
        )
        if resume.get("pod_broadcast"):
            peer.pod_broadcast = True
        piece_nums = resume.get("piece_nums")
        if not piece_nums and resume.get("piece_bitmap"):
            piece_nums = reportcodec.bitmap_to_nums(resume["piece_bitmap"])
        added = self._apply_resume_pieces(task, peer, piece_nums or [])
        # Fresh peers walk the normal register→download transitions; a
        # snapshot ghost is already RUNNING; a SUCCEEDED ghost whose
        # daemon says "still running" drops back to RUNNING — the daemon
        # is the authority on its own download state.
        for event in ("register_normal", "download"):
            if peer.fsm.can(event):
                peer.fsm.event(event)
        if peer.fsm.current not in (PeerState.RUNNING,
                                    PeerState.BACK_TO_SOURCE):
            peer.fsm.restore(PeerState.RUNNING)
        if task.fsm.current != TaskState.SUCCEEDED:
            # A resuming peer never demotes task-level success: SUCCEEDED
            # means the content is fully available somewhere, which one
            # peer's unfinished remainder does not contradict.
            self._mark_task_running(task)
        STATE_REBUILT_COUNT.labels("reregister").inc()
        if self.fleet is not None:
            self.fleet.note_register(reconnect=True)
        if added:
            # The re-announced pieces make this peer a usable parent NOW:
            # wake every schedule loop blocked on this task.
            task.notify_parents_changed()
        log.info("peer resume-registered", peer=peer.id[:24],
                 task=task.id[:16], pieces=len(peer.finished_pieces),
                 rebuilt=added)
        stream = peer.announce_stream
        if stream is None:
            return
        parents = self.scheduling.find_candidate_parents(peer)
        if parents:
            self.scheduling.reattach_peer(peer, parents)
        out = {"type": "normal_task", "task": task.to_wire(),
               "parents": [p.to_wire() for p in parents]}
        stripe = self._stripe_for(task, peer)
        peer.stripe = stripe
        if stripe is not None:
            out["stripe"] = stripe
            STRIPE_HANDOUT_COUNT.labels("striped").inc()
            if self.fleet is not None:
                self.fleet.note_stripe(task.id, peer.id, peer.host.id,
                                       reshuffle=False)
        await stream.send(self._stamped(out))
        if peer.host.tpu_slice:
            aio.spawn(self._push_stripe_updates(
                task, peer.host.tpu_slice, exclude=peer.id))

    async def _register_small(self, task: Task, peer: Peer) -> bool:
        """Single-piece shortcut (reference registerSmallTask :917): hand
        the registrant one SUCCEEDED parent plus piece 0's info so it can
        fetch the whole content with one upload-server GET. Returns False
        to fall through to normal registration."""
        piece = task.pieces.get(0)
        if piece is None:
            return False
        candidates = self.scheduling.find_candidate_parents(peer)
        parent = next((c for c in candidates
                       if c.state == PeerState.SUCCEEDED
                       and c.host.upload_port > 0), None)
        if parent is None:
            return False
        try:
            task.delete_peer_in_edges(peer.id)
            task.add_peer_edge(parent.id, peer.id)
            peer.fsm.event("register_small")
        except Exception:
            return False
        await peer.announce_stream.send(self._stamped({
            "type": "small_task", "task": task.to_wire(),
            "parent": parent.to_wire(), "piece": piece.to_wire()}))
        return True

    def _seed_active(self, task: Task) -> bool:
        # Via the task's seed index, not a full-DAG scan: this probe sits
        # inside every schedule loop iteration and seeds are usually zero.
        for pid in task.seed_peer_ids:
            p = task.load_peer(pid)
            if p is not None and p.is_seed and not p.is_done():
                return True
        return False

    async def _schedule_and_send(self, task: Task, peer: Peer, patience: float = 0.0) -> None:
        deadline = asyncio.get_running_loop().time() + patience
        seed_seen = False
        while True:
            active = self._seed_active(task)
            seed_seen = seed_seen or active
            # Hold while the (possibly still-registering) seed works; stop
            # holding once a seen seed is done/failed or patience runs out.
            hold = (asyncio.get_running_loop().time() < deadline
                    and (active or not seed_seen))
            result = await self.scheduling.schedule_candidate_parents(
                peer, allow_back_source=not hold and not peer.disable_back_source)
            if result.kind != ScheduleResult.FAILED or not hold:
                break
        stream = peer.announce_stream
        if stream is None:
            return
        if result.kind == ScheduleResult.CANDIDATES:
            for parent in result.parents:
                if not peer.host.tpu_slice or not parent.host.tpu_slice:
                    PARENT_PICK_COUNT.labels("unlabeled").inc()
                elif parent.host.tpu_slice == peer.host.tpu_slice:
                    PARENT_PICK_COUNT.labels("intra").inc()
                else:
                    PARENT_PICK_COUNT.labels("cross").inc()
            self.scheduling.reattach_peer(peer, result.parents)
            if peer.fsm.can("download"):
                peer.fsm.event("download")
            self._mark_task_running(task)
            msg = {
                "type": "normal_task",
                "task": task.to_wire(),
                "parents": [p.to_wire() for p in result.parents],
            }
            stripe = self._stripe_for(task, peer)
            peer.stripe = stripe
            if stripe is not None:
                msg["stripe"] = stripe
                STRIPE_HANDOUT_COUNT.labels("striped").inc()
                if self.fleet is not None:
                    self.fleet.note_stripe(task.id, peer.id, peer.host.id,
                                           reshuffle=False)
            await stream.send(self._stamped(msg))
            if peer.host.tpu_slice:
                # Membership may have just changed (this peer joined or
                # reshuffled): re-push differing stripe plans to the other
                # slice members so every host's wanted-set stays disjoint.
                aio.spawn(self._push_stripe_updates(
                    task, peer.host.tpu_slice, exclude=peer.id))
        elif result.kind == ScheduleResult.NEED_BACK_SOURCE:
            self._mark_task_running(task)
            self._to_back_source(task, peer, result.reason)
            await stream.send(self._stamped(
                {"type": "need_back_source", "reason": result.reason,
                 "task": task.to_wire()}))
        else:
            self._fail_peer(peer)
            if self.fleet is not None:
                self.fleet.note_schedule_failed(task.id, peer.id,
                                                peer.host.id, result.reason)
            await stream.send(self._stamped(
                {"type": "schedule_failed", "reason": result.reason}))

    # -- striped slice broadcast (scheduling/stripe.py) --------------------

    def _stripe_members(self, task: Task, slice_name: str) -> list[Peer]:
        """Alive broadcast peers of ``task`` on ``slice_name``. Succeeded
        peers stay members: they hold every piece, so keeping their rank
        costs nothing and spares a reshuffle per finisher; failed/left
        peers trigger the real reshuffle."""
        out = []
        for pid in task.slice_index.get(slice_name, ()):
            q = task.load_peer(pid)
            if q is None or q.fsm.current in (PeerState.FAILED,
                                              PeerState.LEAVE):
                continue
            out.append(q)
        auto = self.config.scheduling.stripe_min_slice_peers
        if 2 <= auto <= len(out):
            return out
        return [q for q in out if q.pod_broadcast]

    def _stripe_for(self, task: Task, peer: Peer) -> dict | None:
        """This peer's stripe plan, or None (unstriped fallback). Ranged
        tasks never stripe — the range already narrows the byte window,
        and mod-S piece ownership over a slice-relative grid would differ
        per range."""
        if not peer.host.tpu_slice or peer.range_header or peer.is_seed:
            return None
        members = self._stripe_members(task, peer.host.tpu_slice)
        if peer not in members:
            return None
        plan = stripe_mod.plan_stripe(
            [stripe_mod.member_key(q.host.tpu_worker_index, q.host.id, q.id)
             for q in members], peer.id)
        if plan is None:
            return None
        # Mates ride a dedicated channel, NOT the parent DAG: intra-slice
        # exchange is mutual (A serves B's stripe while B serves A's),
        # which the acyclic parent DAG cannot express — and ICI transfers
        # don't consume NIC upload slots, so DAG upload accounting would
        # mis-bill them anyway.
        plan["slice"] = peer.host.tpu_slice
        plan["mates"] = [q.to_wire() for q in members
                         if q.id != peer.id and q.host.upload_port > 0]
        return plan

    async def _push_stripe_updates(self, task: Task, slice_name: str,
                                   exclude: str = "") -> None:
        """Membership changed (join, death, reshuffle): push differing
        stripe plans to the slice's live members over their announce
        streams. Parents refresh too — a new mate should also enter the
        DCN candidate picture where the DAG allows it."""
        for pid in list(task.slice_index.get(slice_name, ())):
            if pid == exclude:
                continue
            q = task.load_peer(pid)
            if (q is None or q.announce_stream is None or q.is_done()
                    or q.fsm.current == PeerState.BACK_TO_SOURCE):
                continue
            stripe = self._stripe_for(task, q)
            if stripe == q.stripe:
                continue
            q.stripe = stripe
            msg = {"type": "normal_task", "task": task.to_wire(),
                   "parents": []}
            if stripe is not None:
                msg["stripe"] = stripe
            parents = self.scheduling.find_candidate_parents(q)
            if parents:
                self.scheduling.reattach_peer(q, parents)
                msg["parents"] = [p.to_wire() for p in parents]
            try:
                await q.announce_stream.send(msg)
                STRIPE_HANDOUT_COUNT.labels("reshuffle").inc()
                if self.fleet is not None:
                    self.fleet.note_stripe(task.id, q.id, q.host.id,
                                           reshuffle=True)
            except Exception:
                # A dying stream reaps through _on_stream_gone; the push
                # is best-effort by design.
                pass

    def _mark_task_running(self, task: Task) -> None:
        if task.fsm.can("download"):
            task.fsm.event("download")

    def _to_back_source(self, task: Task, peer: Peer, reason: str) -> None:
        if peer.fsm.can("download_back_to_source"):
            peer.fsm.event("download_back_to_source")
            task.back_to_source_peers.add(peer.id)
            if self.fleet is not None:
                self.fleet.note_back_source(task.id, peer.id, peer.host.id,
                                            reason)
            # A back-sourcing peer is a valid candidate parent from this
            # instant (the sync stream pushes pieces as they land) — wake
            # blocked schedule loops now, not at its first piece report.
            task.notify_parents_changed()
            log.info("peer going back-to-source", peer=peer.id[:24], reason=reason)

    def _fail_peer(self, peer: Peer) -> None:
        if peer.fsm.can("download_failed"):
            peer.fsm.event("download_failed")

    # -- seed triggering (reference downloadTaskBySeedPeer :1504) ----------

    async def _maybe_trigger_seed(self, task: Task, requesting_peer: Peer) -> bool:
        """Pick the least-loaded live seed host and trigger a seed download.
        Returns True if a seed is (already) seeding this task."""
        if not self.config.seed_peer_enabled:
            return False
        # Already seeding?
        if self._seed_active(task):
            return True
        seeds = [h for h in self.hosts.all() if h.is_seed() and h.port > 0]
        if not seeds:
            return False
        seeds.sort(key=lambda h: len(h.peer_ids))
        seed_host = seeds[0]
        ok = await self.seed_clients.trigger_download_task(
            seed_host,
            {
                "task_id": task.id,
                "url": task.url,
                "tag": task.tag,
                "application": task.application,
                "digest": task.digest,
                "filters": task.filtered_query_params,
                "header": task.header,
                "range": task.range_header,
                "tenant": task.tenant,
                "priority": requesting_peer.priority,
            },
        )
        if ok:
            self._mark_task_running(task)
            log.info("triggered seed download", task=task.id[:16], seed=seed_host.id)
        return ok

    # -- piece reports (reference :1291-1455) ------------------------------

    def _handle_download_started(self, msg: dict, task: Task, peer: Peer) -> None:
        task.update_lengths(
            msg.get("content_length", -1),
            msg.get("piece_size", 0),
            msg.get("total_piece_count", -1),
        )

    def _handle_piece_finished(self, msg: dict, task: Task, peer: Peer) -> None:
        REPORT_BATCH_COUNT.labels("dict").inc()
        INGEST_BATCH_PIECES.observe(1)
        self._apply_piece_finished(msg.get("piece") or {}, task, peer)

    def _apply_piece_finished(self, p: dict, task: Task, peer: Peer) -> None:
        num = p["piece_num"]
        if num in peer.finished_pieces:
            # Duplicate report: the client's flush restores a popped batch
            # on cancellation even when the send hit the wire (at-least-once
            # delivery), so application must be idempotent — a re-send must
            # not re-count the parent's upload or duplicate cost samples.
            # Checked on the raw dict BEFORE any PieceInfo construction:
            # this runs once per piece per peer across the whole pod.
            # Resume-rebuilt piece metadata has no digest (the bitset is
            # numbers-only); the idempotent re-report that follows a
            # re-registration is where the digest arrives — backfill it.
            info = task.pieces.get(num)
            if info is not None and not info.digest and p.get("digest"):
                info.digest = p["digest"]
            peer.touch()
            return
        first_piece = not peer.finished_pieces
        peer.add_finished_piece(num, p.get("download_cost_ms", 0))
        self.pod_flight.note_piece(task.id, peer.host.id,
                                   p.get("timings"),
                                   p.get("download_cost_ms", 0))
        if num not in task.pieces:
            # Construct piece metadata only for the first reporter; every
            # later peer re-reporting the same piece skips the allocation.
            task.store_piece(PieceInfo.from_wire(p))
        task.touch()
        if first_piece:
            # The peer just became a usable parent: wake schedule loops
            # instead of letting them poll out their retry interval.
            task.notify_parents_changed()
        parent_id = p.get("dst_peer_id", "")
        parent = self.peers.load(parent_id) if parent_id else None
        if parent is not None:
            parent.host.upload_count += 1
            parent.touch()
        if self.fleet is not None:
            cost = p.get("download_cost_ms", 0)
            col = fleetlib.C_BYTES_UNLABELED
            parent_host = None
            if parent is not None:
                parent_host = parent.host.id
                if peer.host.tpu_slice and parent.host.tpu_slice:
                    col = (fleetlib.C_BYTES_INTRA
                           if parent.host.tpu_slice == peer.host.tpu_slice
                           else fleetlib.C_BYTES_CROSS)
            self.fleet.note_piece(peer.host.id, col,
                                  p.get("range_size", 0), cost,
                                  parent_host, p.get("timings"))

    def _handle_pieces_finished(self, msg: dict, task: Task, peer: Peer) -> None:
        """Coalesced batch (clients flush reports on a short window);
        semantics identical to N piece_finished in order. Two wire forms
        arrive here: the negotiated packed batch (proto/reportcodec —
        decoded by the backend ladder in one call, applied in bulk) and
        the legacy per-piece dict list. Both land the exact same FSM
        state; the wire bench asserts it byte for byte."""
        packed = msg.get("packed")
        if packed is not None:
            try:
                batch = reportcodec.decode_packed(packed)
            except reportcodec.CodecError as e:
                # Malformed packed body: drop the batch, keep the stream.
                # Reports are delivered at-least-once (the conductor
                # restores unsent batches and recovery re-reports all
                # pieces), so dropping never loses state permanently.
                log.warning("malformed packed piece report dropped",
                            peer=peer.id[:24], error=str(e))
                return
            REPORT_BATCH_COUNT.labels("packed").inc()
            INGEST_BATCH_PIECES.observe(batch.n)
            self._apply_packed_batch(batch, task, peer)
            return
        pieces = msg.get("pieces") or []
        REPORT_BATCH_COUNT.labels("dict").inc()
        INGEST_BATCH_PIECES.observe(len(pieces))
        self._apply_piece_dicts(pieces, task, peer)

    def _apply_packed_batch(self, batch, task: Task, peer: Peer) -> None:
        """Bulk-apply a decoded packed batch: set-level dup check, one
        piece_costs extend, one PodAggregator feed, one fleet step per
        distinct parent — Python cost per BATCH, not per piece. Eligible
        only when every piece is new to this peer (the overwhelmingly
        common case — dup re-delivery happens on flush-restore races and
        recovery re-reports); anything else bridges to the dict walk,
        whose per-piece dup handling is the reference semantics."""
        nums = batch.nums
        nums_set = set(nums)
        if len(nums_set) != batch.n \
                or not peer.finished_pieces.isdisjoint(nums_set):
            self._apply_piece_dicts(batch.to_dicts(), task, peer)
            return
        was_empty = not peer.finished_pieces
        peer.finished_pieces.update(nums_set)
        costs = batch.costs
        if batch.min_cost > 0:
            peer.piece_costs.extend(costs)
        elif batch.cost_total:
            peer.piece_costs.extend(c for c in costs if c > 0)
        self.pod_flight.note_pieces(task.id, peer.host.id, batch.n,
                                    batch.phase_ms)
        # Subset probe first: in the steady state every piece is already
        # stored (the first reporter paid that), and <= on a keys view
        # costs one C-level membership sweep with no result-set build.
        missing = (() if nums_set <= task.pieces.keys()
                   else nums_set.difference(task.pieces.keys()))
        if missing:
            starts, sizes, peer_idx, peers = (
                batch.starts, batch.sizes, batch.peer_idx, batch.peers)
            for i, num in enumerate(nums):
                if num in missing:
                    task.store_piece(PieceInfo(
                        piece_num=num, range_start=starts[i],
                        range_size=sizes[i], digest=batch.digest(i),
                        download_cost_ms=costs[i],
                        dst_peer_id=peers[peer_idx[i]]))
        peer.touch()
        task.touch()
        if was_empty and peer.finished_pieces:
            task.notify_parents_changed()
        by_parent_host: dict[str, list] = {}
        my_slice = peer.host.tpu_slice
        for pidx, (k, cost_sum, nbytes) in enumerate(batch.parent_aggs):
            if not k:
                continue
            parent_id = batch.peers[pidx]
            parent = self.peers.load(parent_id) if parent_id else None
            host_key = ""
            col = fleetlib.C_BYTES_UNLABELED
            if parent is not None:
                parent.host.upload_count += k
                parent.touch()
                host_key = parent.host.id
                if my_slice and parent.host.tpu_slice:
                    col = (fleetlib.C_BYTES_INTRA
                           if parent.host.tpu_slice == my_slice
                           else fleetlib.C_BYTES_CROSS)
            entry = by_parent_host.get(host_key)
            if entry is None:
                by_parent_host[host_key] = [k, cost_sum, nbytes, col]
            else:
                entry[0] += k
                entry[1] += cost_sum
                entry[2] += nbytes
        if self.fleet is not None and batch.n:
            self.fleet.note_pieces(peer.host.id, batch.n, batch.cost_total,
                                   by_parent=by_parent_host)

    def _apply_piece_dicts(self, pieces: list, task: Task, peer: Peer) -> None:
        """The reference per-piece walk: the per-batch bookkeeping — task
        touch, parent-availability wakeup, parent upload accounting and
        registry lookups — runs once per batch (or once per distinct
        parent) instead of once per piece. This is the scheduler's
        hottest ingest path: a 1024-host fan-out delivers ~hosts x pieces
        of these."""
        was_empty = not peer.finished_pieces
        # Per-parent aggregation: one registry lookup, one upload-count
        # update, and ONE fleet serve-EWMA step per DISTINCT parent per
        # batch (not per piece) — this is the scheduler's hottest ingest
        # path and the observatory must ride it at batch granularity.
        parent_aggs: dict[str, list] = {}   # pid -> [count, cost_sum, bytes]
        landed = 0
        cost_total = 0
        for p in pieces:
            num = p["piece_num"]
            if num in peer.finished_pieces:
                # Idempotent re-delivery (see _apply_piece_finished) —
                # digest backfill for resume-rebuilt piece metadata.
                info = task.pieces.get(num)
                if info is not None and not info.digest and p.get("digest"):
                    info.digest = p["digest"]
                continue
            cost = p.get("download_cost_ms", 0)
            peer.add_finished_piece(num, cost)
            self.pod_flight.note_piece(task.id, peer.host.id,
                                       p.get("timings"), cost)
            if num not in task.pieces:
                task.store_piece(PieceInfo.from_wire(p))
            landed += 1
            cost_total += cost
            agg = parent_aggs.get(p.get("dst_peer_id", ""))
            if agg is None:
                agg = parent_aggs[p.get("dst_peer_id", "")] = [0, 0, 0]
            agg[0] += 1
            agg[1] += cost
            agg[2] += p.get("range_size", 0)
        peer.touch()
        task.touch()
        if was_empty and peer.finished_pieces:
            task.notify_parents_changed()
        by_parent_host: dict[str, list] = {}
        my_slice = peer.host.tpu_slice
        for parent_id, (k, cost_sum, nbytes) in parent_aggs.items():
            parent = self.peers.load(parent_id) if parent_id else None
            host_key = ""
            col = fleetlib.C_BYTES_UNLABELED
            if parent is not None:
                parent.host.upload_count += k
                parent.touch()
                host_key = parent.host.id
                if my_slice and parent.host.tpu_slice:
                    col = (fleetlib.C_BYTES_INTRA
                           if parent.host.tpu_slice == my_slice
                           else fleetlib.C_BYTES_CROSS)
            entry = by_parent_host.get(host_key)
            if entry is None:
                by_parent_host[host_key] = [k, cost_sum, nbytes, col]
            else:
                entry[0] += k
                entry[1] += cost_sum
                entry[2] += nbytes
        if self.fleet is not None and landed:
            self.fleet.note_pieces(peer.host.id, landed, cost_total,
                                   by_parent=by_parent_host)

    def _handle_piece_failed(self, msg: dict, task: Task, peer: Peer) -> None:
        parent_id = msg.get("parent_id", "")
        if parent_id:
            # Transient failures (429 throttle, size mismatch) only dent the
            # upload stats; permanent ones blocklist the parent for this peer.
            if not msg.get("temporary"):
                peer.block_parents.add(parent_id)
            parent = self.peers.load(parent_id)
            if parent is not None:
                parent.host.upload_count += 1
                parent.host.upload_failed_count += 1
                # Typed reason → pod-wide demotion: enough reason-weighted
                # strikes (corrupt bytes tip in one) quarantine the HOST,
                # filtering it from every peer's candidate set — not just
                # this reporter's blocklist.
                reason = msg.get("reason", "")
                if reason:
                    # Straggler attribution: the failure counts against
                    # the PARENT host that served (or failed to serve).
                    self.pod_flight.note_failure(task.id, parent.host.id,
                                                 reason)
                    if self.fleet is not None:
                        self.fleet.note_piece_failed(parent.host.id, reason)
                if reason and parent.host.note_served_bad(reason):
                    PARENT_DEMOTION_COUNT.labels(reason).inc()
                    self.pod_flight.note_quarantine(task.id, parent.host.id,
                                                    reason)
                    if self.fleet is not None:
                        self.fleet.note_quarantine(task.id, parent.host.id,
                                                   reason,
                                                   reporter=peer.id)
                    log.warning("parent host quarantined",
                                host=parent.host.id, reason=reason,
                                reporter=peer.id[:24])
                    task.notify_parents_changed()

    # -- reschedule (reference :1157 handleRescheduleRequest) --------------

    async def _handle_reschedule(self, msg: dict, task: Task, peer: Peer) -> None:
        peer.reschedule_count += 1
        for pid in msg.get("blocklist") or []:
            peer.block_parents.add(pid)
        task.delete_peer_in_edges(peer.id)
        # The dropped edges freed upload slots on the old parents.
        task.notify_parents_changed()
        patience = 30.0 if self._seed_active(task) else 0.0
        await self._schedule_and_send(task, peer, patience=patience)

    # -- completion (reference :1180/:1236) --------------------------------

    def _note_shipped_flight(self, msg: dict, task: Task,
                             peer: Peer) -> None:
        """Flight shipping ingest: the terminal announce message carries
        the daemon's bounded flight digest (pkg/flight.digest). The pod
        lens stores it (and its clock samples) for the merged timeline;
        the SLO engine books the completion SLIs."""
        fl = msg.get("flight")
        if not isinstance(fl, dict):
            return
        if self.pod_lens is not None:
            self.pod_lens.note_flight(task.id, peer.host.id, fl,
                                      peer_id=peer.id)
        if fl.get("state") != "failed" \
                and msg.get("type", "download_finished") \
                != "download_failed":
            makespan, ttfb, stall_frac = podlenslib.completion_stats(fl)
            if makespan > 0:
                if self.slo is not None:
                    self.slo.note_completion(peer.host.id, makespan,
                                             ttfb_s=ttfb,
                                             stall_frac=stall_frac)
                # Per-tenant burn book: same completion, attributed to the
                # task's tenant instead of the host.
                self.tenant_burn.note_completion(task.tenant, makespan,
                                                ttfb_s=ttfb,
                                                stall_frac=stall_frac)

    def _handle_download_finished(self, msg: dict, task: Task, peer: Peer) -> None:
        self._note_shipped_flight(msg, task, peer)
        if peer.state == PeerState.SUCCEEDED:
            return  # tiny-register peers are marked succeeded up front
        try:
            peer.fsm.event("download_succeeded")
        except TransitionError:
            log.warning("download_finished in bad state", state=peer.state)
            return
        task.update_lengths(
            msg.get("content_length", task.content_length),
            msg.get("piece_size", task.piece_size),
            msg.get("total_piece_count", task.total_piece_count),
        )
        # Detach from parents: the finished peer downloads nothing anymore, so
        # its parents' upload slots must come back (it stays in the DAG as a
        # parent candidate via its own out-edges).
        try:
            task.delete_peer_in_edges(peer.id)
        except Exception:
            pass
        if task.fsm.can("download_succeeded"):
            task.fsm.event("download_succeeded")
        # Finished peer = SUCCEEDED parent + freed upload slots on its old
        # parents: both change candidacy for waiting schedule loops.
        task.notify_parents_changed()
        log.info("peer finished", peer=peer.id[:24], task=task.id[:16])
        # Tiny tasks: pull the content off the finisher's upload server so
        # later registrants get it inlined (reference service_v1.go:1196-1210
        # fills Task.DirectPiece the same way).
        if (task.size_scope() == SizeScope.TINY and not task.direct_piece
                and peer.host.upload_port > 0):
            aio.spawn(self._fetch_direct_piece(task, peer))
        # Persistent-cache replica bookkeeping: a replication download that
        # finished becomes a durable replica row (reference service_v2.go
        # persistent cache peer state handling).
        if self.persistent.get_task(task.id) is not None:
            from dragonfly2_tpu.scheduler.resource.persistentcache import (
                STATE_SUCCEEDED,
            )

            self.persistent.upsert_peer(peer.id, task.id, peer.host.id,
                                        state=STATE_SUCCEEDED)
            self.persistent.upsert_host(
                peer.host.id, hostname=peer.host.hostname, ip=peer.host.ip,
                port=peer.host.port, upload_port=peer.host.upload_port)

    def _handle_download_failed(self, msg: dict, task: Task, peer: Peer) -> None:
        # The failure's flight digest still merges into the pod timeline
        # (a failed host is exactly the one an operator wants on the
        # picture); it books no SLO completion.
        self._note_shipped_flight(msg, task, peer)
        self._fail_peer(peer)
        # Task fails only when nothing is still making progress.
        still_running = any(
            not p.is_done() and p.id != peer.id for p in task.peers()
        )
        if not still_running and task.fsm.can("download_failed"):
            task.fsm.event("download_failed")

    def _on_stream_gone(self, task: Task, peer: Peer) -> None:
        """Stream dropped: a running peer that vanished must not stay a
        parent candidate (reference: peer leave → DAG edge deletion)."""
        if not peer.is_done():
            self._fail_peer(peer)
        if peer.fsm.current in (PeerState.FAILED, PeerState.LEAVE):
            try:
                task.delete_peer_out_edges(peer.id)
                task.delete_peer_in_edges(peer.id)
            except Exception:
                pass
            if peer.host.tpu_slice and (peer.pod_broadcast or peer.stripe):
                # Slice peer death: surviving members reshuffle to S-1
                # stripes (a lone survivor gets no stripe field and falls
                # back to the unstriped path).
                aio.spawn(self._push_stripe_updates(
                    task, peer.host.tpu_slice, exclude=peer.id))

    # ------------------------------------------------------------------ #
    # unary RPCs
    # ------------------------------------------------------------------ #

    async def announce_host(self, body: dict, ctx: RpcContext) -> dict:
        """Periodic host announcement (reference AnnounceHost :460)."""
        h = body or {}
        host = self.hosts.load_or_store(
            Host(
                h.get("id", "unknown"),
                hostname=h.get("hostname", ""),
                ip=h.get("ip", ""),
                port=h.get("port", 0),
                upload_port=h.get("upload_port", 0),
                host_type=HostType(h.get("type", 0)),
                idc=h.get("idc", ""),
                location=h.get("location", ""),
                tpu_slice=h.get("tpu_slice", ""),
                tpu_worker_index=h.get("tpu_worker_index", -1),
            )
        )
        host.port = h.get("port", host.port)
        host.upload_port = h.get("upload_port", host.upload_port)
        if self.fleet is not None:
            self.fleet.note_announce()
        # Clock alignment: the previous announce's round-trip sample
        # (daemon t0/t1 bracketing our echoed sched_wall) feeds the pod
        # lens's per-host offset estimate.
        clock = h.get("clock")
        if self.pod_lens is not None and isinstance(clock, dict):
            self.pod_lens.clock.add_sample(
                host.id, float(clock.get("t0", 0.0)),
                float(clock.get("t1", 0.0)), float(clock.get("echo", 0.0)))
        tel = h.get("telemetry") or {}
        for k, v in tel.items():
            if hasattr(host.telemetry, k):
                setattr(host.telemetry, k, v)
        host.touch()
        resp: dict = {"ok": True, "sched_wall": flightlib.anchored_wall()}
        # The subject host's fleet-wide standing rides back so the daemon
        # can embed it into post-mortem bundles.
        if self.fleet is not None:
            s = self.fleet.scorecards._hosts.get(host.id)
            if s is not None:
                resp["scorecard"] = {
                    "serve_ewma_ms": round(s.serve_ewma_ms, 2),
                    "serve_samples": s.serve_samples,
                    "down_ewma_ms": round(s.down_ewma_ms, 2),
                    "down_samples": s.down_samples,
                    "uploads": round(s.uploads, 1),
                    "failures": {r: round(v, 2)
                                 for r, v in s.failures.items()},
                    "straggler":
                        self.fleet.scorecards.is_straggler(host.id),
                    "zscore": self.fleet.scorecards.zscore(host.id),
                }
        return resp

    async def leave_host(self, body: dict, ctx: RpcContext) -> dict:
        """Host shutdown (reference LeaveHost :641): fail its peers, drop it."""
        host_id = (body or {}).get("id", "")
        host = self.hosts.load(host_id)
        if host is None:
            return {"ok": False}
        for pid in list(host.peer_ids):
            peer = self.peers.load(pid)
            if peer is not None:
                if peer.fsm.can("leave"):
                    peer.fsm.event("leave")
                self.peers.delete(pid)
        self.hosts.delete(host_id)
        # A departing host takes its persistent replicas with it; restore
        # the replica count elsewhere (reference: persistentcache host GC
        # + reschedule).
        affected = self.persistent.delete_peers_of_host(host_id)
        self.persistent.delete_host(host_id)
        for task_id in affected:
            aio.spawn(self._ensure_replicas(task_id))
        return {"ok": True}

    async def leave_peer(self, body: dict, ctx: RpcContext) -> dict:
        peer_id = (body or {}).get("id", "")
        peer = self.peers.load(peer_id)
        if peer is None:
            return {"ok": False}
        if peer.fsm.can("leave"):
            peer.fsm.event("leave")
        self.peers.delete(peer_id)
        return {"ok": True}

    # ------------------------------------------------------------------ #
    # persistent cache task family (reference service_v2.go:1580-1895)
    # ------------------------------------------------------------------ #

    async def upload_persistent_cache_task_started(self, body: dict,
                                                   ctx: RpcContext) -> dict:
        """An uploader begins importing a persistent cache task
        (reference :1726 UploadPersistentCacheTaskStarted)."""
        from dragonfly2_tpu.scheduler.resource import persistentcache as pc

        task_id = body.get("task_id", "")
        if not task_id:
            raise DfError(Code.BadRequest, "task_id required")
        h = body.get("host") or {}
        host_id = h.get("id") or h.get("hostname", "unknown")
        self.persistent.upsert_host(
            host_id, hostname=h.get("hostname", ""), ip=h.get("ip", ""),
            port=h.get("port", 0), upload_port=h.get("upload_port", 0))
        self.persistent.upsert_task(
            task_id, url=body.get("url", ""), tag=body.get("tag", ""),
            application=body.get("application", ""),
            piece_size=body.get("piece_size", 0),
            content_length=body.get("content_length", -1),
            total_piece_count=body.get("total_piece_count", -1),
            replica_count=max(1, int(body.get("replica_count", 1))),
            ttl=float(body.get("ttl", 0)),
            digest=body.get("digest", ""),
            state=pc.STATE_UPLOADING)
        self.persistent.upsert_peer(body.get("peer_id", ""), task_id, host_id,
                                    state=pc.STATE_UPLOADING)
        return {"ok": True}

    async def upload_persistent_cache_task_finished(self, body: dict,
                                                    ctx: RpcContext) -> dict:
        """Uploader finished; record the first replica and fan replication
        triggers until replica_count is met (reference :1791 Finished +
        the replica scheduling the Redis resource drives)."""
        from dragonfly2_tpu.scheduler.resource import persistentcache as pc

        task_id = body.get("task_id", "")
        task = self.persistent.get_task(task_id)
        if task is None:
            raise DfError(Code.PeerTaskNotFound, f"persistent task {task_id} unknown")
        self.persistent.upsert_task(
            task_id, state=pc.STATE_SUCCEEDED,
            content_length=body.get("content_length", task["content_length"]),
            piece_size=body.get("piece_size", task["piece_size"]),
            total_piece_count=body.get("total_piece_count",
                                       task["total_piece_count"]))
        h = body.get("host") or {}
        host_id = h.get("id") or h.get("hostname", "unknown")
        self.persistent.upsert_peer(body.get("peer_id", ""), task_id, host_id,
                                    state=pc.STATE_SUCCEEDED)
        # Replication runs in the background: N trigger RPCs (10s timeout
        # each, possibly against dead hosts) must not stall — or fail — the
        # uploader's Finished ack.
        aio.spawn(self._ensure_replicas(task_id))
        return {"ok": True}

    async def upload_persistent_cache_task_failed(self, body: dict,
                                                  ctx: RpcContext) -> dict:
        """Upload failed: drop the half-registered task (reference :1855) —
        but a failed RE-import of a task with live replicas must not erase
        the healthy replica bookkeeping."""
        from dragonfly2_tpu.scheduler.resource import persistentcache as pc

        task_id = body.get("task_id", "")
        if self.persistent.replica_count(task_id) > 0:
            self.persistent.upsert_task(task_id, state=pc.STATE_SUCCEEDED)
            self.persistent.delete_peer_if_not_succeeded(
                body.get("peer_id", ""))
        else:
            self.persistent.delete_task(task_id)
        return {"ok": True}

    async def stat_persistent_cache_task(self, body: dict,
                                         ctx: RpcContext) -> dict:
        wire = self.persistent.task_wire((body or {}).get("task_id", ""))
        if wire is None:
            raise DfError(Code.PeerTaskNotFound, "persistent task not found")
        return wire

    async def list_persistent_cache_tasks(self, body: dict,
                                          ctx: RpcContext) -> dict:
        return {"tasks": [self.persistent.task_wire(t["task_id"])
                          for t in self.persistent.list_tasks()]}

    async def delete_persistent_cache_task(self, body: dict,
                                           ctx: RpcContext) -> dict:
        """Remove the task everywhere: fan Peer.DeleteTask to every holder,
        then drop the rows (reference DeletePersistentCacheTask)."""
        task_id = (body or {}).get("task_id", "")
        deleted, failed = [], []
        for p in self.persistent.peers_of(task_id):
            host = self._persistent_host(p["host_id"])
            if host is None:
                continue
            ok = await self.seed_clients.delete_task(host, task_id)
            (deleted if ok else failed).append(p["host_id"])
        self.persistent.delete_task(task_id)
        self.tasks.delete(task_id)
        return {"ok": not failed, "deleted": deleted, "failed": failed}

    def _persistent_host(self, host_id: str):
        """Address a persistent host via the live resource if announced,
        else the durable snapshot (scheduler restarted since)."""
        host = self.hosts.load(host_id)
        if host is not None and host.port > 0:
            return host
        row = self.persistent.get_host(host_id)
        if row is None or not row["port"]:
            return None
        return Host(row["host_id"], hostname=row["hostname"], ip=row["ip"],
                    port=row["port"], upload_port=row["upload_port"])

    async def _ensure_replicas(self, task_id: str) -> int:
        """Fan download triggers to hosts without a replica until the
        desired count is met. Returns the number of triggers fired."""
        task = self.persistent.get_task(task_id)
        if task is None or task["state"] != "succeeded":
            return 0
        have = {p["host_id"] for p in self.persistent.peers_of(task_id)}
        want = task["replica_count"] - len(have)
        if want <= 0:
            return 0
        candidates = [h for h in self.hosts.all()
                      if h.port > 0 and h.id not in have]
        candidates.sort(key=lambda h: len(h.peer_ids))
        spec = {
            "task_id": task_id, "url": task["url"], "tag": task["tag"],
            "application": task["application"],
            "digest": task["digest"],     # end-to-end verify on replicas
            # Replicas PULL from peers; dfcache:// has no origin.
            "seed": False, "disable_back_source": True,
        }
        fired = 0
        for host in candidates[:want]:
            if await self.seed_clients.trigger_download_task(host, spec):
                fired += 1
                log.info("replication triggered", task=task_id[:16],
                         host=host.id)
        return fired

    async def _fetch_direct_piece(self, task: Task, peer: Peer) -> None:
        """Download a tiny task's full content (≤128 B) from the finished
        peer's upload server into ``task.direct_piece``."""
        import aiohttp

        url = (f"http://{peer.host.ip}:{peer.host.upload_port}"
               f"/download/{task.id[:3]}/{task.id}")
        try:
            async with aiohttp.ClientSession(
                    timeout=aiohttp.ClientTimeout(total=10)) as sess:
                async with sess.get(url, params={"peerId": peer.id,
                                                 "pieceNum": "0"}) as resp:
                    # 206: upload servers serve pieces as sendfile'd ranges.
                    if resp.status not in (200, 206):
                        return
                    data = await resp.read()
        except aiohttp.ClientError:
            return
        if len(data) != task.content_length:
            return
        # Verify against the reported piece-0 digest (or the whole-task
        # digest) before caching: a corrupt or malicious finisher must not
        # poison the inlined content for every later registrant.
        if not self._verify_direct_piece(task, data):
            log.warning("tiny direct piece digest mismatch, not cached",
                        task=task.id[:16], peer=peer.id[:16])
            return
        task.direct_piece = data
        log.info("tiny direct piece cached", task=task.id[:16],
                 size=len(data))

    @staticmethod
    def _verify_direct_piece(task: Task, data: bytes) -> bool:
        """True iff ``data`` matches every digest the task has on record
        (piece 0's digest and/or the task content digest)."""
        from dragonfly2_tpu.pkg import digest as dfdigest

        expectations = []
        piece = task.pieces.get(0)
        if piece is not None and piece.digest:
            expectations.append(piece.digest)
        if task.digest:
            expectations.append(task.digest)
        for value in expectations:
            try:
                expected = dfdigest.parse(value)
            except dfdigest.InvalidDigestError:
                return False
            if dfdigest.hash_bytes(expected.algorithm, data) != expected:
                return False
        # No digest on record: accept (nothing to verify against), matching
        # the reference's behavior for digest-less tasks.
        return True

    async def announce_task(self, body: dict, ctx: RpcContext) -> dict:
        """A daemon announces an already-complete local task (dfcache import,
        persisted stores after restart) so it becomes a parent candidate —
        reference service_v1.go:331 AnnounceTask."""
        host, task, peer = self._resolve(body)
        task.update_lengths(
            body.get("content_length", task.content_length),
            body.get("piece_size", task.piece_size),
            body.get("total_piece_count", task.total_piece_count),
        )
        # Same apply path as resume re-registration and snapshot restore:
        # the bitset also rebuilds task piece metadata, so all three
        # reconstruction routes converge on one Task state.
        self._apply_resume_pieces(task, peer, body.get("piece_nums") or [])
        for event in ("register_normal", "download", "download_succeeded"):
            if peer.fsm.can(event):
                peer.fsm.event(event)
        if task.fsm.can("download"):
            task.fsm.event("download")
        if task.fsm.can("download_succeeded"):
            task.fsm.event("download_succeeded")
        # A complete local task just became a parent candidate.
        task.notify_parents_changed()
        log.info("task announced", task=task.id[:16], host=host.id,
                 pieces=len(peer.finished_pieces))
        return {"ok": True}

    async def stat_task(self, body: dict, ctx: RpcContext) -> dict:
        task = self.tasks.load((body or {}).get("task_id", ""))
        if task is None:
            raise DfError(Code.PeerTaskNotFound, "task not found")
        return task.to_wire()

    async def stat_peer(self, body: dict, ctx: RpcContext) -> dict:
        peer = self.peers.load((body or {}).get("peer_id", ""))
        if peer is None:
            raise DfError(Code.SchedPeerNotFound, "peer not found")
        return peer.to_wire()

    async def list_hosts(self, body: dict, ctx: RpcContext) -> dict:
        return {"hosts": [h.to_wire() for h in self.hosts.all()]}

    # ------------------------------------------------------------------ #
    # pod lens: merged cross-host timeline
    # ------------------------------------------------------------------ #

    async def pod_timeline_report(self, task_id: str) -> "dict | None":
        """Assemble the merged cross-host timeline: the digests daemons
        shipped on completion, topped up with bounded on-demand
        ``Daemon.FlightReport`` pulls for task participants that never
        shipped one (crashed stream, still running, pre-digest daemon).
        Pulled digests merge but are not retained — the stream-shipped
        copy stays authoritative."""
        if self.pod_lens is None:
            return None
        extra: dict = {}
        task = self.tasks.load(task_id)
        budget = self.config.podlens.pull_missing
        if task is not None and budget > 0:
            shipped = self.pod_lens.shipped_hosts(task_id)
            missing: dict = {}
            for p in task.peers():
                h = p.host
                if h.id not in shipped and h.id not in missing and h.port > 0:
                    missing[h.id] = h
            for host_id, host in list(missing.items())[:budget]:
                d = await self.seed_clients.flight_digest(host, task_id)
                if isinstance(d, dict):
                    extra[host_id] = d
        return self.pod_lens.timeline(task_id, extra=extra)

    async def pod_timeline(self, body: dict, ctx: RpcContext) -> dict:
        """Unary surface for dfget --pod (Daemon.PodTimeline proxies
        here): the merged timeline plus its text waterfall — the SAME
        renderer /debug/pod/<task_id>/timeline?format=text uses."""
        task_id = (body or {}).get("task_id", "")
        report = await self.pod_timeline_report(task_id)
        if report is None:
            raise DfError(Code.PeerTaskNotFound,
                          f"no shipped flight digests for task {task_id}")
        return {"report": report,
                "text": podlenslib.render_timeline(report)}

    # ------------------------------------------------------------------ #
    # GC
    # ------------------------------------------------------------------ #

    def gc(self) -> dict:
        expired = self.persistent.expired_tasks()
        for task in expired:
            aio.spawn(self.delete_persistent_cache_task(
                {"task_id": task["task_id"]}, None))
        # Replication repair: a trigger whose download later failed never
        # created a peer row, so re-check every succeeded task each GC pass
        # and top up under-replicated ones (_ensure_replicas no-ops at
        # quota).
        expired_ids = {t["task_id"] for t in expired}
        for task in self.persistent.list_tasks(state="succeeded"):
            if (task["task_id"] not in expired_ids
                    and self.persistent.replica_count(task["task_id"])
                    < task["replica_count"]):
                aio.spawn(self._ensure_replicas(task["task_id"]))
        return {
            "peers": len(self.peers.gc()),
            "tasks": len(self.tasks.gc()),
            "hosts": len(self.hosts.gc()),
            "persistent_tasks": len(expired),
        }
