"""Scheduler: per-cluster control plane (reference: scheduler/)."""
