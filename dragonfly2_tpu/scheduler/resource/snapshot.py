"""Durable scheduler snapshot: bounded task/peer/host state for HA.

Reference: the reference scheduler keeps live resource state in Redis per
cluster (PAPER.md §1: scheduler → Redis), so a scheduler restart loses
nothing. Ours is in-process memory; this module is the restart story —
a bounded, periodically-flushed snapshot of the LIVE resource state
(hosts, tasks, non-terminal peers with their landed-piece bitsets) in the
same embedded-sqlite backend the persistent-cache rows use
(scheduler/config.py `persistent_cache_db`).

Contract (property-tested in tests/test_scheduler_ha.py): snapshot load
followed by partial resume re-registration must converge to the SAME
Task/Peer state as pure re-registration into an empty scheduler. That
shapes what is written:

  - peers only in RUNNING / SUCCEEDED — exactly the states the live
    re-registration paths can reproduce (a RUNNING conductor re-registers
    with resume state; a SUCCEEDED store re-announces via AnnounceTask).
    PENDING/RECEIVED are transient, BACK_TO_SOURCE conductors have no
    announce receiver to re-register with, FAILED/LEAVE are terminal and
    a re-register replaces them with a fresh peer anyway.
  - tasks only when ≥1 eligible peer holds them (a task no live peer can
    re-announce is a task a fresh scheduler would never learn about).
  - task piece metadata is NOT written: the restore rebuilds it from the
    peers' bitsets through the same apply path live resume uses, so both
    reconstructions are one code path.

Piece bitsets are stored as bitmap blobs (a 25k-piece task costs ~3 KiB
per peer, not a 150 KiB JSON array).
"""

from __future__ import annotations

import json
import sqlite3
import threading
import time

from dragonfly2_tpu.pkg import dflog

log = dflog.get("scheduler.snapshot")

_SCHEMA = """
CREATE TABLE IF NOT EXISTS snap_meta (
  k TEXT PRIMARY KEY, v TEXT
);
CREATE TABLE IF NOT EXISTS snap_hosts (
  host_id TEXT PRIMARY KEY,
  wire TEXT NOT NULL
);
CREATE TABLE IF NOT EXISTS snap_tasks (
  task_id TEXT PRIMARY KEY,
  url TEXT DEFAULT '',
  tag TEXT DEFAULT '',
  application TEXT DEFAULT '',
  digest TEXT DEFAULT '',
  range_header TEXT DEFAULT '',
  content_length INTEGER DEFAULT -1,
  piece_size INTEGER DEFAULT 0,
  total_piece_count INTEGER DEFAULT -1,
  state TEXT DEFAULT 'pending',
  updated_at REAL
);
CREATE TABLE IF NOT EXISTS snap_peers (
  peer_id TEXT PRIMARY KEY,
  task_id TEXT NOT NULL,
  host_id TEXT NOT NULL,
  state TEXT NOT NULL,
  pieces BLOB,
  pod_broadcast INTEGER DEFAULT 0,
  is_seed INTEGER DEFAULT 0,
  priority INTEGER DEFAULT 3,
  range_header TEXT DEFAULT ''
);
CREATE INDEX IF NOT EXISTS snap_peers_task ON snap_peers(task_id);
"""


def pieces_to_blob(nums) -> bytes:
    """Piece-number set → bitmap blob (bit n set ⇔ piece n landed)."""
    if not nums:
        return b""
    top = max(nums)
    buf = bytearray(top // 8 + 1)
    for n in nums:
        buf[n >> 3] |= 1 << (n & 7)
    return bytes(buf)


def blob_to_pieces(blob: bytes) -> list[int]:
    out: list[int] = []
    for i, byte in enumerate(blob or b""):
        if not byte:
            continue
        base = i << 3
        for bit in range(8):
            if byte & (1 << bit):
                out.append(base + bit)
    return out


class SnapshotStore:
    """sqlite-backed snapshot rows. Synchronous — each flush is one
    bounded transaction; row counts are capped by HAConfig."""

    def __init__(self, path: str = ":memory:"):
        self.path = path
        self._conn = sqlite3.connect(path, check_same_thread=False)
        self._conn.row_factory = sqlite3.Row
        self._conn.executescript(_SCHEMA)
        self._lock = threading.Lock()

    def close(self) -> None:
        self._conn.close()

    # -- save --------------------------------------------------------------

    def save(self, hosts, tasks, peers, *, max_tasks: int = 1024,
             max_peers: int = 65536) -> dict:
        """Replace the snapshot with the current live state. ``peers`` is
        every live peer; only RUNNING/SUCCEEDED ones are written (see
        module docstring), newest tasks win the ``max_tasks`` cap."""
        from dragonfly2_tpu.scheduler.resource.peer import PeerState

        eligible = [p for p in peers
                    if p.fsm.current in (PeerState.RUNNING,
                                         PeerState.SUCCEEDED)]
        by_task: dict[str, list] = {}
        for p in eligible:
            by_task.setdefault(p.task.id, []).append(p)
        kept_tasks = sorted(
            (t for t in tasks if t.id in by_task),
            key=lambda t: t.updated_at, reverse=True)[:max_tasks]
        kept_ids = {t.id for t in kept_tasks}
        peer_rows = []
        for tid in kept_ids:
            peer_rows.extend(by_task[tid])
        peer_rows = peer_rows[:max_peers]
        host_ids = {p.host.id for p in peer_rows}
        kept_hosts = [h for h in hosts if h.id in host_ids]

        with self._lock:
            cur = self._conn
            cur.execute("BEGIN")
            try:
                cur.execute("DELETE FROM snap_hosts")
                cur.execute("DELETE FROM snap_tasks")
                cur.execute("DELETE FROM snap_peers")
                cur.executemany(
                    "INSERT INTO snap_hosts (host_id, wire) VALUES (?,?)",
                    [(h.id, json.dumps(h.to_wire())) for h in kept_hosts])
                # Task state is NORMALIZED to what its written peers back:
                # "succeeded" only when a durable SUCCEEDED holder is in
                # the snapshot, else "running". A task FSM that says
                # SUCCEEDED because a long-gone peer once finished would
                # restore a claim no live holder backs — and it is what
                # keeps snapshot-load ∘ re-registration convergent with
                # pure re-registration (the property test's contract).
                cur.executemany(
                    "INSERT INTO snap_tasks (task_id, url, tag, application,"
                    " digest, range_header, content_length, piece_size,"
                    " total_piece_count, state, updated_at)"
                    " VALUES (?,?,?,?,?,?,?,?,?,?,?)",
                    [(t.id, t.url, t.tag, t.application, t.digest,
                      t.range_header, t.content_length, t.piece_size,
                      t.total_piece_count,
                      "succeeded" if any(
                          p.fsm.current == PeerState.SUCCEEDED
                          for p in by_task[t.id]) else "running",
                      t.updated_at)
                     for t in kept_tasks])
                cur.executemany(
                    "INSERT INTO snap_peers (peer_id, task_id, host_id,"
                    " state, pieces, pod_broadcast, is_seed, priority,"
                    " range_header) VALUES (?,?,?,?,?,?,?,?,?)",
                    [(p.id, p.task.id, p.host.id, p.fsm.current,
                      pieces_to_blob(p.finished_pieces),
                      int(p.pod_broadcast), int(p.is_seed), p.priority,
                      p.range_header)
                     for p in peer_rows])
                cur.execute(
                    "INSERT INTO snap_meta (k, v) VALUES ('saved_at', ?)"
                    " ON CONFLICT(k) DO UPDATE SET v=excluded.v",
                    (repr(time.time()),))
                cur.execute("COMMIT")
            except BaseException:
                cur.execute("ROLLBACK")
                raise
        return {"hosts": len(kept_hosts), "tasks": len(kept_tasks),
                "peers": len(peer_rows)}

    # -- load --------------------------------------------------------------

    def load(self) -> dict:
        """All snapshot rows, decoded; the service layer rebuilds the live
        objects (scheduler/service.restore_from_snapshot)."""
        with self._lock:
            hosts = [json.loads(r["wire"]) for r in self._conn.execute(
                "SELECT wire FROM snap_hosts").fetchall()]
            tasks = [dict(r) for r in self._conn.execute(
                "SELECT * FROM snap_tasks").fetchall()]
            peers = []
            for r in self._conn.execute("SELECT * FROM snap_peers"):
                row = dict(r)
                row["piece_nums"] = blob_to_pieces(row.pop("pieces"))
                peers.append(row)
            meta = self._conn.execute(
                "SELECT v FROM snap_meta WHERE k='saved_at'").fetchone()
        return {"hosts": hosts, "tasks": tasks, "peers": peers,
                "saved_at": float(meta["v"]) if meta else 0.0}
