"""Peer: one (task, host) download instance with lifecycle FSM.

Reference: scheduler/resource/standard/peer.go — states Pending →
Received{Empty,Tiny,Small,Normal} → Running → BackToSource →
Succeeded/Failed/Leave (:53-109, transitions :222-243), finished-piece set,
piece-cost window feeding bad-node detection, block-parent tracking.
"""

from __future__ import annotations

import time
from collections import deque

from dragonfly2_tpu.pkg.fsm import FSM, EventDesc
from dragonfly2_tpu.scheduler.resource.host import Host
from dragonfly2_tpu.scheduler.resource.task import Task


class PeerState:
    PENDING = "pending"
    RECEIVED_EMPTY = "received_empty"
    RECEIVED_TINY = "received_tiny"
    RECEIVED_SMALL = "received_small"
    RECEIVED_NORMAL = "received_normal"
    RUNNING = "running"
    BACK_TO_SOURCE = "back_to_source"
    SUCCEEDED = "succeeded"
    FAILED = "failed"
    LEAVE = "leave"


_RECEIVED = (PeerState.RECEIVED_EMPTY, PeerState.RECEIVED_TINY,
             PeerState.RECEIVED_SMALL, PeerState.RECEIVED_NORMAL)

_PEER_EVENTS = [
    EventDesc("register_empty", (PeerState.PENDING,), PeerState.RECEIVED_EMPTY),
    EventDesc("register_tiny", (PeerState.PENDING,), PeerState.RECEIVED_TINY),
    EventDesc("register_small", (PeerState.PENDING,), PeerState.RECEIVED_SMALL),
    EventDesc("register_normal", (PeerState.PENDING,), PeerState.RECEIVED_NORMAL),
    EventDesc("download", _RECEIVED, PeerState.RUNNING),
    EventDesc("download_back_to_source", _RECEIVED + (PeerState.RUNNING,),
              PeerState.BACK_TO_SOURCE),
    EventDesc("download_succeeded",
              (PeerState.RUNNING, PeerState.BACK_TO_SOURCE,
               PeerState.RECEIVED_EMPTY, PeerState.RECEIVED_TINY, PeerState.RECEIVED_SMALL),
              PeerState.SUCCEEDED),
    EventDesc("download_failed", (PeerState.PENDING,) + _RECEIVED +
              (PeerState.RUNNING, PeerState.BACK_TO_SOURCE), PeerState.FAILED),
    EventDesc("leave", (PeerState.PENDING,) + _RECEIVED +
              (PeerState.RUNNING, PeerState.BACK_TO_SOURCE,
               PeerState.SUCCEEDED, PeerState.FAILED), PeerState.LEAVE),
]

# Sliding window size for piece-cost stats (bad-node detection —
# reference evaluator.go keeps the last piece costs on the peer/host).
PIECE_COST_WINDOW = 64


class Peer:
    def __init__(self, peer_id: str, task: Task, host: Host, *,
                 is_seed: bool = False, priority: int = 3, range_header: str = "",
                 disable_back_source: bool = False):
        self.id = peer_id
        self.task = task
        self.host = host
        self.is_seed = is_seed
        self.priority = priority
        self.range_header = range_header
        # Peer refuses origin fetches (dfcache export, --disable-back-source;
        # reference v2 RegisterPeerRequest Download.disableBackToSource).
        self.disable_back_source = disable_back_source
        self.fsm = FSM(PeerState.PENDING, _PEER_EVENTS)
        self.finished_pieces: set[int] = set()
        self.piece_costs: deque[int] = deque(maxlen=PIECE_COST_WINDOW)
        self.block_parents: set[str] = set()      # parents this peer refuses
        self.reschedule_count = 0
        # Striped slice broadcast: registered with the pod_broadcast flag
        # (scheduling/stripe.py), and the last stripe plan pushed to it —
        # membership changes re-push only when the plan differs.
        self.pod_broadcast = False
        self.stripe: dict | None = None
        self.created_at = time.time()
        self.updated_at = time.time()
        # live stream handle for pushing schedule responses (service layer)
        self.announce_stream = None

    @property
    def state(self) -> str:
        return self.fsm.current

    def touch(self) -> None:
        self.updated_at = time.time()

    def add_finished_piece(self, piece_num: int, cost_ms: int = 0) -> None:
        self.finished_pieces.add(piece_num)
        if cost_ms > 0:
            self.piece_costs.append(cost_ms)
        self.touch()

    def finished_piece_count(self) -> int:
        return len(self.finished_pieces)

    def is_done(self) -> bool:
        return self.fsm.current in (PeerState.SUCCEEDED, PeerState.FAILED, PeerState.LEAVE)

    def to_wire(self) -> dict:
        finished_sorted = sorted(self.finished_pieces)
        return {
            "id": self.id,
            "task_id": self.task.id,
            "host": self.host.to_wire(),
            "state": self.state,
            "finished_pieces": finished_sorted,
            # Digests for the LOWEST-numbered advertised pieces (from
            # this task's piece reports): children pull lowest-first, so
            # this covers the window before the parent's sync snapshot
            # arrives — assignments verify at landing instead of pulling
            # digest-blind. Bounded (not the full map): the snapshot
            # delivers the rest moments later, and a 25k-piece task must
            # not re-serialize 25k digests per candidate per reschedule.
            "piece_digests": {
                n: self.task.pieces[n].digest
                for n in finished_sorted[:512]
                if n in self.task.pieces and self.task.pieces[n].digest},
            "is_seed": self.is_seed,
            "priority": self.priority,
        }


class PeerManager:
    """In-memory peer registry with TTL GC (reference peer_manager.go)."""

    def __init__(self, ttl: float = 24 * 3600.0):
        self._peers: dict[str, Peer] = {}
        self._ttl = ttl

    def load(self, peer_id: str) -> Peer | None:
        return self._peers.get(peer_id)

    def load_or_store(self, peer: Peer) -> Peer:
        existing = self._peers.get(peer.id)
        if existing is not None:
            return existing
        self._peers[peer.id] = peer
        peer.task.add_peer(peer)
        peer.host.peer_ids.add(peer.id)
        return peer

    def delete(self, peer_id: str) -> None:
        peer = self._peers.pop(peer_id, None)
        if peer is not None:
            peer.task.delete_peer(peer_id)
            peer.host.peer_ids.discard(peer_id)

    def all(self) -> list[Peer]:
        return list(self._peers.values())

    def gc(self) -> list[str]:
        """TTL + terminal-state sweep (reference peer_manager.go RunGC:
        leave-state peers go immediately, stale peers by TTL)."""
        now = time.time()
        dead = []
        for p in self._peers.values():
            if p.fsm.current == PeerState.LEAVE:
                dead.append(p.id)
            elif (now - p.updated_at) > self._ttl:
                dead.append(p.id)
        for pid in dead:
            self.delete(pid)
        return dead
