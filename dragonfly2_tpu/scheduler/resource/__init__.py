"""Scheduler resource model (reference: scheduler/resource/standard)."""

from dragonfly2_tpu.scheduler.resource.host import Host, HostManager
from dragonfly2_tpu.scheduler.resource.task import Task, TaskManager, TaskState
from dragonfly2_tpu.scheduler.resource.peer import Peer, PeerManager, PeerState

__all__ = [
    "Host",
    "HostManager",
    "Task",
    "TaskManager",
    "TaskState",
    "Peer",
    "PeerManager",
    "PeerState",
]
