"""Persistent cache resource: durable task/peer/host records with replica
management.

Reference: scheduler/resource/persistentcache/ — Redis-backed Task/Peer/Host
managers for persistent cache tasks (replica-managed datasets;
host_manager.go:68, redis key layout pkg/redis/redis.go:91-141) driven by
the v2 RPC family (service_v2.go:1580-1895). There is no Redis in this
stack; durability comes from an embedded sqlite file in the scheduler's
work dir — same contract: records survive scheduler restarts, unlike the
in-memory standard resource.

A persistent cache task is a dfcache entry (task id of ``dfcache://{id}``)
whose desired ``replica_count`` the scheduler enforces: when the uploader
finishes, the scheduler fans download triggers to other hosts until enough
persistent replicas exist, and re-checks when hosts leave.
"""

from __future__ import annotations

import json
import sqlite3
import threading
import time

from dragonfly2_tpu.pkg import dflog

log = dflog.get("scheduler.persistentcache")

STATE_PENDING = "pending"
STATE_UPLOADING = "uploading"
STATE_SUCCEEDED = "succeeded"
STATE_FAILED = "failed"

_SCHEMA = """
CREATE TABLE IF NOT EXISTS pc_tasks (
  task_id TEXT PRIMARY KEY,
  url TEXT DEFAULT '',
  tag TEXT DEFAULT '',
  application TEXT DEFAULT '',
  piece_size INTEGER DEFAULT 0,
  content_length INTEGER DEFAULT -1,
  total_piece_count INTEGER DEFAULT -1,
  replica_count INTEGER DEFAULT 1,
  ttl REAL DEFAULT 0,
  digest TEXT DEFAULT '',
  state TEXT DEFAULT 'pending',
  created_at REAL, updated_at REAL
);
CREATE TABLE IF NOT EXISTS pc_peers (
  peer_id TEXT PRIMARY KEY,
  task_id TEXT NOT NULL,
  host_id TEXT NOT NULL,
  persistent INTEGER DEFAULT 1,
  state TEXT DEFAULT 'pending',
  created_at REAL, updated_at REAL
);
CREATE TABLE IF NOT EXISTS pc_hosts (
  host_id TEXT PRIMARY KEY,
  hostname TEXT DEFAULT '',
  ip TEXT DEFAULT '',
  port INTEGER DEFAULT 0,
  upload_port INTEGER DEFAULT 0,
  info JSON DEFAULT '{}',
  updated_at REAL
);
CREATE INDEX IF NOT EXISTS pc_peers_task ON pc_peers(task_id);
"""


class PersistentCacheResource:
    """sqlite-backed persistent cache state. All methods are synchronous —
    row counts are small and sqlite is local."""

    def __init__(self, path: str = ":memory:"):
        self._conn = sqlite3.connect(path, check_same_thread=False)
        self._conn.row_factory = sqlite3.Row
        self._conn.executescript(_SCHEMA)
        self._lock = threading.Lock()

    def close(self) -> None:
        self._conn.close()

    def _exec(self, sql: str, args=()) -> sqlite3.Cursor:
        with self._lock:
            cur = self._conn.execute(sql, args)
            self._conn.commit()
            return cur

    # -- tasks -------------------------------------------------------------

    def upsert_task(self, task_id: str, **fields) -> dict:
        now = time.time()
        existing = self.get_task(task_id)
        if existing is None:
            cols = {"task_id": task_id, "created_at": now, "updated_at": now,
                    **fields}
            names = ",".join(cols)
            self._exec(
                f"INSERT INTO pc_tasks ({names}) VALUES "
                f"({','.join('?' * len(cols))})", list(cols.values()))
        elif fields:
            sets = ",".join(f"{k}=?" for k in fields)
            self._exec(f"UPDATE pc_tasks SET {sets}, updated_at=? "
                       f"WHERE task_id=?",
                       [*fields.values(), now, task_id])
        return self.get_task(task_id)

    def get_task(self, task_id: str) -> dict | None:
        row = self._exec("SELECT * FROM pc_tasks WHERE task_id=?",
                         (task_id,)).fetchone()
        return dict(row) if row else None

    def list_tasks(self, state: str = "") -> list[dict]:
        if state:
            rows = self._exec("SELECT * FROM pc_tasks WHERE state=?",
                              (state,)).fetchall()
        else:
            rows = self._exec("SELECT * FROM pc_tasks").fetchall()
        return [dict(r) for r in rows]

    def delete_task(self, task_id: str) -> None:
        self._exec("DELETE FROM pc_peers WHERE task_id=?", (task_id,))
        self._exec("DELETE FROM pc_tasks WHERE task_id=?", (task_id,))

    def expired_tasks(self, now: float | None = None) -> list[dict]:
        now = now if now is not None else time.time()
        rows = self._exec(
            "SELECT * FROM pc_tasks WHERE ttl > 0 AND created_at + ttl < ?",
            (now,)).fetchall()
        return [dict(r) for r in rows]

    # -- peers (replicas) --------------------------------------------------

    def upsert_peer(self, peer_id: str, task_id: str, host_id: str, *,
                    persistent: bool = True,
                    state: str = STATE_PENDING) -> None:
        now = time.time()
        self._exec(
            "INSERT INTO pc_peers (peer_id, task_id, host_id, persistent,"
            " state, created_at, updated_at) VALUES (?,?,?,?,?,?,?) "
            "ON CONFLICT(peer_id) DO UPDATE SET state=excluded.state,"
            " persistent=excluded.persistent, updated_at=excluded.updated_at",
            (peer_id, task_id, host_id, int(persistent), state, now, now))

    def peers_of(self, task_id: str, state: str = "") -> list[dict]:
        if state:
            rows = self._exec(
                "SELECT * FROM pc_peers WHERE task_id=? AND state=?",
                (task_id, state)).fetchall()
        else:
            rows = self._exec("SELECT * FROM pc_peers WHERE task_id=?",
                              (task_id,)).fetchall()
        return [dict(r) for r in rows]

    def delete_peer_if_not_succeeded(self, peer_id: str) -> None:
        """Drop a failed uploader's row without touching healthy replicas."""
        self._exec("DELETE FROM pc_peers WHERE peer_id=? AND state != ?",
                   (peer_id, STATE_SUCCEEDED))

    def delete_peers_of_host(self, host_id: str) -> list[str]:
        """Remove a departing host's replicas; returns affected task ids."""
        rows = self._exec("SELECT DISTINCT task_id FROM pc_peers WHERE host_id=?",
                          (host_id,)).fetchall()
        self._exec("DELETE FROM pc_peers WHERE host_id=?", (host_id,))
        return [r["task_id"] for r in rows]

    def replica_count(self, task_id: str) -> int:
        row = self._exec(
            "SELECT COUNT(*) AS n FROM pc_peers WHERE task_id=? AND state=?",
            (task_id, STATE_SUCCEEDED)).fetchone()
        return row["n"]

    # -- hosts -------------------------------------------------------------

    def upsert_host(self, host_id: str, *, hostname: str = "", ip: str = "",
                    port: int = 0, upload_port: int = 0,
                    info: dict | None = None) -> None:
        self._exec(
            "INSERT INTO pc_hosts (host_id, hostname, ip, port, upload_port,"
            " info, updated_at) VALUES (?,?,?,?,?,?,?) "
            "ON CONFLICT(host_id) DO UPDATE SET hostname=excluded.hostname,"
            " ip=excluded.ip, port=excluded.port,"
            " upload_port=excluded.upload_port, info=excluded.info,"
            " updated_at=excluded.updated_at",
            (host_id, hostname, ip, port, upload_port,
             json.dumps(info or {}), time.time()))

    def get_host(self, host_id: str) -> dict | None:
        row = self._exec("SELECT * FROM pc_hosts WHERE host_id=?",
                         (host_id,)).fetchone()
        return dict(row) if row else None

    def list_hosts(self) -> list[dict]:
        return [dict(r) for r in self._exec("SELECT * FROM pc_hosts").fetchall()]

    def delete_host(self, host_id: str) -> None:
        self._exec("DELETE FROM pc_hosts WHERE host_id=?", (host_id,))

    # -- wire --------------------------------------------------------------

    def task_wire(self, task_id: str) -> dict | None:
        task = self.get_task(task_id)
        if task is None:
            return None
        peers = self.peers_of(task_id)
        return {
            **task,
            "current_replicas": self.replica_count(task_id),
            "peers": [{"peer_id": p["peer_id"], "host_id": p["host_id"],
                       "state": p["state"], "persistent": bool(p["persistent"])}
                      for p in peers],
        }
