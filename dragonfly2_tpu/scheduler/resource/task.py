"""Task: one piece of content being distributed; holds the peer DAG.

Reference: scheduler/resource/standard/task.go — FSM Pending/Running/
Succeeded/Failed/Leave (:58-84, transitions :197-219), the peer DAG
(:154-155, edge maintenance :312-353), SizeScope (:468-490), back-to-source
peer accounting.
"""

from __future__ import annotations

import asyncio
import time

from dragonfly2_tpu.pkg.dag import DAG
from dragonfly2_tpu.pkg.fsm import FSM, EventDesc
from dragonfly2_tpu.pkg.piece import PieceInfo, SizeScope


class TaskState:
    PENDING = "pending"
    RUNNING = "running"
    SUCCEEDED = "succeeded"
    FAILED = "failed"
    LEAVE = "leave"


_TASK_EVENTS = [
    EventDesc("download", (TaskState.PENDING, TaskState.FAILED, TaskState.SUCCEEDED), TaskState.RUNNING),
    EventDesc("download_succeeded", (TaskState.RUNNING, TaskState.FAILED), TaskState.SUCCEEDED),
    EventDesc("download_failed", (TaskState.RUNNING,), TaskState.FAILED),
    EventDesc("leave", (TaskState.PENDING, TaskState.RUNNING, TaskState.SUCCEEDED, TaskState.FAILED),
              TaskState.LEAVE),
]


class Task:
    def __init__(self, task_id: str, url: str = "", *, tag: str = "", application: str = "",
                 digest: str = "", filtered_query_params: list[str] | None = None,
                 header: dict | None = None, back_to_source_limit: int = 200,
                 range_header: str = "", tenant: str = ""):
        self.id = task_id
        self.url = url
        self.tag = tag
        self.application = application
        # QoS attribution tag (dragonfly2_tpu/qos): who this content is
        # being pulled FOR. Not part of task identity — two tenants
        # pulling the same content share the task; the first registrant's
        # tenant wins attribution (later ones backfill an empty tag).
        self.tenant = tenant
        self.digest = digest
        self.filtered_query_params = filtered_query_params or []
        self.header = header or {}
        # Ranged task (the id encodes it): a triggered seed must fetch
        # exactly this slice, or its store would hold the whole object
        # under the ranged id.
        self.range_header = range_header
        self.content_length = -1
        self.piece_size = 0
        self.total_piece_count = -1
        self.pieces: dict[int, PieceInfo] = {}   # known piece metadata
        # Tiny-task content (≤128 B), inlined in register responses once a
        # finisher uploads it (reference task.go:133 DirectPiece).
        self.direct_piece: bytes = b""
        self.fsm = FSM(TaskState.PENDING, _TASK_EVENTS)
        self.dag: DAG = DAG()                    # peer tree: parent → child
        self.back_to_source_limit = back_to_source_limit
        self.back_to_source_peers: set[str] = set()
        self.created_at = time.time()
        self.updated_at = time.time()
        # Parent-availability wakeup: schedulers waiting for a usable
        # parent block on this instead of poll-sleeping (reference polls
        # at RetryInterval=500ms — scheduler/config/constants.go:68-70;
        # event-driven cuts first-piece latency to the actual arrival).
        self._parents_event = asyncio.Event()
        # ICI locality index: slice name → peer ids on that slice, so
        # candidate sampling can prefer same-slice parents instead of
        # relying on a random DAG sample to contain one (at 16 hosts per
        # slice in a 256-host pod the random base rate is ~6%).
        self.slice_index: dict[str, set[str]] = {}
        # Seed membership index (is_seed is fixed at peer construction):
        # the scheduler's seed-active probe runs inside every schedule
        # loop iteration and must not scan the whole peer DAG for the
        # usually-zero seeds.
        self.seed_peer_ids: set[str] = set()

    def notify_parents_changed(self) -> None:
        """Wake every scheduler retry-loop waiting on this task: a peer
        gained its first piece, finished, or released upload slots."""
        event, self._parents_event = self._parents_event, asyncio.Event()
        event.set()

    async def wait_parents_changed(self, timeout: float) -> None:
        """Wait until parent availability may have changed, at most
        ``timeout`` seconds (the poll interval becomes an upper bound)."""
        event = self._parents_event
        try:
            await asyncio.wait_for(event.wait(), timeout)
        except (asyncio.TimeoutError, TimeoutError):
            pass

    # -- state -------------------------------------------------------------

    @property
    def state(self) -> str:
        return self.fsm.current

    def touch(self) -> None:
        self.updated_at = time.time()

    def size_scope(self) -> str:
        return SizeScope.of(self.content_length, self.piece_size, self.total_piece_count)

    def has_available_peer(self, blocklist: set[str] | None = None) -> bool:
        """Any finished/running peer that could serve pieces
        (reference task.go HasAvailablePeer)."""
        blocklist = blocklist or set()
        from dragonfly2_tpu.scheduler.resource.peer import PeerState

        serving = (PeerState.RUNNING, PeerState.BACK_TO_SOURCE,
                   PeerState.SUCCEEDED)

        def _available(peer) -> bool:
            if peer.id in blocklist:
                return False
            if peer.fsm.current in serving and peer.finished_pieces:
                return True
            return peer.fsm.current == PeerState.SUCCEEDED

        # Early-exit DAG probe: this runs on every register, and the
        # oldest (first-inserted) peers are exactly the finished ones,
        # so the steady-state cost is O(1), not O(peers).
        return self.dag.find_value(_available) is not None

    def can_back_to_source(self) -> bool:
        """Bounded number of peers may hit origin
        (reference task.go CanBackToSource)."""
        return len(self.back_to_source_peers) < self.back_to_source_limit

    # -- peer DAG (reference task.go:154,312-353) --------------------------

    def add_peer(self, peer) -> None:
        if not self.dag.has_vertex(peer.id):
            self.dag.add_vertex(peer.id, peer)
            if peer.host.tpu_slice:
                self.slice_index.setdefault(
                    peer.host.tpu_slice, set()).add(peer.id)
            if peer.is_seed:
                self.seed_peer_ids.add(peer.id)

    def _release_upload_slots(self, peer_id: str, *, parents: bool, children: bool) -> None:
        """Upload-concurrency accounting: each parent→child edge holds one
        upload slot on the parent's host (reference: ConcurrentUploadLimit;
        evaluator free-upload term + scheduling filter read this)."""
        if not self.dag.has_vertex(peer_id):
            return
        v = self.dag.get_vertex(peer_id)
        if parents:
            for p in v.parents.values():
                host = p.value.host
                host.concurrent_upload_count = max(0, host.concurrent_upload_count - 1)
        if children:
            host = v.value.host
            host.concurrent_upload_count = max(
                0, host.concurrent_upload_count - v.out_degree())

    def delete_peer(self, peer_id: str) -> None:
        self._release_upload_slots(peer_id, parents=True, children=True)
        peer = self.load_peer(peer_id)
        if peer is not None and peer.host.tpu_slice:
            members = self.slice_index.get(peer.host.tpu_slice)
            if members is not None:
                members.discard(peer_id)
        self.seed_peer_ids.discard(peer_id)
        self.dag.delete_vertex(peer_id)

    def load_peer(self, peer_id: str):
        if not self.dag.has_vertex(peer_id):
            return None
        return self.dag.get_vertex(peer_id).value

    def peers(self) -> list:
        return list(self.dag.values())

    def peer_count(self) -> int:
        return self.dag.vertex_count()

    def add_peer_edge(self, parent_id: str, child_id: str) -> None:
        self.dag.add_edge(parent_id, child_id)
        self.dag.get_vertex(parent_id).value.host.concurrent_upload_count += 1

    def delete_peer_in_edges(self, peer_id: str) -> None:
        """Detach a peer from its parents before rescheduling
        (reference task.go DeletePeerInEdges)."""
        self._release_upload_slots(peer_id, parents=True, children=False)
        self.dag.delete_vertex_in_edges(peer_id)

    def delete_peer_out_edges(self, peer_id: str) -> None:
        self._release_upload_slots(peer_id, parents=False, children=True)
        self.dag.delete_vertex_out_edges(peer_id)

    def can_add_peer_edge(self, parent_id: str, child_id: str) -> bool:
        return self.dag.can_add_edge(parent_id, child_id)

    def peer_out_degree(self, peer_id: str) -> int:
        return self.dag.get_vertex(peer_id).out_degree()

    # -- piece metadata ----------------------------------------------------

    def store_piece(self, piece: PieceInfo) -> None:
        self.pieces.setdefault(piece.piece_num, piece)

    def update_lengths(self, content_length: int, piece_size: int, total_piece_count: int) -> None:
        if content_length >= 0:
            self.content_length = content_length
        if piece_size > 0:
            self.piece_size = piece_size
        if total_piece_count >= 0:
            self.total_piece_count = total_piece_count
        self.touch()

    def to_wire(self) -> dict:
        return {
            "id": self.id,
            "url": self.url,
            "tag": self.tag,
            "application": self.application,
            "tenant": self.tenant,
            "state": self.state,
            "content_length": self.content_length,
            "piece_size": self.piece_size,
            "total_piece_count": self.total_piece_count,
            "peer_count": self.peer_count(),
            "size_scope": self.size_scope(),
        }


class TaskManager:
    """In-memory task registry with TTL GC (reference task_manager.go:134)."""

    def __init__(self, ttl: float = 24 * 3600.0):
        self._tasks: dict[str, Task] = {}
        self._ttl = ttl

    def load(self, task_id: str) -> Task | None:
        return self._tasks.get(task_id)

    def load_or_store(self, task: Task) -> Task:
        existing = self._tasks.get(task.id)
        if existing is not None:
            existing.touch()
            return existing
        self._tasks[task.id] = task
        return task

    def delete(self, task_id: str) -> None:
        self._tasks.pop(task_id, None)

    def all(self) -> list[Task]:
        return list(self._tasks.values())

    def gc(self) -> list[str]:
        now = time.time()
        dead = [t.id for t in self._tasks.values()
                if t.peer_count() == 0 and (now - t.updated_at) > self._ttl]
        for tid in dead:
            del self._tasks[tid]
        return dead
