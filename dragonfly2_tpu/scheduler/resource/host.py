"""Host: one daemon instance and its telemetry.

Reference: scheduler/resource/standard/host.go:140-360 — identity, network
location (IDC / '|'-separated location path), upload concurrency accounting,
CPU/memory/network telemetry, TTL for GC. TPU extension: slice/worker
coordinates used by the topology-aware evaluator (ICI vs DCN distance).
"""

from __future__ import annotations

import time
from dataclasses import dataclass, field

from dragonfly2_tpu.pkg.quarantine import DecayingPenalty, penalize_entry
from dragonfly2_tpu.pkg.types import HostType
from dragonfly2_tpu.scheduler.config import (
    PEER_CONCURRENT_UPLOAD_LIMIT,
    SEED_PEER_CONCURRENT_UPLOAD_LIMIT,
)


@dataclass
class HostTelemetry:
    """Announced host stats (reference host.go CPU/Memory/Network/Disk/Build;
    filled by the daemon announcer from psutil)."""

    cpu_percent: float = 0.0
    mem_percent: float = 0.0
    disk_free: int = 0
    net_rx_rate: int = 0
    net_tx_rate: int = 0
    os: str = ""
    platform: str = ""
    version: str = ""


class Host:
    def __init__(
        self,
        host_id: str,
        *,
        hostname: str = "",
        ip: str = "",
        port: int = 0,            # drpc peer port
        upload_port: int = 0,     # HTTP piece upload port
        host_type: HostType = HostType.NORMAL,
        idc: str = "",
        location: str = "",
        tpu_slice: str = "",
        tpu_worker_index: int = -1,
        concurrent_upload_limit: int = 0,
    ):
        self.id = host_id
        self.hostname = hostname or host_id
        self.ip = ip
        self.port = port
        self.upload_port = upload_port
        self.type = host_type
        self.idc = idc
        self.location = location
        self.tpu_slice = tpu_slice
        self.tpu_worker_index = tpu_worker_index
        if concurrent_upload_limit <= 0:
            concurrent_upload_limit = (
                SEED_PEER_CONCURRENT_UPLOAD_LIMIT if host_type.is_seed()
                else PEER_CONCURRENT_UPLOAD_LIMIT
            )
        self.concurrent_upload_limit = concurrent_upload_limit
        self.concurrent_upload_count = 0
        self.upload_count = 0
        self.upload_failed_count = 0
        self.telemetry = HostTelemetry()
        self.created_at = time.time()
        self.updated_at = time.time()
        # peer ids on this host (peer GC on LeaveHost)
        self.peer_ids: set[str] = set()
        # Bad-serve quarantine (pkg/quarantine discipline, same constants
        # as the daemon side): children's typed piece_failed reports add
        # reason-weighted, half-life-decaying penalty; while quarantined
        # the host is filtered from EVERY peer's candidate set — one
        # child's crc mismatch protects the whole pod.
        self._penalty = DecayingPenalty()

    # -- bad-serve quarantine ----------------------------------------------

    def note_served_bad(self, reason: str) -> bool:
        """Record a typed serving failure. Returns True when the host just
        ENTERED quarantine (callers report that edge)."""
        return penalize_entry(self._penalty, reason, time.monotonic())

    def quarantined(self) -> bool:
        return time.monotonic() < self._penalty.quarantined_until

    # -- upload accounting (evaluator free-upload term) --------------------

    def free_upload_count(self) -> int:
        return max(0, self.concurrent_upload_limit - self.concurrent_upload_count)

    def upload_success_rate(self) -> float:
        if self.upload_count == 0:
            return 1.0 if self.type.is_seed() else 0.6  # optimistic prior
        return 1.0 - (self.upload_failed_count / self.upload_count)

    def touch(self) -> None:
        self.updated_at = time.time()

    def is_seed(self) -> bool:
        return self.type.is_seed()

    def to_wire(self) -> dict:
        return {
            "id": self.id,
            "hostname": self.hostname,
            "ip": self.ip,
            "port": self.port,
            "upload_port": self.upload_port,
            "type": int(self.type),
            "idc": self.idc,
            "location": self.location,
            "tpu_slice": self.tpu_slice,
            "tpu_worker_index": self.tpu_worker_index,
        }


class HostManager:
    """In-memory host registry with TTL GC (reference host_manager.go)."""

    def __init__(self, ttl: float = 3600.0):
        self._hosts: dict[str, Host] = {}
        self._ttl = ttl

    def load(self, host_id: str) -> Host | None:
        return self._hosts.get(host_id)

    def store(self, host: Host) -> Host:
        self._hosts[host.id] = host
        return host

    def load_or_store(self, host: Host) -> Host:
        existing = self._hosts.get(host.id)
        if existing is not None:
            existing.touch()
            return existing
        return self.store(host)

    def delete(self, host_id: str) -> None:
        self._hosts.pop(host_id, None)

    def all(self) -> list[Host]:
        return list(self._hosts.values())

    def counts(self) -> dict:
        """Fleet-gauge summary (pkg/fleet sampler): hosts by state. One
        O(hosts) scan, called at bucket-rotation cadence, not per event."""
        total = seed = quarantined = 0
        for h in self._hosts.values():
            total += 1
            if h.is_seed():
                seed += 1
            if h.quarantined():
                quarantined += 1
        return {"total": total, "seed": seed, "quarantined": quarantined}

    def gc(self) -> list[str]:
        now = time.time()
        dead = [h.id for h in self._hosts.values()
                if not h.peer_ids and (now - h.updated_at) > self._ttl]
        for hid in dead:
            del self._hosts[hid]
        return dead
