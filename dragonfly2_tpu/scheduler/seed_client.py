"""Seed-peer client pool: scheduler → seed daemon trigger calls.

Reference: scheduler/resource/standard/seed_peer.go + seed_peer_client.go —
TriggerDownloadTask asks a seed daemon to fetch a task from origin on behalf
of the cluster (the ObtainSeeds/v2 DownloadTask path).
"""

from __future__ import annotations

from dragonfly2_tpu.pkg import dflog
from dragonfly2_tpu.pkg.types import NetAddr
from dragonfly2_tpu.rpc import Client

log = dflog.get("scheduler.seed_client")


class SeedPeerClientPool:
    def __init__(self):
        self._clients: dict[str, Client] = {}

    def _client(self, ip: str, port: int) -> Client:
        key = f"{ip}:{port}"
        cli = self._clients.get(key)
        if cli is None:
            cli = Client(NetAddr.tcp(ip, port))
            self._clients[key] = cli
        return cli

    async def trigger_download_task(self, host, task_spec: dict) -> bool:
        """Fire-and-forget trigger; the seed reports progress through its own
        AnnouncePeer stream. Returns False if the seed is unreachable."""
        cli = self._client(host.ip, host.port)
        try:
            resp = await cli.call("Peer.TriggerDownloadTask", task_spec, timeout=10.0)
            return bool(resp and resp.get("ok"))
        except Exception as e:
            log.warning("seed trigger failed", seed=host.id, error=str(e))
            return False

    async def delete_task(self, host, task_id: str) -> bool:
        """Remove a task's local store on a daemon (delete_task job fan-out —
        reference scheduler/job/job.go deleteTask → dfdaemon client)."""
        cli = self._client(host.ip, host.port)
        try:
            resp = await cli.call("Peer.DeleteTask", {"task_id": task_id}, timeout=10.0)
            return bool(resp and resp.get("ok"))
        except Exception as e:
            log.warning("peer delete_task failed", host=host.id, error=str(e))
            return False

    async def stat_task(self, host, task_id: str) -> dict | None:
        """Remote task stat on a daemon (get_task job / sync probes)."""
        cli = self._client(host.ip, host.port)
        try:
            return await cli.call("Peer.StatTask", {"task_id": task_id}, timeout=10.0)
        except Exception:
            return None

    async def flight_digest(self, host, task_id: str) -> dict | None:
        """On-demand pod-lens pull: the compact flight digest for a task
        a daemon ran but whose shipped digest never arrived (crashed
        stream, still running). Best-effort — None on any failure."""
        cli = self._client(host.ip, host.port)
        try:
            resp = await cli.call("Daemon.FlightReport",
                                  {"task_id": task_id}, timeout=5.0)
            return resp.get("digest") if isinstance(resp, dict) else None
        except Exception:
            return None

    async def close(self) -> None:
        for cli in self._clients.values():
            await cli.close()
        self._clients.clear()
