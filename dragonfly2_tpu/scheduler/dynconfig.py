"""Scheduler dynconfig: pulls cluster config + seed peers from the manager.

Reference: scheduler/config/dynconfig.go — NewDynconfig wraps the generic
puller with {scheduler cluster client/config, seed peers} from the manager,
feeding the resource layer and seed-peer client.
"""

from __future__ import annotations

from typing import Any

from dragonfly2_tpu.manager.client import ManagerClient
from dragonfly2_tpu.pkg import dflog
from dragonfly2_tpu.pkg.dynconfig import Dynconfig
from dragonfly2_tpu.pkg.types import HostType

log = dflog.get("scheduler.dynconfig")


class SchedulerDynconfig:
    def __init__(self, manager_client: ManagerClient, cluster_id: int, *,
                 refresh_interval: float = 10.0, cache_dir: str = ""):
        self.client = manager_client
        self.cluster_id = cluster_id
        self.dc = Dynconfig(f"scheduler-c{cluster_id}", self._fetch,
                            refresh_interval=refresh_interval,
                            cache_dir=cache_dir)

    async def _fetch(self) -> dict[str, Any]:
        cluster = await self.client.get_scheduler_cluster_config(self.cluster_id)
        seed_peers = await self.client.list_seed_peers(self.cluster_id)
        return {
            "config": cluster.get("config", {}),
            "client_config": cluster.get("client_config", {}),
            "scopes": cluster.get("scopes", {}),
            "seed_peers": seed_peers,
        }

    async def get(self) -> dict[str, Any]:
        return await self.dc.get()

    async def seed_peers(self) -> list[dict]:
        return (await self.get()).get("seed_peers", [])

    def register(self, observer) -> None:
        self.dc.register(observer)

    def serve(self) -> None:
        self.dc.serve()

    def stop(self) -> None:
        self.dc.stop()


def seed_peer_host_wire(sp: dict) -> dict:
    """Convert a manager seed-peer row into an AnnounceHost-shaped dict so the
    resource layer can pre-register the seed before it announces itself."""
    type_map = {"super": HostType.SUPER_SEED, "strong": HostType.STRONG_SEED,
                "weak": HostType.WEAK_SEED}
    return {
        "id": f"{sp['hostname']}-{sp['ip']}-seed",
        "hostname": sp["hostname"],
        "ip": sp["ip"],
        "port": sp["port"],
        "upload_port": sp.get("download_port", 0),
        "type": int(type_map.get(sp.get("type", "super"), HostType.SUPER_SEED)),
        "idc": sp.get("idc", ""),
        "location": sp.get("location", ""),
    }
