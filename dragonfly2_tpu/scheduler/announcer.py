"""Scheduler announcer: self-registration with the manager + keepalive.

Reference: scheduler/announcer/announcer.go — New (:51) calls
UpdateScheduler, announceToManager (:91) keeps alive over the stream.
"""

from __future__ import annotations

import socket

from dragonfly2_tpu.manager.client import ManagerClient
from dragonfly2_tpu.pkg import dflog
from dragonfly2_tpu.pkg.types import NetAddr

log = dflog.get("scheduler.announcer")


class SchedulerAnnouncer:
    def __init__(self, manager_addr: str, *, cluster_id: int, port: int,
                 ip: str = "", hostname: str = "", idc: str = "",
                 location: str = "", keepalive_interval: float = 5.0,
                 qos_payload=None):
        host, _, mport = manager_addr.rpartition(":")
        self.client = ManagerClient(NetAddr.tcp(host, int(mport)))
        self.cluster_id = cluster_id
        self.port = port
        self.hostname = hostname or socket.gethostname()
        self.ip = ip or "127.0.0.1"
        self.idc = idc
        self.location = location
        self.keepalive_interval = keepalive_interval
        # Zero-arg callable returning {"tenant_burn": {...}} (or any dict)
        # to piggyback on keepalives — the scheduler passes the tenant
        # burn-book snapshot so manager job admission sees fresh burn.
        self.qos_payload = qos_payload
        self.registered: dict | None = None

    async def start(self) -> dict:
        self.registered = await self.client.update_scheduler(
            hostname=self.hostname, ip=self.ip, port=self.port,
            idc=self.idc, location=self.location,
            scheduler_cluster_id=self.cluster_id)
        self.client.start_keepalive(
            source_type="scheduler", hostname=self.hostname, ip=self.ip,
            cluster_id=self.registered["scheduler_cluster_id"],
            interval=self.keepalive_interval, payload=self.qos_payload)
        log.info("registered with manager", id=self.registered["id"],
                 cluster=self.registered["scheduler_cluster_id"])
        return self.registered

    async def stop(self) -> None:
        await self.client.close()
