"""Scheduler configuration and tuning constants.

Reference: scheduler/config/config.go + constants.go:26-107 (the numbers
that shape scheduling behavior). TPU addition: topology affinity weights for
ICI/DCN-aware parent selection (BASELINE.json north star).
"""

from __future__ import annotations

from dataclasses import dataclass, field

import yaml

from dragonfly2_tpu.pkg.prof import ProfConfig

# Reference scheduler/config/constants.go values.
SEED_PEER_CONCURRENT_UPLOAD_LIMIT = 2000   # :26-28
PEER_CONCURRENT_UPLOAD_LIMIT = 200         # :29-31
CANDIDATE_PARENT_LIMIT = 4                 # :32-34
FILTER_PARENT_LIMIT = 15                   # :35-37
TASK_BACK_TO_SOURCE_PEER_COUNT = 200       # :59-61
RETRY_LIMIT = 5                            # :64-65
RETRY_BACK_TO_SOURCE_LIMIT = 4             # :66-67
RETRY_INTERVAL = 0.5                       # :68-70 (500ms)
PIECE_DOWNLOAD_TIMEOUT = 30 * 60.0         # :71-73
PEER_TTL = 24 * 3600.0                     # :77-79
HOST_TTL = 3600.0                          # :86-88 (reference 1h)
TASK_TTL = 24 * 3600.0


@dataclass
class SchedulerServerConfig:
    host: str = "127.0.0.1"
    port: int = 8002                       # reference DefaultPort (constants.go:42)
    advertise_ip: str = ""


@dataclass
class SchedulingConfig:
    # "default" = built-in weighted evaluator; any other name is resolved
    # through the plugin registry (reference evaluator plugin.go:39
    # LoadPlugin when algorithm == "plugin").
    algorithm: str = "default"
    candidate_parent_limit: int = CANDIDATE_PARENT_LIMIT
    filter_parent_limit: int = FILTER_PARENT_LIMIT
    retry_limit: int = RETRY_LIMIT
    retry_back_to_source_limit: int = RETRY_BACK_TO_SOURCE_LIMIT
    retry_interval: float = RETRY_INTERVAL
    back_to_source_count: int = TASK_BACK_TO_SOURCE_PEER_COUNT
    # How long to hold a peer that refuses back-to-source (dfcache export)
    # in the schedule loop waiting for a parent to appear.
    no_source_patience: float = 30.0
    # Striped slice broadcast (scheduling/stripe.py): peers that register
    # with pod_broadcast=true always stripe once >= 2 same-slice
    # broadcast peers share the task. Setting this >= 2 additionally
    # auto-stripes ANY task with that many alive same-slice peers — off
    # by default so plain fan-outs keep the classic full-copy semantics
    # unless the deployment opts in.
    stripe_min_slice_peers: int = 0
    # Evaluator weights (reference evaluator_base.go:28-46); topology terms
    # replace IDC/location weighting when TPU topology metadata is present.
    weight_finished_pieces: float = 0.2
    weight_upload_success: float = 0.2
    weight_free_upload: float = 0.15
    weight_host_type: float = 0.15
    weight_idc_affinity: float = 0.15
    weight_location_affinity: float = 0.15


@dataclass
class FleetConfig:
    """Fleet observatory bounds (pkg/fleet): the continuous scheduler-side
    cluster view. All structures are preallocated/bounded — these knobs
    size them; ``enabled=False`` removes the per-event hooks entirely
    (fleet_bench publishes the paired on/off overhead)."""

    enabled: bool = True
    bucket_s: float = 5.0          # time-series bucket width
    buckets: int = 720             # ring length (5s x 720 = 1h)
    decision_cap: int = 1024       # audit-log ring length
    scorecard_hosts: int = 1024    # per-host scorecards kept (LRU past it)
    straggler_z: float = 3.0       # robust z-score flag threshold
    min_serve_samples: int = 8     # serve EWMA samples before scoring
    min_population: int = 8        # scored hosts before anyone is flagged
    # Advisory candidate filter: flagged stragglers are dropped from
    # parent candidate sets (each drop is recorded in the decision log).
    straggler_filter: bool = True
    # Cluster control tower (pkg/cluster): hard byte cap on the fleet
    # frame each manager keepalive carries (halving-until-fit past it).
    frame_max_bytes: int = 8192


@dataclass
class PodLensConfig:
    """Pod lens (pkg/podlens) + SLO engine (pkg/slo) bounds: the merged
    cross-host timeline store, the per-host clock estimator, and the
    continuous burn-rate evaluation. All bounded; ``enabled=False``
    removes the digest-ingest hooks entirely (podlens_bench publishes
    the paired on/off overhead as ``config10_podlens``)."""

    enabled: bool = True
    slo_enabled: bool = True
    max_tasks: int = 256           # task digests kept (LRU past it)
    clock_hosts: int = 4096        # per-host clock sample slots
    pull_missing: int = 16         # on-demand FlightReport pulls/timeline
    max_completions: int = 4096    # SLO completion ring length


@dataclass
class HAConfig:
    """Crash-recovery (scheduler HA): a bounded, periodically-flushed
    snapshot of live task/peer/host state in the same embedded-sqlite
    backend as the persistent-cache rows, so a restarted scheduler serves
    correct stripe plans and parent sets immediately — before every host
    has re-announced. Snapshot load and live resume re-registration
    converge to the same state (property-tested in
    tests/test_scheduler_ha.py)."""

    enabled: bool = True
    # Snapshot db path; "" reuses ``persistent_cache_db`` (one durable
    # file per scheduler). ":memory:" keeps the machinery live for tests
    # without durability.
    snapshot_db: str = ""
    snapshot_interval: float = 5.0
    # Bounds: newest tasks win; peers are capped per flush (terminal
    # peers are never written, so these bound live state only).
    max_tasks: int = 1024
    max_peers: int = 65536


@dataclass
class GCConfig:
    peer_ttl: float = PEER_TTL
    host_ttl: float = HOST_TTL
    task_ttl: float = TASK_TTL
    interval: float = 60.0


@dataclass
class SchedulerConfig:
    server: SchedulerServerConfig = field(default_factory=SchedulerServerConfig)
    scheduling: SchedulingConfig = field(default_factory=SchedulingConfig)
    gc: GCConfig = field(default_factory=GCConfig)
    fleet: FleetConfig = field(default_factory=FleetConfig)
    podlens: PodLensConfig = field(default_factory=PodLensConfig)
    ha: HAConfig = field(default_factory=HAConfig)
    # Runtime observatory (pkg/prof): /debug/prof* on the scheduler's
    # metrics server + the loop_lag SLO probe wired into the engine.
    prof: ProfConfig = field(default_factory=ProfConfig)
    manager_addr: str = ""                 # manager drpc for registration
    # Advertised hostname for manager registration and cluster-frame
    # attribution; "" = socket.gethostname(). Multi-scheduler tests on
    # one machine need distinct identities (the manager keys schedulers
    # by hostname+ip+cluster).
    hostname: str = ""
    manager_keepalive_interval: float = 5.0
    cluster_id: int = 1
    # Durable persistent-cache state (reference: Redis-backed
    # scheduler/resource/persistentcache); ":memory:" = tests/dev.
    persistent_cache_db: str = ":memory:"
    metrics_port: int = 0
    seed_peer_enabled: bool = True

    @classmethod
    def load(cls, path: str) -> "SchedulerConfig":
        with open(path) as f:
            data = yaml.safe_load(f) or {}
        cfg = cls()
        from dragonfly2_tpu.daemon.config import _merge_dataclass

        _merge_dataclass(cfg, data)
        return cfg
