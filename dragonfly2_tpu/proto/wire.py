"""The drpc wire contract: every method's request shape, in one module.

Reference: the entire RPC surface of Dragonfly2 is a single versioned
protobuf module (``d7y.io/api/v2`` — /root/reference/go.mod:6) that every
role compiles against. This module plays that role for the msgpack drpc
surface: a declarative schema per method (unary requests, stream opens,
and client→server stream messages), validated at the SERVER boundary
(rpc/server.py) so malformed or mistyped bodies fail fast with
Code.BadRequest instead of surfacing as deep KeyErrors/TypeErrors — the
class of bug per-handler tests can't exhaustively cover.

Semantics follow protobuf's spirit: unknown fields pass through
(forward compatibility), missing optional fields take their defaults,
required fields and type mismatches reject the call. Handlers keep
reading plain dicts — the schema is enforcement, not a codegen layer.
"""

from __future__ import annotations

from typing import Any

__all__ = [
    "F", "Msg", "SchemaError",
    "validate_unary", "validate_stream_open", "validate_stream_msg",
    "UNARY", "STREAM_OPEN", "STREAM_MSGS",
]


class SchemaError(ValueError):
    """A body failed validation; message names the method+field."""


class F:
    """One field: type, requiredness, optional nested/list schema."""

    __slots__ = ("type", "required", "spec", "item")

    def __init__(self, type_: type | tuple, required: bool = False,
                 spec: "Msg | None" = None, item: "F | None" = None):
        self.type = type_
        self.required = required
        self.spec = spec      # nested Msg for dict fields
        self.item = item      # element spec for list fields


class Msg:
    """A message shape: field name → F. Unknown fields are allowed."""

    __slots__ = ("name", "fields")

    def __init__(self, name: str, **fields: F):
        self.name = name
        self.fields = fields

    def validate(self, body: Any, where: str) -> None:
        if body is None:
            body = {}
        if not isinstance(body, dict):
            raise SchemaError(f"{where}: body must be a map, got "
                              f"{type(body).__name__}")
        for fname, f in self.fields.items():
            if fname not in body:
                if f.required:
                    raise SchemaError(f"{where}: missing required field "
                                      f"{fname!r}")
                continue
            value = body[fname]
            if value is None and not f.required:
                continue
            self._check(fname, f, value, where)

    def _check(self, fname: str, f: F, value: Any, where: str) -> None:
        ok = isinstance(value, f.type)
        # bools are ints in Python; don't let a bool satisfy an int field
        # unless the field is bool itself.
        if ok and isinstance(value, bool) and f.type is not bool:
            types = f.type if isinstance(f.type, tuple) else (f.type,)
            ok = bool in types
        # ints satisfy float fields (msgpack preserves the distinction) —
        # but bools, despite being ints, satisfy neither.
        if (not ok and f.type is float and isinstance(value, int)
                and not isinstance(value, bool)):
            ok = True
        if not ok:
            raise SchemaError(
                f"{where}: field {fname!r} must be "
                f"{getattr(f.type, '__name__', f.type)}, got "
                f"{type(value).__name__}")
        if f.spec is not None and isinstance(value, dict):
            f.spec.validate(value, f"{where}.{fname}")
        if f.item is not None and isinstance(value, (list, tuple)):
            for i, item in enumerate(value):
                self._check(f"{fname}[{i}]", f.item, item, where)


# --------------------------------------------------------------------- #
# Shared shapes
# --------------------------------------------------------------------- #

HOST = Msg(
    "Host",
    id=F(str), hostname=F(str), ip=F(str), port=F(int), upload_port=F(int),
    type=F(int), idc=F(str), location=F(str), tpu_slice=F(str),
    tpu_worker_index=F(int), telemetry=F(dict),
)

URL_META = Msg(
    "UrlMeta",
    digest=F(str), tag=F(str), range=F(str), filter=F(str),
    header=F(dict), application=F(str), priority=F(int),
    # QoS attribution tag (dragonfly2_tpu/qos): rides with the request
    # but stays OUT of task identity — two tenants pulling the same
    # content share one task.
    tenant=F(str),
)

PIECE = Msg(
    "Piece",
    piece_num=F(int, required=True), range_start=F(int), range_size=F(int),
    digest=F(str), download_cost_ms=F(int), dst_peer_id=F(str),
    # Flight-recorder per-phase split of download_cost_ms ({dcn_ms,
    # stall_ms, store_ms}): the scheduler's PodAggregator folds these into
    # per-host straggler attribution (/debug/pod/<task_id>). Optional —
    # origin/imported pieces report without it.
    timings=F(dict),
)

# Packed piece-report batch (proto/reportcodec): the negotiated compact
# alternative to a PIECE dict list — delta-coded piece nums, fixed-width
# columns, interned dst_peer_id table. Only sent after the scheduler
# advertised ``packed_reports`` on a stamped answer; structural decode
# validation (column length, varint bounds, intern indices) lives in
# reportcodec.decode_packed — the schema only pins the envelope types.
PACKED_PIECES = Msg(
    "PackedPieces",
    v=F(int, required=True), n=F(int, required=True),
    peers=F(list, required=True, item=F(str)),
    nums=F(bytes, required=True), cols=F(bytes, required=True),
    digests=F(dict),
)

_PERSISTENT_COMMON = dict(
    task_id=F(str, required=True), peer_id=F(str), host=F(dict, spec=HOST),
)

# Clock-alignment round-trip sample (pkg/podlens.ClockEstimator): the
# daemon stamped t0/t1 (its anchored monotonic wall clock) around a prior
# announce whose response echoed the scheduler's ``sched_wall``; the NTP
# midpoint (t0+t1)/2 - echo estimates the host's offset with a
# guaranteed |error| <= (t1-t0)/2 bound.
CLOCK_SAMPLE = Msg(
    "ClockSample",
    t0=F(float, required=True), t1=F(float, required=True),
    echo=F(float, required=True),
)

# Resume state on a (re-)register: the daemon's full local task state —
# landed piece bitset, task geometry, contiguous-prefix digest, stripe
# membership — so a failover ring member or a restarted scheduler can
# rebuild Task/Peer FSMs from re-registrations instead of treating the
# peer as fresh (no re-download of landed pieces, no spurious
# back-to-source). piece_nums is the compact form; digests ride the
# idempotent re-report that follows.
RESUME = Msg(
    "Resume",
    piece_nums=F(list, item=F(int)),
    # Packed alternative to piece_nums (bit i of byte i>>3 = piece i
    # landed, proto/reportcodec.nums_to_bitmap): a 64k-host restart storm
    # re-registers with one bit per piece instead of a msgpack int list.
    # Negotiated like packed reports; an old scheduler ignores it and the
    # idempotent recovery re-report rebuilds the same state.
    piece_bitmap=F(bytes),
    content_length=F(int), piece_size=F(int), total_piece_count=F(int),
    prefix_digest=F(str), pod_broadcast=F(bool), stripe=F(dict),
)

# Compact bounded flight digest (pkg/flight.digest): phase totals +
# merged phase segments + truncated waterfall + clock samples, shipped on
# the terminal announce message so the scheduler's pod lens can merge
# cross-host timelines. Validated loosely (dict) — the digest is
# forward-evolving and byte-capped at the source.
FLIGHT_DIGEST = Msg(
    "FlightDigest",
    v=F(int), task_id=F(str), state=F(str), start_wall=F(float),
    wall_s=F(float), phases=F(dict), segments=F(list),
    pieces=F(list), events=F(list), clock=F(list),
)

# --------------------------------------------------------------------- #
# Unary request schemas, keyed by method
# --------------------------------------------------------------------- #

UNARY: dict[str, Msg] = {
    # Scheduler (reference schedulerv2 + persistent-cache family)
    "Scheduler.AnnounceHost": Msg(
        "AnnounceHost",
        id=F(str, required=True), hostname=F(str), ip=F(str), port=F(int),
        upload_port=F(int), type=F(int), idc=F(str), location=F(str),
        tpu_slice=F(str), tpu_worker_index=F(int), telemetry=F(dict),
        # Previous announce's round-trip clock sample (the response
        # carries ``sched_wall`` to echo back) — feeds the pod lens's
        # per-host clock alignment.
        clock=F(dict, spec=CLOCK_SAMPLE)),
    # Merged cross-host broadcast timeline (pkg/podlens): the scheduler
    # assembles shipped flight digests (+ on-demand Daemon.FlightReport
    # pulls) into one wall-aligned pod view — dfget --pod's data source.
    "Scheduler.PodTimeline": Msg(
        "PodTimeline", task_id=F(str, required=True)),
    "Scheduler.LeaveHost": Msg("LeaveHost", id=F(str, required=True)),
    "Scheduler.LeavePeer": Msg("LeavePeer", id=F(str, required=True)),
    "Scheduler.AnnounceTask": Msg(
        "AnnounceTask",
        task_id=F(str, required=True), peer_id=F(str, required=True),
        url=F(str), tag=F(str), application=F(str),
        host=F(dict, required=True, spec=HOST),
        content_length=F(int), piece_size=F(int), total_piece_count=F(int),
        piece_nums=F(list, item=F(int))),
    "Scheduler.StatTask": Msg("StatTask", task_id=F(str, required=True)),
    "Scheduler.StatPeer": Msg("StatPeer", peer_id=F(str, required=True)),
    "Scheduler.ListHosts": Msg("ListHosts"),
    "Scheduler.UploadPersistentCacheTaskStarted": Msg(
        "UploadPersistentCacheTaskStarted",
        **_PERSISTENT_COMMON,
        url=F(str), tag=F(str), application=F(str), piece_size=F(int),
        content_length=F(int), total_piece_count=F(int),
        replica_count=F(int), ttl=F(float), digest=F(str)),
    "Scheduler.UploadPersistentCacheTaskFinished": Msg(
        "UploadPersistentCacheTaskFinished",
        **_PERSISTENT_COMMON,
        content_length=F(int), piece_size=F(int), total_piece_count=F(int)),
    "Scheduler.UploadPersistentCacheTaskFailed": Msg(
        "UploadPersistentCacheTaskFailed", **_PERSISTENT_COMMON),
    "Scheduler.StatPersistentCacheTask": Msg(
        "StatPersistentCacheTask", task_id=F(str, required=True)),
    "Scheduler.ListPersistentCacheTasks": Msg("ListPersistentCacheTasks"),
    "Scheduler.DeletePersistentCacheTask": Msg(
        "DeletePersistentCacheTask", task_id=F(str, required=True)),

    # Daemon download service (unix socket — dfget/dfcache attach)
    "Daemon.StatTask": Msg("DaemonStatTask", task_id=F(str, required=True)),
    "Daemon.ImportTask": Msg(
        "ImportTask",
        path=F(str, required=True), cache_id=F(str, required=True),
        tag=F(str), application=F(str), digest=F(str),
        persistent=F(bool), replica_count=F(int), ttl=F(float)),
    "Daemon.DeleteTask": Msg("DeleteTask", task_id=F(str, required=True)),
    "Daemon.Health": Msg("Health"),
    # Flight-recorder autopsy: the phase breakdown + waterfall for a task
    # this daemon ran (dfget --explain, tooling; also served on the PEER
    # service so the scheduler can pull digests on demand for the pod
    # timeline).
    "Daemon.FlightReport": Msg("FlightReport",
                               task_id=F(str, required=True)),
    # dfget --pod: the daemon proxies the merged cross-host timeline from
    # the scheduler (Scheduler.PodTimeline) over its own ring client.
    "Daemon.PodTimeline": Msg("DaemonPodTimeline",
                              task_id=F(str, required=True)),

    # Peer service (TCP — other daemons + scheduler triggers)
    "Peer.GetPieceTasks": Msg(
        "GetPieceTasks", task_id=F(str, required=True)),
    "Peer.TriggerDownloadTask": Msg(
        "TriggerDownloadTask",
        url=F(str, required=True), task_id=F(str), tag=F(str),
        application=F(str), digest=F(str), header=F(dict),
        filters=F(list, item=F(str)), seed=F(bool),
        disable_back_source=F(bool),
        # preheat-to-device: "tpu" additionally lands the content in the
        # triggered daemon's HBM sink (north-star pod-wide warm-up)
        device=F(str),
        # sharded preheat: warm only this byte range ("bytes=a-b") — a
        # distinct ranged task; stage groups preheat their own spans
        range=F(str),
        # pod-wide preheat: register the triggered pull as a striped
        # slice broadcast (scheduler answers with a stripe plan)
        pod_broadcast=F(bool),
        # QoS plane: the triggering caller's tenant tag + priority class
        # carry into the seed task so preheats are attributable and
        # dispatched fairly like any other pull
        tenant=F(str), priority=F(int)),
    "Peer.StatTask": Msg("PeerStatTask", task_id=F(str, required=True)),
    "Peer.DeleteTask": Msg("PeerDeleteTask", task_id=F(str, required=True)),

    # Manager (reference managerv2)
    "Manager.GetScheduler": Msg(
        "GetScheduler", hostname=F(str), ip=F(str),
        scheduler_cluster_id=F(int)),
    "Manager.ListSchedulers": Msg(
        "ListSchedulers", hostname=F(str), ip=F(str), idc=F(str),
        location=F(str)),
    "Manager.UpdateScheduler": Msg(
        "UpdateScheduler",
        hostname=F(str, required=True), ip=F(str, required=True),
        scheduler_cluster_id=F(int),   # omitted → seeded default cluster
        port=F(int), idc=F(str), location=F(str), state=F(str),
        features=F(list)),
    "Manager.GetSchedulerClusterConfig": Msg(
        "GetSchedulerClusterConfig",
        scheduler_cluster_id=F(int, required=True)),
    "Manager.ListSeedPeers": Msg(
        "ListSeedPeers", scheduler_cluster_id=F(int, required=True)),
    "Manager.UpdateSeedPeer": Msg(
        "UpdateSeedPeer",
        hostname=F(str, required=True), ip=F(str, required=True),
        seed_peer_cluster_id=F(int),   # omitted → seeded default cluster
        port=F(int), download_port=F(int), object_storage_port=F(int),
        type=F(str), idc=F(str), location=F(str), state=F(str)),
    "Manager.DeleteSeedPeer": Msg(
        "DeleteSeedPeer", hostname=F(str), ip=F(str),
        seed_peer_cluster_id=F(int)),
    "Manager.ListApplications": Msg("ListApplications"),
    "Manager.ListBuckets": Msg("ListBuckets"),
    "Manager.UpsertPeer": Msg(
        "UpsertPeer", hostname=F(str), ip=F(str), port=F(int),
        idc=F(str), location=F(str), state=F(str)),
    "Manager.PollJob": Msg(
        "PollJob", queue=F(str, required=True), timeout=F(float)),
    "Manager.CompleteJob": Msg(
        "CompleteJob",
        group_id=F(str, required=True), task_uuid=F(str, required=True),
        state=F(str), result=F(dict)),
    "Manager.TakeJobTokens": Msg(
        "TakeJobTokens", cluster_ids=F(list, required=True), tokens=F(int)),
}

# --------------------------------------------------------------------- #
# Stream open schemas
# --------------------------------------------------------------------- #

STREAM_OPEN: dict[str, Msg] = {
    "Scheduler.AnnouncePeer": Msg(
        "AnnouncePeerOpen",
        host=F(dict, required=True, spec=HOST),
        peer_id=F(str, required=True), task_id=F(str, required=True),
        url=F(str), tag=F(str), application=F(str), digest=F(str),
        filters=F(list, item=F(str)), header=F(dict), priority=F(int),
        # QoS attribution tag — carried into the scheduler's Task so
        # completions feed the per-tenant burn book (qos/admission)
        tenant=F(str),
        range=F(str), is_seed=F(bool), disable_back_source=F(bool),
        # striped slice broadcast: the task fans to >=2 same-slice hosts;
        # the scheduler answers with a stripe plan (piece%S ownership)
        pod_broadcast=F(bool)),
    "Daemon.Download": Msg(
        "DownloadOpen",
        url=F(str, required=True), output=F(str),
        meta=F(dict, spec=URL_META), disable_back_source=F(bool),
        device=F(str), pod_broadcast=F(bool),
        # checkpoint-delta plane: task id of the locally-landed base
        # version; chunks the base already holds are copied locally and
        # only changed chunks cross the wire (dfget --delta-base)
        delta_base=F(str)),
    "Daemon.ExportTask": Msg(
        "ExportTaskOpen",
        cache_id=F(str, required=True), output=F(str, required=True),
        tag=F(str), application=F(str), digest=F(str)),
    "Peer.SyncPieceTasks": Msg(
        "SyncPieceTasksOpen",
        task_id=F(str, required=True), peer_id=F(str)),
    "Manager.KeepAlive": Msg(
        "KeepAliveOpen",
        source_type=F(str), hostname=F(str), ip=F(str), cluster_id=F(int)),
}

# --------------------------------------------------------------------- #
# Client→server stream message schemas, by method and "type" discriminator
# --------------------------------------------------------------------- #

STREAM_MSGS: dict[str, dict[str, Msg]] = {
    "Scheduler.AnnouncePeer": {
        "register": Msg("Register", resume=F(dict, spec=RESUME)),
        "download_started": Msg(
            "DownloadStarted", content_length=F(int), piece_size=F(int),
            total_piece_count=F(int)),
        "piece_finished": Msg(
            "PieceFinished", piece=F(dict, required=True, spec=PIECE)),
        "pieces_finished": Msg(
            "PiecesFinished",
            # Exactly one of the two forms rides a message: the legacy
            # per-piece dict list, or the negotiated packed batch.
            pieces=F(list, item=F(dict, spec=PIECE)),
            packed=F(dict, spec=PACKED_PIECES)),
        "piece_failed": Msg(
            "PieceFailed", piece_num=F(int), parent_id=F(str),
            temporary=F(bool),
            # Typed failure reason (pkg/quarantine.REASON_WEIGHTS
            # vocabulary): feeds the scheduler-side parent demotion.
            reason=F(str)),
        "reschedule": Msg(
            "Reschedule", blocklist=F(list, item=F(str)),
            description=F(str)),
        "download_finished": Msg(
            "DownloadFinished", content_length=F(int), piece_size=F(int),
            total_piece_count=F(int),
            # Compact bounded flight digest (pkg/flight.digest) — the
            # "flight shipping" half of the pod lens: named events +
            # phase segments + per-piece waterfall + clock samples, one
            # per task, byte-capped at the source.
            flight=F(dict, spec=FLIGHT_DIGEST)),
        "download_failed": Msg("DownloadFailed", reason=F(str),
                               flight=F(dict, spec=FLIGHT_DIGEST)),
    },
}


# --------------------------------------------------------------------- #
# Boundary hooks (called by rpc/server.py)
# --------------------------------------------------------------------- #

def validate_unary(method: str, body: Any) -> None:
    """Raises SchemaError when ``body`` violates the method's schema.
    Unknown methods pass (plugins can register methods the core schema
    does not know — same posture as proto unknown fields)."""
    schema = UNARY.get(method)
    if schema is not None:
        schema.validate(body, method)


def validate_stream_open(method: str, body: Any) -> None:
    schema = STREAM_OPEN.get(method)
    if schema is not None:
        schema.validate(body, method)


def validate_stream_msg(method: str, body: Any) -> None:
    """Validate one client→server stream message. Messages with an
    unknown discriminator pass (server dispatch already warns), but on a
    schema'd method the body must at least be a map — a raw scalar would
    otherwise surface as an AttributeError deep in the handler."""
    kinds = STREAM_MSGS.get(method)
    if kinds is None:
        return
    if not isinstance(body, dict):
        raise SchemaError(f"{method}: stream message must be a map, got "
                          f"{type(body).__name__}")
    schema = kinds.get(body.get("type", ""))
    if schema is not None:
        schema.validate(body, f"{method}/{body.get('type')}")
