"""Common wire types shared by all surfaces (reference: commonv1/commonv2)."""

from __future__ import annotations

from dataclasses import dataclass, field
from typing import Any

from dragonfly2_tpu.pkg.types import Priority, TaskType


@dataclass
class UrlMeta:
    """Metadata distinguishing task identity and fetch behavior
    (reference commonv1.UrlMeta)."""

    digest: str = ""                   # expected content digest "sha256:..."
    tag: str = ""                      # task isolation tag
    range: str = ""                    # HTTP range within the URL content
    filter: str = ""                   # '&'-separated query params to ignore
    header: dict[str, str] = field(default_factory=dict)
    application: str = ""
    priority: int = int(Priority.LEVEL3)
    tenant: str = ""                   # QoS attribution tag (qos plane);
                                       # NOT part of task identity

    def to_wire(self) -> dict[str, Any]:
        return {
            "digest": self.digest,
            "tag": self.tag,
            "range": self.range,
            "filter": self.filter,
            "header": self.header,
            "application": self.application,
            "priority": self.priority,
            "tenant": self.tenant,
        }

    @classmethod
    def from_wire(cls, d: dict[str, Any] | None) -> "UrlMeta":
        d = d or {}
        return cls(
            digest=d.get("digest", ""),
            tag=d.get("tag", ""),
            range=d.get("range", ""),
            filter=d.get("filter", ""),
            header=d.get("header", {}) or {},
            application=d.get("application", ""),
            priority=d.get("priority", int(Priority.LEVEL3)),
            tenant=d.get("tenant", ""),
        )


@dataclass
class TaskMetadata:
    """Resolved task facts, set once the origin/first piece is known."""

    task_id: str
    url: str = ""
    content_length: int = -1
    piece_size: int = 0
    total_piece_count: int = -1
    digest: str = ""
    task_type: int = int(TaskType.STANDARD)

    def to_wire(self) -> dict[str, Any]:
        return {
            "task_id": self.task_id,
            "url": self.url,
            "content_length": self.content_length,
            "piece_size": self.piece_size,
            "total_piece_count": self.total_piece_count,
            "digest": self.digest,
            "task_type": self.task_type,
        }

    @classmethod
    def from_wire(cls, d: dict[str, Any]) -> "TaskMetadata":
        return cls(
            task_id=d["task_id"],
            url=d.get("url", ""),
            content_length=d.get("content_length", -1),
            piece_size=d.get("piece_size", 0),
            total_piece_count=d.get("total_piece_count", -1),
            digest=d.get("digest", ""),
            task_type=d.get("task_type", int(TaskType.STANDARD)),
        )
