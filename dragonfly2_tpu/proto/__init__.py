"""Message schemas for drpc surfaces.

Modeled on the reference's v2 protobuf API (d7y.io/api/v2: commonv2,
schedulerv2, dfdaemonv2) — typed request/response dataclasses with explicit
wire dicts. The v2 shape (AnnouncePeer stream dispatching on typed requests)
was chosen over v1's PeerPacket per SURVEY.md §7.1.
"""
