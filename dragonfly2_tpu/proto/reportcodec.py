"""Packed piece-report batches: the announce wire diet.

A coalesced ``pieces_finished`` batch is the scheduler's hottest ingest
unit — at 16k hosts it arrives tens of thousands of times per broadcast,
and the per-piece dict form (proto/wire.PIECE) pays msgpack map overhead
plus a Python dict walk per piece. The packed form here is a *negotiated
wire alternative* (the scheduler advertises ``packed_reports`` on every
stamped answer; the conductor only emits it after seeing the flag), so
mixed-version fleets interoperate: an old scheduler never receives
packed batches, an old daemon keeps sending dict lists, and unknown
fields pass schema validation on both sides.

Packed layout (``encode_reports`` → msgpack-ready dict)::

    {v: 1, n: <count>,
     peers:   [interned dst_peer_id strings, <= 65535],
     nums:    <bytes — zigzag-varint deltas of piece_num in batch order>,
     cols:    <bytes — n fixed 36-byte little-endian columns>,
     digests: {index: str}  # spill for digests that aren't crc32c:%08x}

Column struct ``<IQIHHIIII``: download_cost_ms u32, range_start u64,
range_size u32, peer_idx u16, flags u16, dcn_ms u32, stall_ms u32,
store_ms u32, digest_crc u32. Flags: bit0 = report carried a (truthy)
``timings`` dict; bit1 = digest packed as its crc32c word (string form
``crc32c:%08x``); bit2 = digest spilled to ``digests``.

Exactness contract: ``encode_reports`` REFUSES (returns None, caller
falls back to the dict list) any report the packed form cannot represent
*exactly* — unknown keys, non-int numerics, bools, negative values,
field overflow, unknown timings keys — so a packed batch decodes to the
same scheduler FSM state the dict walk would have produced, bit for bit.
tests/test_report_codec.py fuzzes this equivalence; the wire bench
asserts it against the legacy decoder as oracle.

Decoding sits behind the same backend ladder as delta/chunker — native
(``native/src/dfreport.cc``, one ctypes call per batch) > numpy >
python — selected once, self-probed against the pure-python reference
before native is trusted, forceable via ``DF_REPORT_BACKEND``. Backends
can only change speed, never the decoded batch: every rung returns the
same plain-Python lists and aggregates.

Also here: the landed-piece bitmap for ``RESUME`` (``nums_to_bitmap`` /
``bitmap_to_nums``) — a 64k-host restart storm re-registers with one
bit per piece instead of a msgpack int list.
"""

from __future__ import annotations

import os
import struct

try:
    import numpy as np
except ImportError:          # pragma: no cover - numpy is everywhere in CI
    np = None

from dragonfly2_tpu.pkg import metrics

__all__ = [
    "CodecError", "DecodedBatch", "encode_reports", "decode_packed",
    "report_backend", "nums_to_bitmap", "bitmap_to_nums",
    "FLAG_TIMINGS", "FLAG_CRC_DIGEST", "FLAG_SPILL_DIGEST",
]


class CodecError(ValueError):
    """A packed batch failed structural validation (truncated columns,
    varint overrun, out-of-range intern index). The scheduler drops the
    batch with a warning — at-least-once re-delivery re-reports the
    pieces — rather than killing the announce stream."""


# One column per piece: cost u32, range_start u64, range_size u32,
# peer_idx u16, flags u16, dcn u32, stall u32, store u32, digest_crc u32.
COLS = struct.Struct("<IQIHHIIII")
COL_SIZE = COLS.size            # 36

FLAG_TIMINGS = 1        # report carried a truthy timings dict
FLAG_CRC_DIGEST = 2     # digest packed as crc32c word ("crc32c:%08x")
FLAG_SPILL_DIGEST = 4   # digest spilled to the digests map

_U32 = 1 << 32
_U64 = 1 << 64
_ALLOWED_KEYS = frozenset((
    "piece_num", "range_start", "range_size", "digest",
    "download_cost_ms", "dst_peer_id", "timings"))
_TIMING_KEYS = ("dcn_ms", "stall_ms", "store_ms")
_HEX = frozenset("0123456789abcdef")

REPORT_BACKEND_ACTIVE = metrics.gauge(
    "scheduler_report_backend",
    "Selected packed piece-report decode backend (1 = active; ladder "
    "native > numpy > python, see proto/reportcodec.py)", ("backend",))


# --------------------------------------------------------------------- #
# varint / zigzag (piece-num delta stream)
# --------------------------------------------------------------------- #

def _zigzag(v: int) -> int:
    # v is a signed 64-bit delta; arithmetic shift makes this the classic
    # protobuf zigzag: 0,-1,1,-2,... -> 0,1,2,3,...
    return (v << 1) ^ (v >> 63)


def _encode_nums(nums: list) -> bytes:
    out = bytearray()
    prev = 0
    for num in nums:
        zz = _zigzag(num - prev)
        prev = num
        while zz >= 0x80:
            out.append((zz & 0x7F) | 0x80)
            zz >>= 7
        out.append(zz)
    return bytes(out)


def _decode_nums(buf: bytes, n: int) -> list:
    """Decode exactly ``n`` zigzag-varint deltas consuming all of ``buf``;
    the pure-python reference every other backend must match."""
    nums = []
    pos = 0
    end = len(buf)
    prev = 0
    for _ in range(n):
        zz = 0
        shift = 0
        while True:
            if pos >= end or shift > 63:
                raise CodecError("piece-num varint stream truncated")
            b = buf[pos]
            pos += 1
            zz |= (b & 0x7F) << shift
            if not b & 0x80:
                break
            shift += 7
        prev += (zz >> 1) ^ -(zz & 1)
        if prev < 0:
            raise CodecError("negative piece number")
        nums.append(prev)
    if pos != end:
        raise CodecError("trailing bytes after piece-num stream")
    return nums


# --------------------------------------------------------------------- #
# encode (conductor side)
# --------------------------------------------------------------------- #

def _int_field(v, bound: int):
    """Value as a non-negative int below ``bound``, or None to refuse.
    bool is an int in Python but means something else on the wire."""
    if type(v) is not int or not 0 <= v < bound:
        return None
    return v


def encode_reports(reports: list) -> "dict | None":
    """The packed wire form of a report batch, or None when any report
    is not exactly representable (caller sends the dict list instead).
    Refusal is the compatibility valve: new report fields, float costs,
    or exotic digests simply keep riding the legacy encoding."""
    n = len(reports)
    if n == 0 or n > _U32 - 1:
        return None
    peers: list = []
    peer_idx: dict = {}
    nums: list = []
    cols = bytearray(n * COL_SIZE)
    digests: dict = {}
    pack_into = COLS.pack_into
    for i, r in enumerate(reports):
        if not isinstance(r, dict) or not _ALLOWED_KEYS.issuperset(r):
            return None
        num = r.get("piece_num")
        if type(num) is not int or not 0 <= num < (1 << 63):
            return None
        start = _int_field(r.get("range_start"), _U64)
        size = _int_field(r.get("range_size"), _U32)
        cost = _int_field(r.get("download_cost_ms", 0), _U32)
        if start is None or size is None or cost is None:
            return None
        dst = r.get("dst_peer_id", "")
        if type(dst) is not str:
            return None
        pi = peer_idx.get(dst)
        if pi is None:
            if len(peers) >= 0xFFFF:
                return None
            pi = peer_idx[dst] = len(peers)
            peers.append(dst)
        flags = 0
        dcn = stall = store = 0
        timings = r.get("timings")
        if timings is not None:
            if not isinstance(timings, dict) \
                    or not set(timings).issubset(_TIMING_KEYS):
                return None
            if timings:        # {} is falsy: the dict walk ignores it too
                flags |= FLAG_TIMINGS
                vals = []
                for key in _TIMING_KEYS:
                    v = timings.get(key)
                    if v is None:
                        v = 0   # dict walk: int(timings.get(k, 0) or 0)
                    v = _int_field(v, _U32)
                    if v is None:
                        return None
                    vals.append(v)
                dcn, stall, store = vals
        crc = 0
        digest = r.get("digest", "")
        if type(digest) is not str:
            return None
        if digest:
            if (len(digest) == 15 and digest.startswith("crc32c:")
                    and _HEX.issuperset(digest[7:])):
                crc = int(digest[7:], 16)
                flags |= FLAG_CRC_DIGEST
            else:
                digests[i] = digest
                flags |= FLAG_SPILL_DIGEST
        nums.append(num)
        pack_into(cols, i * COL_SIZE, cost, start, size, pi, flags,
                  dcn, stall, store, crc)
    packed = {"v": 1, "n": n, "peers": peers,
              "nums": _encode_nums(nums), "cols": bytes(cols)}
    if digests:
        packed["digests"] = digests
    return packed


# --------------------------------------------------------------------- #
# decoded batch
# --------------------------------------------------------------------- #

class DecodedBatch:
    """One decoded packed batch: per-piece columns as plain Python lists
    (identical across backends) plus the batch aggregates the scheduler's
    apply path consumes — phase sums for PodAggregator, per-parent
    [count, cost_sum, bytes] for fleet scorecards — computed inside the
    backend so the hot path never walks pieces in Python."""

    __slots__ = ("n", "peers", "nums", "costs", "starts", "sizes",
                 "peer_idx", "flags", "crcs", "spill",
                 "cost_total", "bytes_total", "phase_ms", "parent_aggs",
                 "min_cost", "_phase_cols")

    def __init__(self, n, peers, nums, costs, starts, sizes, peer_idx,
                 flags, crcs, spill, cost_total, bytes_total, phase_ms,
                 parent_aggs, min_cost):
        self.n = n
        self.peers = peers
        self.nums = nums
        self.costs = costs
        self.starts = starts
        self.sizes = sizes
        self.peer_idx = peer_idx
        self.flags = flags
        self.crcs = crcs
        self.spill = spill
        self.cost_total = cost_total
        self.bytes_total = bytes_total
        self.phase_ms = phase_ms          # (dcn, stall, store) sums
        self.parent_aggs = parent_aggs    # per peer idx: [k, cost, bytes]
        self.min_cost = min_cost
        # Per-piece phase columns: only the slow-path bridge and debug
        # accessors need them — backends hand them over via _set_phases.
        self._phase_cols = ((), (), ())

    def digest(self, i: int) -> str:
        f = self.flags[i]
        if f & FLAG_CRC_DIGEST:
            return f"crc32c:{self.crcs[i]:08x}"
        if f & FLAG_SPILL_DIGEST:
            return self.spill.get(i, "")
        return ""

    def timings(self, i: int) -> "dict | None":
        if not self.flags[i] & FLAG_TIMINGS:
            return None
        return {"dcn_ms": self.phase_of(i, 0), "stall_ms": self.phase_of(i, 1),
                "store_ms": self.phase_of(i, 2)}

    def phase_of(self, i: int, phase: int) -> int:
        return self._phase_cols[phase][i]

    def to_dicts(self) -> list:
        """The equivalent dict-list batch — the slow-path bridge when the
        bulk apply can't run (duplicate nums, partially-known peer) and
        the reconstruction every fuzz test round-trips against."""
        out = []
        dcns, stalls, stores = self._phase_cols
        for i in range(self.n):
            d = {"piece_num": self.nums[i],
                 "range_start": self.starts[i],
                 "range_size": self.sizes[i],
                 "digest": self.digest(i),
                 "download_cost_ms": self.costs[i],
                 "dst_peer_id": self.peers[self.peer_idx[i]]}
            if self.flags[i] & FLAG_TIMINGS:
                d["timings"] = {"dcn_ms": dcns[i], "stall_ms": stalls[i],
                                "store_ms": stores[i]}
            out.append(d)
        return out

    def _set_phases(self, dcns, stalls, stores):
        self._phase_cols = (dcns, stalls, stores)
        return self


def _finish(n, peers, nums, cols, spill):
    """Shared python-rung finishing: aggregate totals from unpacked
    column lists (the reference semantics every backend must match)."""
    costs, starts, sizes, pidx, flags, dcns, stalls, stores, crcs = cols
    cost_total = 0
    bytes_total = 0
    dcn_t = stall_t = store_t = 0
    aggs = [[0, 0, 0] for _ in peers]
    min_cost = 0
    for i in range(n):
        c = costs[i]
        cost_total += c
        bytes_total += sizes[i]
        if flags[i] & FLAG_TIMINGS:
            dcn_t += dcns[i]
            stall_t += stalls[i]
            store_t += stores[i]
        else:
            dcn_t += c
        a = aggs[pidx[i]]
        a[0] += 1
        a[1] += c
        a[2] += sizes[i]
        if i == 0 or c < min_cost:
            min_cost = c
    batch = DecodedBatch(n, peers, nums, costs, starts, sizes, pidx,
                         flags, crcs, spill, cost_total, bytes_total,
                         (dcn_t, stall_t, store_t), aggs, min_cost)
    return batch._set_phases(dcns, stalls, stores)


# --------------------------------------------------------------------- #
# decode backends (native > numpy > python; FSM-identical by contract)
# --------------------------------------------------------------------- #

def _decode_python(nums_b, cols_b, n, peers, spill):
    nums = _decode_nums(nums_b, n)
    cols = tuple([] for _ in range(9))
    appends = [c.append for c in cols]
    n_peers = len(peers)
    for row in COLS.iter_unpack(cols_b):
        if row[3] >= n_peers:
            raise CodecError("peer intern index out of range")
        for v, app in zip(row, appends):
            app(v)
    return _finish(n, peers, nums, cols, spill)


_NP_DTYPE = None
if np is not None:
    _NP_DTYPE = np.dtype([
        ("cost", "<u4"), ("start", "<u8"), ("size", "<u4"),
        ("peer", "<u2"), ("flags", "<u2"), ("dcn", "<u4"),
        ("stall", "<u4"), ("store", "<u4"), ("crc", "<u4")])


def _decode_numpy(nums_b, cols_b, n, peers, spill):
    nums = _decode_nums(nums_b, n)     # varint stream stays a Python loop
    arr = np.frombuffer(cols_b, dtype=_NP_DTYPE)
    pidx = arr["peer"].astype(np.int64)
    n_peers = len(peers)
    if n and int(pidx.max()) >= n_peers:
        raise CodecError("peer intern index out of range")
    cost = arr["cost"].astype(np.int64)
    size = arr["size"].astype(np.int64)
    flags = arr["flags"]
    timed = (flags & FLAG_TIMINGS).astype(bool)
    dcn = arr["dcn"].astype(np.int64)
    # int64 accumulation throughout: identical to the python rung, no
    # float64 rounding at any batch size.
    dcn_t = int(np.where(timed, dcn, cost).sum())
    stall_t = int(arr["stall"].astype(np.int64)[timed].sum())
    store_t = int(arr["store"].astype(np.int64)[timed].sum())
    counts = np.bincount(pidx, minlength=n_peers)
    agg_cost = np.zeros(n_peers, np.int64)
    np.add.at(agg_cost, pidx, cost)
    agg_bytes = np.zeros(n_peers, np.int64)
    np.add.at(agg_bytes, pidx, size)
    aggs = [[int(counts[p]), int(agg_cost[p]), int(agg_bytes[p])]
            for p in range(n_peers)]
    batch = DecodedBatch(
        n, peers, nums, cost.tolist(), arr["start"].tolist(), size.tolist(),
        pidx.tolist(), flags.tolist(), arr["crc"].tolist(), spill,
        int(cost.sum()), int(size.sum()), (dcn_t, stall_t, store_t),
        aggs, int(cost.min()) if n else 0)
    return batch._set_phases(dcn.tolist(), arr["stall"].tolist(),
                             arr["store"].tolist())


def _native_decoder():
    """The dfreport.cc kernel as a decode function, or None. Self-checked
    against the pure-python reference on a deterministic batch before
    selection (the delta/chunker probe discipline)."""
    try:
        from dragonfly2_tpu.native import binding
    except ImportError:
        return None
    if not hasattr(binding, "report_decode"):
        return None      # stale prebuilt library without the kernel

    def decode(nums_b, cols_b, n, peers, spill):
        try:
            (nums, costs, starts, sizes, pidx, flags, dcns, stalls,
             stores, crcs, aggs, totals) = binding.report_decode(
                nums_b, cols_b, n, len(peers))
        except ValueError as e:
            raise CodecError(str(e)) from None
        batch = DecodedBatch(
            n, peers, nums, costs, starts, sizes, pidx, flags, crcs,
            spill, totals[0], totals[1], (totals[2], totals[3], totals[4]),
            aggs, totals[5])
        return batch._set_phases(dcns, stalls, stores)

    probe_reports = [
        {"piece_num": 7, "range_start": 7 << 20, "range_size": 1 << 20,
         "digest": "crc32c:00c0ffee", "download_cost_ms": 3,
         "dst_peer_id": "peer-a",
         "timings": {"dcn_ms": 2, "stall_ms": 0, "store_ms": 1}},
        {"piece_num": 3, "range_start": 3 << 20, "range_size": 1 << 20,
         "digest": "md5:abc", "download_cost_ms": 9, "dst_peer_id": ""},
        {"piece_num": 4, "range_start": 4 << 20, "range_size": 512,
         "digest": "", "download_cost_ms": 0, "dst_peer_id": "peer-a"},
    ]
    packed = encode_reports(probe_reports)
    try:
        got = decode(packed["nums"], packed["cols"], packed["n"],
                     list(packed["peers"]), dict(packed.get("digests") or {}))
        ref = _decode_python(packed["nums"], packed["cols"], packed["n"],
                             list(packed["peers"]),
                             dict(packed.get("digests") or {}))
        if got.to_dicts() != ref.to_dicts() \
                or got.parent_aggs != ref.parent_aggs \
                or got.phase_ms != ref.phase_ms \
                or (got.cost_total, got.bytes_total, got.min_cost) != (
                    ref.cost_total, ref.bytes_total, ref.min_cost):
            return None
    except Exception:
        return None
    return decode


_decoder = None
_backend_name = "unset"


def _select_decoder():
    """Pick the fastest available backend (native > numpy > python),
    honoring DF_REPORT_BACKEND={native,numpy,python} to pin a rung."""
    global _decoder, _backend_name
    forced = os.environ.get("DF_REPORT_BACKEND", "").strip().lower()
    native = None if forced in ("numpy", "python") else _native_decoder()
    if native is not None:
        _decoder, _backend_name = native, "native"
    elif np is not None and forced != "python":
        _decoder, _backend_name = _decode_numpy, "numpy"
    else:
        _decoder, _backend_name = _decode_python, "python"
    REPORT_BACKEND_ACTIVE.labels(_backend_name).set(1)
    return _decoder


def report_backend() -> str:
    """Which packed-batch decode implementation ingest uses:
    "native" (dfreport.cc), "numpy", or "python"."""
    if _decoder is None:
        _select_decoder()
    return _backend_name


def decode_packed(packed: dict) -> DecodedBatch:
    """Decode a packed ``pieces_finished`` batch. Raises CodecError on
    any structural violation — the caller drops the batch (at-least-once
    re-delivery restores the pieces) instead of failing the stream."""
    if not isinstance(packed, dict) or packed.get("v") != 1:
        raise CodecError(f"unsupported packed version {packed.get('v')!r}"
                         if isinstance(packed, dict)
                         else "packed body must be a map")
    n = packed.get("n")
    if type(n) is not int or n < 0:
        raise CodecError("bad piece count")
    peers = packed.get("peers")
    if not isinstance(peers, list) \
            or any(not isinstance(p, str) for p in peers):
        raise CodecError("bad peer intern table")
    nums_b = packed.get("nums")
    cols_b = packed.get("cols")
    if not isinstance(nums_b, (bytes, bytearray)) \
            or not isinstance(cols_b, (bytes, bytearray)):
        raise CodecError("nums/cols must be binary")
    if len(cols_b) != n * COL_SIZE:
        raise CodecError(f"column block is {len(cols_b)} bytes, "
                         f"want {n * COL_SIZE}")
    spill_raw = packed.get("digests") or {}
    if not isinstance(spill_raw, dict):
        raise CodecError("digest spill must be a map")
    spill = {}
    for k, v in spill_raw.items():
        if type(k) is not int or not isinstance(v, str) or not 0 <= k < n:
            raise CodecError("bad digest spill entry")
        spill[k] = v
    decoder = _decoder if _decoder is not None else _select_decoder()
    return decoder(bytes(nums_b), bytes(cols_b), n, list(peers), spill)


# --------------------------------------------------------------------- #
# RESUME piece bitmap
# --------------------------------------------------------------------- #

# bit i of byte (num >> 3) set <=> piece num landed.
_BITS_OF = tuple(
    tuple(b for b in range(8) if v & (1 << b)) for v in range(256))


def nums_to_bitmap(nums) -> bytes:
    """Landed-piece set as a little-bitmap (bit i of byte i>>3)."""
    if not nums:
        return b""
    out = bytearray((max(nums) >> 3) + 1)
    for num in nums:
        out[num >> 3] |= 1 << (num & 7)
    return bytes(out)


def bitmap_to_nums(bitmap) -> list:
    """Ascending piece numbers set in ``bitmap`` (inverse of
    nums_to_bitmap up to ordering/duplicates)."""
    nums = []
    extend = nums.extend
    base = 0
    for byte in bytes(bitmap):
        if byte:
            extend(base + b for b in _BITS_OF[byte])
        base += 8
    return nums
