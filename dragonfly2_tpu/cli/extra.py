"""Additional CLI subcommands registered as stages land."""

from __future__ import annotations

import argparse
import asyncio


def register(sub: argparse._SubParsersAction) -> None:
    _add_scheduler(sub)
    _add_manager(sub)
    _add_dfcache(sub)
    _add_dfstore(sub)


def _default_sock(work_home: str) -> str:
    from dragonfly2_tpu.pkg.dfpath import Dfpath

    return (Dfpath(work_home) if work_home else Dfpath()).daemon_sock


def _add_dfcache(sub: argparse._SubParsersAction) -> None:
    p = sub.add_parser("dfcache",
                       help="import/export/stat P2P cache entries (reference client/dfcache)")
    p.add_argument("op", choices=["import", "export", "stat", "delete"])
    p.add_argument("cache_id", help="cache entry id (task identity across hosts)")
    p.add_argument("--path", default="", help="local file (import)")
    p.add_argument("--output", default="", help="destination path (export)")
    p.add_argument("--tag", default="")
    p.add_argument("--application", default="")
    p.add_argument("--work-home", default="")
    p.add_argument("--timeout", type=float, default=60.0)
    p.add_argument("--persistent", action="store_true",
                   help="scheduler-managed persistent cache task (import)")
    p.add_argument("--replica-count", type=int, default=1)
    p.add_argument("--ttl", type=float, default=0.0,
                   help="persistent task TTL seconds (0 = forever)")
    p.set_defaults(func=_run_dfcache)


def _run_dfcache(args: argparse.Namespace) -> int:
    import json

    from dragonfly2_tpu.client import dfcache

    cfg = dfcache.DfcacheConfig(
        daemon_sock=_default_sock(args.work_home), cache_id=args.cache_id,
        tag=args.tag, application=args.application, timeout=args.timeout)

    async def run() -> int:
        if args.op == "import":
            if not args.path:
                print("--path required for import")
                return 2
            result = await dfcache.import_file(
                cfg, args.path, persistent=args.persistent,
                replica_count=args.replica_count, ttl=args.ttl)
        elif args.op == "export":
            if not args.output:
                print("--output required for export")
                return 2
            result = await dfcache.export_file(cfg, args.output)
        elif args.op == "stat":
            result = await dfcache.stat(cfg)
        else:
            result = await dfcache.delete(cfg)
        print(json.dumps(result))
        return 0

    return asyncio.run(run())


def _add_dfstore(sub: argparse._SubParsersAction) -> None:
    p = sub.add_parser("dfstore",
                       help="object-storage ops via the daemon gateway (reference client/dfstore)")
    p.add_argument("op", choices=["cp", "rm", "stat", "ls", "mb", "rb",
                                  "prefetch"])
    p.add_argument("args", nargs="*",
                   help="cp SRC DST (df://bucket/key or local path); "
                        "rm/stat/prefetch df://bucket/key; ls/mb/rb df://bucket")
    p.add_argument("--endpoint", default="http://127.0.0.1:65004",
                   help="daemon object gateway endpoint")
    p.add_argument("--mode", default="async_write_back")
    p.add_argument("--device", default="", choices=["", "tpu"],
                   help="prefetch: additionally land the object in the "
                        "daemon's TPU HBM sink (north-star --device=tpu)")
    p.add_argument("--range", dest="range_", default="",
                   help="prefetch: warm only this byte span a-b "
                        "(a ranged task; sharded warm-up)")
    p.add_argument("--timeout", type=float, default=None,
                   help="client timeout seconds (default 60; prefetch "
                        "defaults to 3600 — it blocks until the daemon "
                        "finishes the warm-up; 0 = no timeout)")
    p.set_defaults(func=_run_dfstore)


def _parse_df_url(value: str) -> tuple[str, str]:
    if not value.startswith("df://"):
        raise ValueError(f"not a df:// url: {value}")
    rest = value[5:]
    bucket, _, key = rest.partition("/")
    return bucket, key


def _run_dfstore(args: argparse.Namespace) -> int:
    import json

    from dragonfly2_tpu.client.dfstore import Dfstore

    required_args = {"cp": 2, "rm": 1, "stat": 1, "ls": 0, "mb": 1, "rb": 1,
                     "prefetch": 1}

    async def run() -> int:
        if len(args.args) < required_args[args.op]:
            print(f"dfstore {args.op}: expected {required_args[args.op]} "
                  f"argument(s), got {len(args.args)}")
            return 2
        if args.timeout is None:
            timeout = 3600.0 if args.op == "prefetch" else 60.0
        else:
            timeout = args.timeout  # 0 = unbounded (Dfstore maps it to None)
        store = Dfstore(args.endpoint, timeout=timeout)
        try:
            a = args.args
            if args.op == "cp":
                src, dst = a[0], a[1]
                if src.startswith("df://"):
                    bucket, key = _parse_df_url(src)
                    data = await store.get_object(bucket, key)
                    with open(dst, "wb") as f:
                        f.write(data)
                    print(f"downloaded {len(data)} bytes -> {dst}")
                else:
                    bucket, key = _parse_df_url(dst)
                    with open(src, "rb") as f:
                        data = f.read()
                    digest = await store.put_object(bucket, key, data, mode=args.mode)
                    print(f"uploaded {len(data)} bytes digest={digest}")
            elif args.op == "rm":
                bucket, key = _parse_df_url(a[0])
                await store.delete_object(bucket, key)
                print("deleted")
            elif args.op == "prefetch":
                bucket, key = _parse_df_url(a[0])
                result = await store.prefetch_object(
                    bucket, key, device=args.device,
                    range_header=args.range_)
                print(json.dumps(result))
            elif args.op == "stat":
                bucket, key = _parse_df_url(a[0])
                info = await store.stat_object(bucket, key)
                print(json.dumps(info.__dict__))
            elif args.op == "ls":
                bucket, _ = _parse_df_url(a[0]) if a else ("", "")
                if bucket:
                    for o in await store.list_objects(bucket):
                        print(f"{o.content_length:>12} {o.key}")
                else:
                    for name in await store.list_buckets():
                        print(name)
            elif args.op == "mb":
                bucket, _ = _parse_df_url(a[0])
                await store.create_bucket(bucket)
                print(f"created bucket {bucket}")
            elif args.op == "rb":
                bucket, _ = _parse_df_url(a[0])
                await store.delete_bucket(bucket)
                print(f"deleted bucket {bucket}")
            return 0
        finally:
            await store.close()

    return asyncio.run(run())


def _add_manager(sub: argparse._SubParsersAction) -> None:
    p = sub.add_parser("manager", help="run the manager global control plane")
    p.add_argument("--host", default="127.0.0.1")
    p.add_argument("--port", type=int, default=8080, help="REST port")
    p.add_argument("--grpc-port", type=int, default=65003, help="drpc port")
    p.add_argument("--db", default=":memory:", help="sqlite path (default in-memory)")
    p.add_argument("--metrics-port", type=int, default=0,
                   help="fixed port for /metrics + /debug/cluster* "
                        "(0 = ephemeral, negative disables)")
    p.add_argument("--keepalive-timeout", type=float, default=60.0,
                   help="seconds before a silent scheduler/seed-peer "
                        "keepalive flips the row inactive")
    p.add_argument("--keepalive-gc-interval", type=float, default=30.0,
                   help="seconds between expire_stale sweeps")
    p.set_defaults(func=_run_manager)


def _run_manager(args: argparse.Namespace) -> int:
    from dragonfly2_tpu.manager.config import DatabaseConfig, GrpcConfig, ManagerConfig, RestConfig
    from dragonfly2_tpu.manager.server import ManagerServer

    cfg = ManagerConfig(
        server=RestConfig(host=args.host, port=args.port),
        grpc=GrpcConfig(host=args.host, port=args.grpc_port),
        database=DatabaseConfig(path=args.db),
        keepalive_timeout=args.keepalive_timeout,
        keepalive_gc_interval=args.keepalive_gc_interval,
        metrics_port=args.metrics_port,
    )

    async def run() -> int:
        server = ManagerServer(cfg)
        import signal

        loop = asyncio.get_running_loop()
        for sig in (signal.SIGINT, signal.SIGTERM):
            loop.add_signal_handler(sig, lambda: asyncio.ensure_future(server.stop()))
        await server.serve()
        return 0

    return asyncio.run(run())


def _add_scheduler(sub: argparse._SubParsersAction) -> None:
    p = sub.add_parser("scheduler", help="run the scheduler control plane")
    p.add_argument("--config", default="", help="YAML config path")
    p.add_argument("--host", default="127.0.0.1")
    p.add_argument("--port", type=int, default=8002)
    p.add_argument("--manager", default="", help="manager drpc addr host:port")
    p.add_argument("--metrics-port", type=int, default=0,
                   help="fixed port for /metrics (0 = ephemeral)")
    p.set_defaults(func=_run_scheduler)


def _run_scheduler(args: argparse.Namespace) -> int:
    from dragonfly2_tpu.scheduler.config import SchedulerConfig
    from dragonfly2_tpu.scheduler.server import SchedulerServer

    if args.config:
        cfg = SchedulerConfig.load(args.config)
    else:
        cfg = SchedulerConfig()
    cfg.server.host = args.host
    cfg.server.port = args.port
    if args.manager:
        cfg.manager_addr = args.manager
    if args.metrics_port:
        cfg.metrics_port = args.metrics_port

    async def run() -> int:
        server = SchedulerServer(cfg)
        import signal

        loop = asyncio.get_running_loop()
        for sig in (signal.SIGINT, signal.SIGTERM):
            loop.add_signal_handler(sig, lambda: asyncio.ensure_future(server.stop()))
        await server.serve()
        return 0

    return asyncio.run(run())
