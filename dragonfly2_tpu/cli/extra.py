"""Additional CLI subcommands registered as stages land."""

from __future__ import annotations

import argparse
import asyncio


def register(sub: argparse._SubParsersAction) -> None:
    _add_scheduler(sub)
    _add_manager(sub)


def _add_manager(sub: argparse._SubParsersAction) -> None:
    p = sub.add_parser("manager", help="run the manager global control plane")
    p.add_argument("--host", default="127.0.0.1")
    p.add_argument("--port", type=int, default=8080, help="REST port")
    p.add_argument("--grpc-port", type=int, default=65003, help="drpc port")
    p.add_argument("--db", default=":memory:", help="sqlite path (default in-memory)")
    p.set_defaults(func=_run_manager)


def _run_manager(args: argparse.Namespace) -> int:
    from dragonfly2_tpu.manager.config import DatabaseConfig, GrpcConfig, ManagerConfig, RestConfig
    from dragonfly2_tpu.manager.server import ManagerServer

    cfg = ManagerConfig(
        server=RestConfig(host=args.host, port=args.port),
        grpc=GrpcConfig(host=args.host, port=args.grpc_port),
        database=DatabaseConfig(path=args.db),
    )

    async def run() -> int:
        server = ManagerServer(cfg)
        import signal

        loop = asyncio.get_running_loop()
        for sig in (signal.SIGINT, signal.SIGTERM):
            loop.add_signal_handler(sig, lambda: asyncio.ensure_future(server.stop()))
        await server.serve()
        return 0

    return asyncio.run(run())


def _add_scheduler(sub: argparse._SubParsersAction) -> None:
    p = sub.add_parser("scheduler", help="run the scheduler control plane")
    p.add_argument("--config", default="", help="YAML config path")
    p.add_argument("--host", default="127.0.0.1")
    p.add_argument("--port", type=int, default=8002)
    p.add_argument("--manager", default="", help="manager drpc addr host:port")
    p.set_defaults(func=_run_scheduler)


def _run_scheduler(args: argparse.Namespace) -> int:
    from dragonfly2_tpu.scheduler.config import SchedulerConfig
    from dragonfly2_tpu.scheduler.server import SchedulerServer

    if args.config:
        cfg = SchedulerConfig.load(args.config)
    else:
        cfg = SchedulerConfig()
    cfg.server.host = args.host
    cfg.server.port = args.port
    if args.manager:
        cfg.manager_addr = args.manager

    async def run() -> int:
        server = SchedulerServer(cfg)
        import signal

        loop = asyncio.get_running_loop()
        for sig in (signal.SIGINT, signal.SIGTERM):
            loop.add_signal_handler(sig, lambda: asyncio.ensure_future(server.stop()))
        await server.serve()
        return 0

    return asyncio.run(run())
