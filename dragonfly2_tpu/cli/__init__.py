"""CLI entry points (reference: cmd/{dfget,dfcache,dfstore,scheduler,manager})."""
