"""``df`` multi-command CLI.

Reference: cmd/ — one cobra binary per role; we expose one Python entry with
subcommands: dfget, daemon, scheduler, manager, dfcache, dfstore.
``python -m dragonfly2_tpu.cli.main <cmd> ...`` or the ``df`` console script.
"""

from __future__ import annotations

import argparse
import asyncio
import os
import subprocess
import sys
import time

from dragonfly2_tpu.pkg import dflog
from dragonfly2_tpu.pkg.dfpath import Dfpath
from dragonfly2_tpu.pkg.types import format_size

log = dflog.get("cli")


def _add_dfget(sub: argparse._SubParsersAction) -> None:
    p = sub.add_parser("dfget", help="download a file through the P2P fabric")
    p.add_argument("url", help="source URL (http/https/file/gs)")
    p.add_argument("-O", "--output", default="",
                   help="output path (optional with --device tpu)")
    p.add_argument("--device", default="", choices=["", "tpu"],
                   help="also land verified pieces into the daemon's TPU "
                        "HBM sink (requires tpu_sink.enabled in the daemon)")
    p.add_argument("--tag", default="", help="task isolation tag")
    p.add_argument("--application", default="")
    p.add_argument("--tenant", default="",
                   help="QoS attribution tag: every byte this download "
                        "moves is accounted (and rate-shared) under this "
                        "tenant; burning tenants get deprioritized")
    p.add_argument("--priority", type=int, default=3,
                   help="QoS priority 0-6 (>=5 interactive, 3-4 normal, "
                        "<=2 background) — sets the weighted-fair "
                        "dispatch class on every daemon on the path")
    p.add_argument("--digest", default="", help="expected digest algo:hex")
    p.add_argument("--filter", default="", help="'&'-separated query params to ignore")
    p.add_argument("--range", dest="range_", default="", help="byte range a-b")
    p.add_argument("--header", action="append", default=[], help="k:v (repeatable)")
    p.add_argument("--disable-back-source", action="store_true")
    p.add_argument("--pod-broadcast", action="store_true",
                   help="register as a striped slice broadcast: each "
                        "same-slice host DCN-pulls 1/S of the pieces and "
                        "the slice completes the copy internally")
    p.add_argument("--delta-base", default="",
                   help="task id of a locally-landed base version: chunks "
                        "the base already holds are copied (and verified) "
                        "locally, only changed chunks cross the wire as "
                        "ranged P2P tasks (checkpoint-delta plane)")
    p.add_argument("--explain", action="store_true",
                   help="after the download, print the flight recorder's "
                        "critical-path autopsy (phase breakdown + per-piece "
                        "waterfall) — where the wall time went")
    p.add_argument("--pod", action="store_true",
                   help="also fetch the scheduler's merged cross-host pod "
                        "timeline for this task (clock-aligned per-host "
                        "phase bars, slowest host named) — the same "
                        "waterfall /debug/pod/<task_id>/timeline?format="
                        "text renders")
    p.add_argument("--cluster", action="store_true",
                   help="with --explain and --manager: also fetch and "
                        "print the manager's merged cluster control-tower "
                        "view (per-scheduler fleet rollup, stragglers "
                        "attributed to their owning scheduler) — the same "
                        "view /debug/cluster?format=text renders")
    p.add_argument("--recursive", action="store_true")
    p.add_argument("--level", type=int, default=5, help="recursion depth")
    p.add_argument("--timeout", type=float, default=0.0)
    p.add_argument("--work-home", default="")
    p.add_argument("--no-daemon", action="store_true", help="never spawn a daemon")
    p.add_argument("--scheduler", action="append", default=[],
                   help="scheduler host:port handed to an auto-spawned "
                        "daemon (repeatable) — a cold host joins the P2P "
                        "fabric on first dfget (reference "
                        "cmd/dfget/cmd/root.go:251-340)")
    p.add_argument("--manager", default="",
                   help="manager drpc host:port for the auto-spawned daemon")
    p.set_defaults(func=_run_dfget)


def _run_dfget(args: argparse.Namespace) -> int:
    from dragonfly2_tpu.client import dfget as dfget_lib
    from dragonfly2_tpu.proto.common import UrlMeta

    path = Dfpath(args.work_home) if args.work_home else Dfpath()
    header = {}
    for h in args.header:
        k, _, v = h.partition(":")
        header[k.strip()] = v.strip()
    meta = UrlMeta(digest=args.digest, tag=args.tag, filter=args.filter,
                   application=args.application, header=header,
                   range=args.range_, priority=args.priority,
                   tenant=args.tenant)
    cfg = dfget_lib.DfgetConfig(
        url=args.url,
        output=args.output,
        daemon_sock=path.daemon_sock,
        meta=meta,
        disable_back_source=args.disable_back_source,
        recursive=args.recursive,
        level=args.level,
        timeout=args.timeout,
        device=args.device,
        pod_broadcast=args.pod_broadcast,
        explain=args.explain,
        pod=args.pod,
        delta_base=args.delta_base,
    )
    if not args.output and args.device != "tpu":
        sys.stderr.write("dfget: error: -O/--output is required "
                         "(optional only with --device tpu)\n")
        return 2

    async def run() -> int:
        if not args.no_daemon and not await dfget_lib.is_daemon_alive(path.daemon_sock):
            _spawn_daemon(path, device_sink=(args.device == "tpu"),
                          schedulers=args.scheduler, manager=args.manager)
            await _wait_daemon(path.daemon_sock)
        start = time.monotonic()
        state = {"last": 0}

        def on_progress(msg: dict) -> None:
            if msg.get("state") != "running":
                return
            done = msg.get("completed_length", 0)
            total = msg.get("content_length", -1)
            if done - state["last"] >= (8 << 20) or done == total:
                state["last"] = done
                pct = f"{100 * done / total:5.1f}%" if total > 0 else "  ?  "
                sys.stderr.write(f"\r{pct} {format_size(done)}")
                sys.stderr.flush()

        try:
            result = await dfget_lib.download(cfg, on_progress)
        finally:
            # One-shot process: close any source-fallback session pool
            # cleanly instead of leaking it to interpreter exit.
            from dragonfly2_tpu.source.client import default_registry

            await default_registry().close_all()
        elapsed = time.monotonic() - start
        size = result.get("completed_length", 0)
        rate = size / elapsed if elapsed > 0 else 0
        sys.stderr.write(
            f"\rdownloaded {format_size(size)} in {elapsed:.2f}s "
            f"({format_size(int(rate))}/s) task={result.get('task_id', '')[:16]} "
            f"reuse={result.get('from_reuse', False)} p2p={result.get('from_p2p', False)}"
            + (f" device_verified={result.get('device_verified', False)}"
               if cfg.device else "") + "\n"
        )
        flight_info = result.get("flight") or {}
        if args.explain and flight_info.get("text"):
            from dragonfly2_tpu import qos

            sys.stderr.write(
                f"qos: tenant={qos.normalize_tenant(args.tenant)} "
                f"class={qos.class_of(args.priority)} "
                f"(priority={args.priority})\n")
            sys.stderr.write(flight_info["text"] + "\n")
        pod_info = result.get("pod") or {}
        if args.pod and pod_info.get("text"):
            sys.stderr.write(pod_info["text"] + "\n")
        if args.cluster:
            if not args.manager:
                sys.stderr.write("dfget: --cluster needs --manager "
                                 "host:port\n")
            else:
                try:
                    from dragonfly2_tpu.manager.client import ManagerClient
                    from dragonfly2_tpu.pkg.types import NetAddr

                    mhost, _, mport = args.manager.rpartition(":")
                    mc = ManagerClient(NetAddr.tcp(mhost, int(mport)))
                    try:
                        view = await mc.cluster_view()
                    finally:
                        await mc.close()
                    sys.stderr.write(view.get("text", "") + "\n")
                except Exception as e:
                    sys.stderr.write(f"dfget: cluster view unavailable: "
                                     f"{e}\n")
        return 0

    try:
        return asyncio.run(run())
    except Exception as e:
        sys.stderr.write(f"\ndfget: error: {e}\n")
        return 1


def _spawn_daemon(path: Dfpath, *, device_sink: bool = False,
                  schedulers: list | None = None, manager: str = "") -> None:
    """Fork a daemon like dfget does (reference cmd/dfget/cmd/root.go:313).
    Scheduler/manager addresses thread through so a COLD host's first
    dfget joins the P2P fabric, not just a local-cache daemon."""
    path.ensure()
    cmd = [sys.executable, "-m", "dragonfly2_tpu.cli.main", "daemon",
           "--work-home", path.root]
    for addr in schedulers or []:
        cmd += ["--scheduler", addr]
    if manager:
        cmd += ["--manager", manager]
    if device_sink:
        cmd.append("--device-sink")
    with open(os.path.join(path.log_dir, "daemon-spawn.log"), "ab") as logf:
        subprocess.Popen(cmd, stdout=logf, stderr=logf,
                         start_new_session=True, close_fds=True)
    log.info("spawned daemon", work_home=path.root)


async def _wait_daemon(sock: str, timeout: float = 15.0) -> None:
    from dragonfly2_tpu.client.dfget import is_daemon_alive

    deadline = time.monotonic() + timeout
    while time.monotonic() < deadline:
        if await is_daemon_alive(sock):
            return
        await asyncio.sleep(0.1)
    raise RuntimeError(f"daemon did not come up on {sock} within {timeout}s")


def _add_daemon(sub: argparse._SubParsersAction) -> None:
    p = sub.add_parser("daemon", help="run the peer daemon (dfdaemon)")
    p.add_argument("--config", default="", help="YAML config path")
    p.add_argument("--work-home", default="")
    p.add_argument("--seed-peer", action="store_true")
    p.add_argument("--scheduler", action="append", default=[],
                   help="scheduler host:port (repeatable)")
    p.add_argument("--manager", default="",
                   help="manager drpc host:port (dynconfig scheduler resolution)")
    p.add_argument("--proxy-port", type=int, default=-1,
                   help="enable the HTTP proxy on this port (0 = ephemeral)")
    p.add_argument("--registry-mirror", default="",
                   help="remote registry URL to mirror through the proxy")
    p.add_argument("--alive-time", type=float, default=0.0)
    p.add_argument("--object-storage-port", type=int, default=-1,
                   help="enable the S3-like object gateway on this port (0 = ephemeral)")
    p.add_argument("--object-storage-backend", default="fs",
                   help="fs | s3 | gcs | oss | obs")
    p.add_argument("--object-storage-option", action="append", default=[],
                   help="backend kwarg k=v (repeatable), e.g. root=/data/buckets")
    p.add_argument("--pex-port", type=int, default=-1,
                   help="enable gossip peer exchange on this UDP port (0 = ephemeral)")
    p.add_argument("--pex-seed", action="append", default=[],
                   help="PEX bootstrap host:port (repeatable)")
    p.add_argument("--pex-secret", default="",
                   help="shared HMAC secret for gossip datagrams")
    p.add_argument("--prefetch", action="store_true",
                   help="ranged-request misses also prefetch the whole task")
    p.add_argument("--hijack-https", action="store_true",
                   help="TLS-intercept CONNECT tunnels with a CA-forged cert")
    p.add_argument("--device-sink", action="store_true",
                   help="enable the TPU HBM sink (tasks with --device tpu "
                        "land verified pieces in device memory)")
    p.add_argument("--metrics-port", type=int, default=0,
                   help="fixed port for /metrics + /debug endpoints "
                        "(0 = ephemeral, -1 = disabled)")
    p.add_argument("--piece-concurrency", type=int, default=0,
                   help="concurrent origin range streams for back-to-source "
                        "(0 = config default; caps origin request fan-in)")
    p.add_argument("--tpu-slice", default="",
                   help="ICI domain label for this host (e.g. slice-3); "
                        "the scheduler prefers parents inside the same "
                        "slice lexicographically")
    p.add_argument("--tpu-worker-index", type=int, default=-1,
                   help="worker index within the slice")
    p.add_argument("--hostname", default="",
                   help="override this daemon's advertised hostname "
                        "(multi-daemon-per-machine tests; the host id is "
                        "hostname-port)")
    p.add_argument("--clock-offset", type=float, default=0.0,
                   help="chaos/test knob: skew every wall stamp this "
                        "daemon reports by this many seconds — the "
                        "scheduler's clock alignment must recover it")
    p.set_defaults(func=_run_daemon)


def _run_daemon(args: argparse.Namespace) -> int:
    from dragonfly2_tpu.daemon.config import DaemonConfig
    from dragonfly2_tpu.daemon.daemon import Daemon

    if args.config:
        cfg = DaemonConfig.load(args.config)
    else:
        cfg = DaemonConfig()
    if args.work_home:
        cfg.work_home = args.work_home
        cfg.__post_init__()
    if args.seed_peer:
        cfg.seed_peer = True
    if args.scheduler:
        cfg.scheduler.addrs = args.scheduler
    if args.manager:
        cfg.manager_addr = args.manager
    if args.proxy_port >= 0:
        cfg.proxy.enabled = True
        cfg.proxy.port = args.proxy_port
    if args.registry_mirror:
        cfg.proxy.enabled = True
        cfg.proxy.registry_mirror = args.registry_mirror
    if args.alive_time:
        cfg.alive_time = args.alive_time
    if args.tpu_slice:
        cfg.host.tpu_slice = args.tpu_slice
    if args.tpu_worker_index >= 0:
        cfg.host.tpu_worker_index = args.tpu_worker_index
    if args.hostname:
        cfg.host.hostname = args.hostname
    if args.clock_offset:
        cfg.clock_offset_s = args.clock_offset
    if args.object_storage_port >= 0:
        cfg.object_storage.enabled = True
        cfg.object_storage.port = args.object_storage_port
        cfg.object_storage.backend = args.object_storage_backend
        opts = dict(kv.split("=", 1) for kv in args.object_storage_option if "=" in kv)
        if args.object_storage_backend == "fs" and "root" not in opts:
            import os

            opts["root"] = os.path.join(cfg.work_home or ".", "buckets")
        cfg.object_storage.backend_options = opts
    if args.pex_port >= 0 or args.pex_seed:
        cfg.pex.enabled = True
        if args.pex_port >= 0:
            cfg.pex.port = args.pex_port
        cfg.pex.seeds = args.pex_seed
    if args.pex_secret:
        cfg.pex.secret = args.pex_secret
    if args.prefetch:
        cfg.download.prefetch = True
    if args.device_sink:
        cfg.tpu_sink.enabled = True
    if args.metrics_port:
        cfg.metrics_port = args.metrics_port
    if args.piece_concurrency > 0:
        cfg.download.piece_concurrency = args.piece_concurrency
    if args.hijack_https:
        cfg.proxy.enabled = True
        cfg.proxy.hijack_https = True

    async def run() -> int:
        daemon = Daemon(cfg)
        import signal

        loop = asyncio.get_running_loop()
        for sig in (signal.SIGINT, signal.SIGTERM):
            loop.add_signal_handler(sig, lambda: asyncio.ensure_future(daemon.stop()))
        await daemon.serve()
        return 0

    return asyncio.run(run())


def main(argv: list[str] | None = None) -> int:
    parser = argparse.ArgumentParser(prog="df", description="TPU-native P2P content fabric")
    sub = parser.add_subparsers(dest="command", required=True)
    _add_dfget(sub)
    _add_daemon(sub)
    # scheduler/manager/dfcache/dfstore subcommands are registered as those
    # stages land (SURVEY.md §7 build order).
    try:
        from dragonfly2_tpu.cli import extra

        extra.register(sub)
    except ImportError:
        pass
    args = parser.parse_args(argv)
    return args.func(args)


if __name__ == "__main__":
    sys.exit(main())
