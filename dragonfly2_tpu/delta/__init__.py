"""Checkpoint-delta distribution plane.

Content-defined chunking + chunk manifests + a delta resolver so version
N+1 of a checkpoint re-transfers only the chunks that actually changed;
everything else is copied locally out of the landed version N
(digest-verified during the copy). See docs/ARCHITECTURE.md
"Checkpoint delta plane".
"""

from dragonfly2_tpu.delta.chunker import CDCParams, Chunk, GearChunker, chunk_bytes
from dragonfly2_tpu.delta.manifest import (
    DeltaManifest,
    ManifestError,
    build_manifest,
    fetch_or_build_manifest,
    manifest_from_store,
    manifest_object_key,
)
from dragonfly2_tpu.delta.resolver import (
    DeltaPlan,
    fetch_manifest,
    manifest_url,
    plan_delta,
    publish_manifest_for,
    run_delta_task,
)

__all__ = [
    "CDCParams", "Chunk", "GearChunker", "chunk_bytes",
    "DeltaManifest", "ManifestError", "build_manifest",
    "fetch_or_build_manifest", "manifest_from_store", "manifest_object_key",
    "DeltaPlan", "fetch_manifest", "manifest_url", "plan_delta",
    "publish_manifest_for", "run_delta_task",
]
