"""Chunk manifests: the shippable description of one content version.

A manifest is the CDC chunk list ``{offset, length, sha256}`` over one
content version plus the chunking geometry that produced it (two hosts
can only dedup against each other when their manifests agree on
params). Manifests are small (a 70B-scale shard is ~10k chunks, ~1 MB of
JSON) and are themselves cached as P2P objects so the chunk walk runs
once per version, not once per host:

  * object-gateway surface: ``.dfdelta/<key>.json`` beside the object,
    ``fetch_or_build_manifest`` — the exact ``.dfidx`` pattern from the
    dataset plane (dataset/tar_index.py::fetch_or_build_index);
  * fabric surface: published as a ``dfdelta://<task_id>`` P2P task
    keyed by the content task id (delta/resolver.py).
"""

from __future__ import annotations

import json
from dataclasses import dataclass, field

from dragonfly2_tpu.delta.chunker import CDCParams, Chunk, GearChunker
from dragonfly2_tpu.pkg import dflog, metrics

log = dflog.get("delta.manifest")

MANIFEST_VERSION = 1
# Hidden bucket prefix for gateway-cached manifests (same bucket as the
# content so ACL/lifecycle follow it; same discipline as INDEX_PREFIX).
MANIFEST_PREFIX = ".dfdelta/"

MANIFEST_FETCHES = metrics.counter(
    "peer_delta_manifest_total",
    "Delta manifest resolutions by outcome", ("result",))


class ManifestError(Exception):
    """Malformed or inconsistent chunk manifest."""


@dataclass
class DeltaManifest:
    """One content version's chunk map. ``name`` is the object key or
    URL it describes (informational); identity is carried by where the
    manifest is cached (object key / task id)."""

    name: str
    content_length: int
    chunks: list[Chunk]
    params: CDCParams = field(default_factory=CDCParams)
    version: int = MANIFEST_VERSION

    @property
    def num_chunks(self) -> int:
        return len(self.chunks)

    def digest_map(self) -> dict[str, Chunk]:
        """sha256 hex -> chunk (first occurrence wins; duplicate content
        chunks are interchangeable by construction)."""
        out: dict[str, Chunk] = {}
        for c in self.chunks:
            out.setdefault(c.sha256, c)
        return out

    def validate(self) -> None:
        """Chunks must exactly tile [0, content_length)."""
        off = 0
        for c in self.chunks:
            if c.offset != off or c.length <= 0:
                raise ManifestError(
                    f"chunk at {c.offset} breaks tiling (expected {off})")
            off = c.end
        if off != self.content_length:
            raise ManifestError(
                f"chunks cover {off}B of {self.content_length}B content")

    # -- serialization (the P2P-cached form) -------------------------------

    def to_json_bytes(self) -> bytes:
        doc = {
            "v": self.version,
            "name": self.name,
            "size": self.content_length,
            "params": [self.params.mask_bits, self.params.min_size,
                       self.params.max_size],
            "chunks": [[c.offset, c.length, c.sha256] for c in self.chunks],
        }
        return json.dumps(doc, separators=(",", ":")).encode()

    @classmethod
    def from_json_bytes(cls, raw: bytes) -> "DeltaManifest":
        try:
            doc = json.loads(raw)
            if doc["v"] != MANIFEST_VERSION:
                raise ManifestError(
                    f"manifest version {doc['v']} unsupported")
            bits, mn, mx = doc["params"]
            m = cls(
                name=doc["name"], content_length=int(doc["size"]),
                chunks=[Chunk(int(o), int(n), str(s))
                        for o, n, s in doc["chunks"]],
                params=CDCParams(mask_bits=int(bits), min_size=int(mn),
                                 max_size=int(mx)))
        except ManifestError:
            raise
        except Exception as e:
            raise ManifestError(f"corrupt delta manifest: {e}") from e
        m.validate()
        return m


def build_manifest(data: bytes, name: str = "",
                   params: CDCParams | None = None) -> DeltaManifest:
    """Manifest of in-memory content."""
    ch = GearChunker(params)
    ch.feed(data)
    ch.finish()
    return DeltaManifest(name=name, content_length=len(data),
                         chunks=ch.chunks, params=ch.params)


def manifest_from_store(store, name: str = "",
                        params: CDCParams | None = None,
                        span: int = 8 << 20) -> DeltaManifest:
    """Manifest of a COMPLETED local task store: bounded pooled reads fed
    through the streaming chunker (never the whole content in memory).
    Runs CPU hashing — callers on an event loop wrap it in to_thread."""
    from dragonfly2_tpu.storage.local_store import (
        acquire_read_buffer,
        release_read_buffer,
    )

    total = store.metadata.content_length
    if total < 0:
        raise ManifestError(
            f"task {store.metadata.task_id[:16]} has unknown length")
    ch = GearChunker(params)
    with store:
        buf = acquire_read_buffer(span)
        try:
            off = 0
            while off < total:
                take = min(span, total - off)
                store.read_into(off, take, buf)
                ch.feed(bytes(buf[:take]))
                off += take
        finally:
            release_read_buffer(buf)
    ch.finish()
    return DeltaManifest(name=name or store.metadata.url,
                         content_length=total, chunks=ch.chunks,
                         params=ch.params)


# -- gateway-cached manifest lifecycle (the .dfidx pattern) ----------------

def manifest_object_key(key: str) -> str:
    return f"{MANIFEST_PREFIX}{key}.json"


async def fetch_or_build_manifest(store, bucket: str, key: str, *,
                                  params: CDCParams | None = None,
                                  publish: bool = True) -> DeltaManifest:
    """The pod-wide manifest contract over the object gateway: try the
    cached manifest object first (chunked once, fetched everywhere); on
    miss, stream the object ONE pass through the chunker and publish the
    result back (best effort; racing builders converge on identical
    bytes). A cached manifest whose recorded size disagrees with the
    object's current length is stale and rebuilt."""
    from dragonfly2_tpu.client.dfstore import DfstoreError

    meta = await store.stat_object(bucket, key)    # missing object raises
    try:
        raw = await store.get_object(bucket, manifest_object_key(key))
        m = DeltaManifest.from_json_bytes(raw)
        if m.content_length == meta.content_length and (
                params is None or m.params == params):
            MANIFEST_FETCHES.labels("hit").inc()
            return m
        log.info("cached delta manifest stale; rebuilding", key=key,
                 cached=m.content_length, actual=meta.content_length)
        MANIFEST_FETCHES.labels("stale").inc()
    except DfstoreError:
        pass
    except ManifestError as e:
        log.warning("cached delta manifest corrupt; rebuilding",
                    key=key, error=str(e)[:200])
        MANIFEST_FETCHES.labels("corrupt").inc()
    ch = GearChunker(params)
    async for chunk in await store.stream_object(bucket, key):
        ch.feed(chunk)
    ch.finish()
    m = DeltaManifest(name=key, content_length=ch.consumed,
                      chunks=ch.chunks, params=ch.params)
    m.validate()
    MANIFEST_FETCHES.labels("built").inc()
    if publish:
        try:
            await store.put_object(bucket, manifest_object_key(key),
                                   m.to_json_bytes())
        except DfstoreError as e:
            log.warning("delta manifest publish failed (non-fatal)",
                        key=key, error=str(e)[:200])
    return m
