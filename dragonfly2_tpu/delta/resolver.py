"""Delta resolver: land version N+1 by copying version N locally.

Given a locally-landed base task (version N) and version N+1's chunk
manifest, partition N+1's chunks into *reused* (same sha256 present
anywhere in the base — copied out of the base store through the pooled
read engine, digest verified DURING the copy) and *fetched* (pulled as
ranged P2P tasks, one per coalesced span, byte-identical task ids across
every host running the same delta so the fabric dedups per span). The
patched result lands as a completely normal task: piece-structured
store, verified end digest, announced to the scheduler, served to other
peers, resumable (already-landed pieces are skipped on retry).

Manifests travel over the fabric itself: ``dfdelta://<content_task_id>``
is a tiny P2P task (keyed by the content's task id) that any host
holding the full content can build and publish — the first host to land
a version cold publishes its manifest, every later host deltas.

Accounting invariant (pinned by bench + e2e):
``peer_delta_bytes_total{kind=reused} + {kind=fetched}`` over one task
equals the content length EXACTLY — every byte is attributed to exactly
one transfer class, and a corrupt base chunk re-fetches under
``fetched`` (plus a ``corrupt_base`` chunk count), never double-books.
"""

from __future__ import annotations

import asyncio
import hashlib
import os
import time
from dataclasses import dataclass, field

from dragonfly2_tpu.delta.chunker import CDCParams, Chunk
from dragonfly2_tpu.delta.manifest import (
    MANIFEST_FETCHES,
    DeltaManifest,
    ManifestError,
    manifest_from_store,
)
from dragonfly2_tpu.pkg import dflog, metrics
from dragonfly2_tpu.pkg import flight as flightlib
from dragonfly2_tpu.pkg.errors import Code, DfError, StorageError, describe
from dragonfly2_tpu.pkg.piece import compute_piece_count, compute_piece_size
from dragonfly2_tpu.storage.local_store import (
    acquire_read_buffer,
    release_read_buffer,
)

log = dflog.get("delta.resolver")

# The accounting yardstick: every content byte of a delta task lands as
# exactly one of these.
DELTA_BYTES = metrics.counter(
    "peer_delta_bytes_total",
    "Delta-task content bytes by transfer class (reused = copied from "
    "the local base version, fetched = pulled as ranged P2P tasks); the "
    "two sum exactly to the task's content length", ("kind",))
DELTA_CHUNKS = metrics.counter(
    "peer_delta_chunks_total",
    "Delta-task chunks by resolution (corrupt_base = base copy failed "
    "its digest during the copy and was transparently re-fetched)",
    ("result",))

# URL scheme of fabric-published manifests: task id of the manifest task
# is a pure function of the CONTENT task id, so every host resolves the
# same manifest task without origin cooperation.
MANIFEST_SCHEME = "dfdelta"
MANIFEST_TAG = "dfdelta-manifest"


def manifest_url(content_task_id: str) -> str:
    return f"{MANIFEST_SCHEME}://{content_task_id}"


@dataclass
class DeltaPlan:
    """Partition of the new version's chunks against a base manifest."""

    reused: list[tuple[Chunk, Chunk]] = field(default_factory=list)  # (new, base)
    fetched: list[Chunk] = field(default_factory=list)

    @property
    def reused_bytes(self) -> int:
        return sum(c.length for c, _ in self.reused)

    @property
    def fetched_bytes(self) -> int:
        return sum(c.length for c in self.fetched)

    def fetch_spans(self) -> list[tuple[int, int]]:
        """ADJACENT fetched chunks coalesced into ranged-task spans.
        Only zero-gap merges: a gap byte is a reused byte, and reused
        bytes must never ride the wire (the accounting invariant)."""
        spans: list[list[int]] = []
        for c in self.fetched:
            if spans and c.offset == spans[-1][1]:
                spans[-1][1] = c.end
            else:
                spans.append([c.offset, c.end])
        return [(s, e) for s, e in spans]


def plan_delta(new_m: DeltaManifest, base_m: DeltaManifest) -> DeltaPlan:
    """Chunk-level dedup: a new chunk whose (sha256, length) appears
    anywhere in the base is reused from there; everything else is
    fetched. Pure function — both manifests must share chunking params
    (callers rebuild the base manifest otherwise)."""
    if new_m.params != base_m.params:
        raise ManifestError(
            f"chunking params differ: {new_m.params} vs {base_m.params}")
    base_map = base_m.digest_map()
    plan = DeltaPlan()
    for c in new_m.chunks:
        b = base_map.get(c.sha256)
        if b is not None and b.length == c.length:
            plan.reused.append((c, b))
        else:
            plan.fetched.append(c)
    return plan


# ------------------------------------------------------------------ #
# Fabric-published manifests (dfdelta:// tasks)
# ------------------------------------------------------------------ #

def _manifest_request(content_task_id: str):
    from dragonfly2_tpu.daemon.peer.task_manager import FileTaskRequest
    from dragonfly2_tpu.proto.common import UrlMeta

    return FileTaskRequest(
        url=manifest_url(content_task_id), output="",
        meta=UrlMeta(tag=MANIFEST_TAG),
        # dfdelta:// has no origin; the manifest either exists in the
        # fabric or it doesn't.
        disable_back_source=True)


async def fetch_manifest(tm, content_task_id: str,
                         timeout: float = 8.0) -> DeltaManifest | None:
    """Pull the fabric-published manifest for a content task id; None
    when no host has published one (callers fall back to a full
    download, after which they publish it themselves). The timeout is
    deliberately short: an unpublished manifest costs the scheduler's
    full no-source patience before failing, and every miss has a cheap
    recovery (build locally / plain download)."""
    req = _manifest_request(content_task_id)

    async def _drain():
        final = None
        async for p in tm.start_file_task(req):
            if p.state == "failed":
                return None
            if p.state == "done":
                final = p
        return final

    try:
        # wait_for, not asyncio.timeout: this runs on 3.10 too.
        final = await asyncio.wait_for(_drain(), timeout)
    except (DfError, asyncio.TimeoutError):
        MANIFEST_FETCHES.labels("miss").inc()
        return None
    if final is None:
        MANIFEST_FETCHES.labels("miss").inc()
        return None
    store = tm.storage.find_completed_task(final.task_id)
    if store is None:
        return None
    n = store.metadata.content_length
    buf = acquire_read_buffer(n)
    try:
        with store:
            await asyncio.to_thread(store.read_into, 0, n, buf)
        m = DeltaManifest.from_json_bytes(bytes(buf[:n]))
    except ManifestError as e:
        log.warning("fabric manifest corrupt; ignoring",
                    task=content_task_id[:16], error=str(e)[:200])
        MANIFEST_FETCHES.labels("corrupt").inc()
        return None
    finally:
        release_read_buffer(buf)
    MANIFEST_FETCHES.labels("hit").inc()
    return m


async def publish_manifest_for(tm, content_task_id: str, *,
                               params: CDCParams | None = None,
                               manifest: DeltaManifest | None = None,
                               ) -> DeltaManifest | None:
    """Build the manifest from THIS host's completed copy of the content
    (or take a prebuilt one) and import it as the ``dfdelta://`` task
    (announced to the scheduler like any dfcache import, so peers can
    pull it). Idempotent: an already-published manifest task is reused.
    Returns the manifest, or None when the content is not landed here."""
    store = tm.storage.find_completed_task(content_task_id)
    if store is None:
        log.warning("cannot publish manifest: content not landed",
                    task=content_task_id[:16])
        return None
    m = manifest
    if m is None:
        m = await asyncio.to_thread(manifest_from_store, store,
                                    store.metadata.url, params)
    req = _manifest_request(content_task_id)
    if tm.storage.find_completed_task(req.task_id()) is not None:
        MANIFEST_FETCHES.labels("published").inc()
        return m
    path = os.path.join(tm.storage.opt.data_dir,
                        f".manifest-{content_task_id[:16]}.json")
    try:
        data = m.to_json_bytes()
        await asyncio.to_thread(_write_file, path, data)
        await tm.import_task(path, req)
    finally:
        try:
            os.unlink(path)
        except OSError:
            pass
    MANIFEST_FETCHES.labels("published").inc()
    return m


def _write_file(path: str, data: bytes) -> None:
    with open(path, "wb") as f:
        f.write(data)


# ------------------------------------------------------------------ #
# The delta landing engine
# ------------------------------------------------------------------ #

class _SpanFetches:
    """Concurrent ranged-task pulls of the fetch spans, bounded, with
    per-span buffers released after the last consuming chunk."""

    def __init__(self, fetcher, spans: list[tuple[int, int]],
                 consumers: dict[tuple[int, int], int],
                 concurrency: int = 4):
        self.fetcher = fetcher
        self._bufs: dict[tuple[int, int], memoryview] = {}
        self._remaining = dict(consumers)
        self._sem = asyncio.Semaphore(concurrency)
        self._tasks = {
            span: asyncio.ensure_future(self._pull(span)) for span in spans}

    async def _pull(self, span: tuple[int, int]) -> memoryview:
        s, e = span
        buf = acquire_read_buffer(e - s)
        try:
            async with self._sem:
                await self.fetcher.fetch_into(s, e, buf[:e - s])
        except BaseException:
            release_read_buffer(buf)
            raise
        self._bufs[span] = buf
        return buf

    async def view(self, span: tuple[int, int]) -> memoryview:
        buf = await self._tasks[span]
        s, e = span
        return buf[:e - s]

    def consumed(self, span: tuple[int, int]) -> None:
        self._remaining[span] -= 1
        if self._remaining[span] <= 0:
            buf = self._bufs.pop(span, None)
            if buf is not None:
                release_read_buffer(buf)

    async def close(self) -> None:
        for t in self._tasks.values():
            t.cancel()
        await asyncio.gather(*self._tasks.values(), return_exceptions=True)
        for buf in self._bufs.values():
            release_read_buffer(buf)
        self._bufs.clear()


def _range_fetcher(tm, req):
    """Ranged-task fetcher for the delta spans: the dataset plane's
    DaemonRangeFetcher, parameterized so span task ids agree across
    every host running the same delta (tag/application/header ride
    along; the whole-content digest is deliberately dropped — it cannot
    name a slice)."""
    from dragonfly2_tpu.dataset.shard_reader import DaemonRangeFetcher

    return DaemonRangeFetcher(
        tm, req.url, tag=req.meta.tag, application=req.meta.application,
        header=dict(req.meta.header), pod_broadcast=req.pod_broadcast)


async def _resolve_manifests(tm, req, task_id: str, base_store, *,
                             params: CDCParams | None):
    """(new_manifest, base_manifest) or None when the delta path is not
    viable (no published manifest for the new version)."""
    new_m = await fetch_manifest(tm, task_id)
    if new_m is None:
        return None
    want = new_m.params
    base_id = base_store.metadata.task_id
    base_m = await fetch_manifest(tm, base_id)
    if (base_m is None or base_m.params != want
            or base_m.content_length != base_store.metadata.content_length):
        base_m = await asyncio.to_thread(
            manifest_from_store, base_store, base_store.metadata.url, want)
        # Publish the freshly-built base manifest (best effort): the
        # next host deltaing from the same base then fabric-fetches it
        # instead of paying the miss patience + a local chunk walk.
        try:
            await publish_manifest_for(tm, base_id, manifest=base_m)
        except Exception as e:
            log.warning("base manifest publish failed (non-fatal)",
                        base=base_id[:16], error=describe(e))
    if params is not None and want != params:
        log.info("delta using published chunk params", task=task_id[:16])
    return new_m, base_m


async def run_delta_task(tm, req, base_task_id: str, *,
                         params: CDCParams | None = None,
                         fetch_concurrency: int = 4):
    """Drive one delta download on a TaskManager; yields
    FileTaskProgress frames exactly like ``start_file_task`` (the
    ``Daemon.Download`` handler streams them verbatim).

    Degradation ladder — every rung lands the bytes:
      1. completed/running task → plain reuse/dedup via start_file_task;
      2. no landed base, or no published manifest, or zero chunk overlap
         → plain full download (then this host best-effort PUBLISHES the
         manifest so the next host deltas);
      3. the delta proper — and inside it, a base chunk that fails its
         digest during the local copy is re-fetched as a ranged task
         (counted ``corrupt_base``), never trusted into the result.
    """
    task_id = req.task_id()

    async def _fallback(publish: bool):
        ok = False
        async for p in tm.start_file_task(req):
            if p.state == "done":
                ok = True
            yield p
        if ok and publish:
            try:
                await publish_manifest_for(tm, task_id, params=params)
            except Exception as e:     # best effort, never fails the task
                log.warning("manifest publish after full landing failed",
                            task=task_id[:16], error=describe(e))

    if (tm.storage.find_completed_task(task_id) is not None
            or tm.is_task_running(task_id)):
        async for p in _fallback(publish=False):
            yield p
        return

    base_store = tm.storage.find_completed_task(base_task_id)
    if base_store is None:
        log.info("delta base not landed; full download",
                 task=task_id[:16], base=base_task_id[:16])
        async for p in _fallback(publish=True):
            yield p
        return
    manifests = await _resolve_manifests(tm, req, task_id, base_store,
                                         params=params)
    if manifests is None:
        log.info("no published manifest; full download + publish",
                 task=task_id[:16])
        async for p in _fallback(publish=True):
            yield p
        return
    new_m, base_m = manifests
    plan = plan_delta(new_m, base_m)
    if plan.reused_bytes == 0:
        log.info("zero chunk overlap with base; full download",
                 task=task_id[:16], base=base_task_id[:16])
        async for p in _fallback(publish=True):
            yield p
        return

    async for p in _run_delta(tm, req, task_id, base_store, new_m, plan,
                              fetch_concurrency):
        yield p


async def _run_delta(tm, req, task_id: str, base_store,
                     new_m: DeltaManifest, plan: DeltaPlan,
                     fetch_concurrency: int):
    from dragonfly2_tpu.daemon.peer.broker import PieceEvent
    from dragonfly2_tpu.daemon.peer.task_manager import (
        TaskStoreMetadata,
        _RunningTask,
    )
    from dragonfly2_tpu.pkg import idgen

    peer_id = req.peer_id or idgen.peer_id_v1(tm.host_ip)
    store = tm.storage.register_task(TaskStoreMetadata(
        task_id=task_id, peer_id=peer_id, url=req.url, tag=req.meta.tag,
        application=req.meta.application, header=dict(req.meta.header)))
    run = _RunningTask(store)
    tm._running[task_id] = run
    store.pin()
    base_store.pin()
    fetches: _SpanFetches | None = None
    stats = {"reused_bytes": 0, "fetched_bytes": 0, "chunks_reused": 0,
             "chunks_fetched": 0, "corrupt_base": 0,
             "chunks_total": new_m.num_chunks,
             "content_length": new_m.content_length}
    log.info("delta landing", task=task_id[:16],
             base=base_store.metadata.task_id[:16],
             chunks=new_m.num_chunks, reuse_frac=round(
                 plan.reused_bytes / max(1, new_m.content_length), 4))
    try:
        tf = tm.flight.task(task_id)
        fetcher = _range_fetcher(tm, req)
        spans = plan.fetch_spans()
        consumers: dict[tuple[int, int], int] = {}
        span_of: dict[int, tuple[int, int]] = {}
        si = 0
        for c in plan.fetched:
            while si < len(spans) and spans[si][1] <= c.offset:
                si += 1
            span_of[c.offset] = spans[si]
            consumers[spans[si]] = consumers.get(spans[si], 0) + 1
        fetches = _SpanFetches(fetcher, spans, consumers,
                               concurrency=fetch_concurrency)

        async for p in _assemble(tm, req, store, base_store, new_m, plan,
                                 fetches, span_of, fetcher, stats, tf,
                                 peer_id):
            yield p
    except DfError as e:
        await _fail(tm, req, store, run, task_id, peer_id, e)
        yield _failed_progress(task_id, peer_id, run.error)
        return
    except Exception as e:     # pragma: no cover - defensive
        log.error("delta task crashed", exc_info=True)
        await _fail(tm, req, store, run, task_id, peer_id,
                    DfError(Code.UnknownError, describe(e)))
        yield _failed_progress(task_id, peer_id, run.error)
        return
    finally:
        if fetches is not None:
            await fetches.close()
        base_store.unpin()
        store.unpin()
        if run.error is None and not store.metadata.done:
            # Generator closed early (client disconnect). The LANDED
            # pieces are digest-verified chunk copies, so the store
            # survives for resume (a retry skips them) — but waiters must
            # see a terminal state.
            run.error = DfError(Code.ClientContextCanceled,
                                "delta download aborted by client")
            tm.flight.finish_task(task_id, "failed", note=str(run.error))
            tm.broker.publish(task_id, PieceEvent([], failed=True))
        run.done.set()
        tm._running.pop(task_id, None)


async def _fail(tm, req, store, run, task_id, peer_id, err: DfError) -> None:
    from dragonfly2_tpu.daemon.peer.broker import PieceEvent

    store.mark_invalid()
    run.error = err
    tm.flight.finish_task(task_id, "failed", note=str(err))
    tm.broker.publish(task_id, PieceEvent([], failed=True))


def _failed_progress(task_id: str, peer_id: str, err: DfError):
    from dragonfly2_tpu.daemon.peer.task_manager import FileTaskProgress

    return FileTaskProgress(state="failed", task_id=task_id,
                            peer_id=peer_id, error=err.to_wire())


async def _assemble(tm, req, store, base_store, new_m: DeltaManifest,
                    plan: DeltaPlan, fetches: _SpanFetches,
                    span_of: dict, fetcher, stats: dict, tf, peer_id: str):
    """Walk the new manifest in offset order, materializing each chunk
    (local verified copy or fetched span slice) into piece-structured
    writes on the target store, then finalize exactly like a downloaded
    task."""
    from dragonfly2_tpu.daemon.peer.broker import PieceEvent
    from dragonfly2_tpu.daemon.peer.task_manager import FileTaskProgress

    total = new_m.content_length
    piece_size = store.metadata.piece_size or compute_piece_size(total)
    store.update_task(content_length=total, piece_size=piece_size,
                      total_piece_count=compute_piece_count(
                          total, piece_size))
    base_of = {c.offset: b for c, b in plan.reused}

    piece_buf = acquire_read_buffer(piece_size)
    chunk_buf = acquire_read_buffer(new_m.params.max_size)
    last_progress = 0.0
    try:
        piece_num = 0
        piece_fill = 0
        pos = 0                          # absolute content position
        for c in new_m.chunks:
            view = await _chunk_bytes(tm, req, c, base_of, base_store,
                                      fetches, span_of, fetcher, chunk_buf,
                                      stats, tf)
            # Copy the chunk into the piece grid (a chunk can straddle
            # many pieces and vice versa).
            off = 0
            while off < c.length:
                take = min(c.length - off, piece_size - piece_fill)
                piece_buf[piece_fill:piece_fill + take] = \
                    view[off:off + take]
                piece_fill += take
                off += take
                pos += take
                if piece_fill == piece_size or pos == total:
                    if not store.has_piece(piece_num):   # resume skip
                        await asyncio.to_thread(
                            store.write_piece, piece_num,
                            piece_buf[:piece_fill])
                    store.touch()
                    piece_num += 1
                    piece_fill = 0
            if c.offset in span_of:
                fetches.consumed(span_of[c.offset])
            now = time.monotonic()
            if now - last_progress >= 0.1:
                last_progress = now
                yield FileTaskProgress(
                    state="running", task_id=store.metadata.task_id,
                    peer_id=peer_id, content_length=total,
                    completed_length=store.downloaded_bytes(),
                    piece_count=len(store.metadata.pieces),
                    total_piece_count=store.metadata.total_piece_count)
    finally:
        release_read_buffer(piece_buf)
        release_read_buffer(chunk_buf)

    # Exact-accounting invariant before anything is announced.
    booked = stats["reused_bytes"] + stats["fetched_bytes"]
    if booked != total:
        raise DfError(Code.UnknownError,
                      f"delta accounting drift: {booked} != {total}")
    task_id = store.metadata.task_id
    await tm._finalize_content_digest(req, store)
    store.mark_done()
    tm.flight.finish_task(task_id, "done")
    tm._pex_announce(task_id)
    # Announce like an import: no conductor registered this task with the
    # scheduler, and peers must be able to pull it from here.
    await tm._announce_local_task(store, task_id, peer_id)
    if len(tm.delta_stats) > 256:
        tm.delta_stats.clear()
    tm.delta_stats[task_id] = dict(stats)
    tm.broker.publish(task_id, PieceEvent(
        [], store.metadata.total_piece_count, total,
        store.metadata.piece_size, done=True))
    if req.output:
        with store:
            await asyncio.to_thread(store.store_to, req.output)
    device_verified = False
    if req.device == "tpu":
        device_verified = await tm._finalize_device(req, task_id, store)
    log.info("delta landed", task=task_id[:16],
             reused_mb=round(stats["reused_bytes"] / 1e6, 2),
             fetched_mb=round(stats["fetched_bytes"] / 1e6, 2),
             corrupt_base=stats["corrupt_base"])
    yield tm._final_progress(store, task_id, peer_id,
                             device_verified=device_verified)


async def _chunk_bytes(tm, req, c: Chunk, base_of: dict, base_store,
                       fetches: _SpanFetches, span_of: dict, fetcher,
                       chunk_buf, stats: dict, tf) -> memoryview:
    """One chunk's verified bytes: local copy from the base (digest
    checked during the copy; corrupt → transparent ranged re-fetch) or a
    slice of its fetched span."""
    b = base_of.get(c.offset)
    if b is None:
        t0 = time.perf_counter()
        span = span_of[c.offset]
        view = await fetches.view(span)
        tf.record(flightlib.EV_DELTA_FETCH, -1,
                  (time.perf_counter() - t0) * 1000.0, str(c.length))
        stats["fetched_bytes"] += c.length
        stats["chunks_fetched"] += 1
        DELTA_BYTES.labels("fetched").inc(c.length)
        DELTA_CHUNKS.labels("fetched").inc()
        return view[c.offset - span[0]: c.end - span[0]]

    t0 = time.perf_counter()
    view = chunk_buf[:c.length]
    ok = False
    try:
        with base_store:
            await asyncio.to_thread(base_store.read_into, b.offset,
                                    b.length, view)
        digest = await asyncio.to_thread(
            lambda: hashlib.sha256(view).hexdigest())
        ok = digest == c.sha256
    except (StorageError, OSError) as e:
        log.warning("base chunk read failed; re-fetching",
                    base_offset=b.offset, error=str(e)[:200])
    if ok:
        tf.record(flightlib.EV_DELTA_REUSE, -1,
                  (time.perf_counter() - t0) * 1000.0, str(c.length))
        stats["reused_bytes"] += c.length
        stats["chunks_reused"] += 1
        DELTA_BYTES.labels("reused").inc(c.length)
        DELTA_CHUNKS.labels("reused").inc()
        return view
    # Corrupt (or unreadable) base chunk: the digest gate caught it
    # during the copy — re-fetch THIS chunk as its own ranged task and
    # book it as fetched, plus the corrupt_base count.
    log.warning("base chunk digest mismatch; re-fetching",
                new_offset=c.offset, base_offset=b.offset,
                length=c.length)
    stats["corrupt_base"] += 1
    DELTA_CHUNKS.labels("corrupt_base").inc()
    t0 = time.perf_counter()
    await fetcher.fetch_into(c.offset, c.end, view)
    digest = await asyncio.to_thread(
        lambda: hashlib.sha256(view).hexdigest())
    if digest != c.sha256:
        raise DfError(Code.ClientPieceDownloadFail,
                      f"delta chunk at {c.offset} failed its manifest "
                      f"digest even after re-fetch")
    tf.record(flightlib.EV_DELTA_FETCH, -1,
              (time.perf_counter() - t0) * 1000.0, str(c.length))
    stats["fetched_bytes"] += c.length
    stats["chunks_fetched"] += 1
    DELTA_BYTES.labels("fetched").inc(c.length)
    DELTA_CHUNKS.labels("fetched").inc()
    return view
