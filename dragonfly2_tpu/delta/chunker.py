"""Rolling-hash content-defined chunking (gear CDC).

The cut decision at byte ``i`` depends ONLY on the ``WINDOW`` bytes
ending at ``i`` (the gear hash is a shifted sum over a sliding window,
never reset at cut points), so identical content regions produce
identical chunk boundaries regardless of what precedes them — inserting
or deleting bytes re-chunks the file locally and every chunk outside the
edit neighborhood keeps its digest. That is the property the delta plane
buys dedup with: version N+1's manifest mostly names chunks version N
already landed.

Determinism contract: the gear table is derived from SHA-256 (no process
seed), the hash window is fixed, and ``feed()`` may split the stream
anywhere — the emitted chunk sequence is a pure function of (content,
params). tests/test_delta.py pins split-independence and the
shift-resistance property.

The per-position hash is computed vectorized over numpy (a shifted-sum
convolution over the window), not per byte in Python — the chunker sits
in front of real checkpoint shards.
"""

from __future__ import annotations

import hashlib
from dataclasses import dataclass

import numpy as np

# Sliding window of the gear hash: how many bytes influence a cut
# decision. The hash is the classic gear recurrence h = 2h + gear[b]
# carried mod 2^32, whose infinite-window form is EXACTLY a 32-byte
# window (older contributions shift out of the register) — so 32 is not
# a tuning choice, it is the arithmetic.
WINDOW = 32

# Gear table: 256 deterministic 32-bit values (sha256 of the byte value;
# NOT random.seed — two builds must always agree).
_GEAR = np.array(
    [int.from_bytes(hashlib.sha256(bytes([i])).digest()[:4], "little")
     for i in range(256)],
    dtype=np.uint32)


@dataclass(frozen=True)
class CDCParams:
    """Chunking geometry. ``mask_bits`` sets the expected spacing of cut
    candidates (2^mask_bits bytes); the expected chunk size is
    ``min_size + 2^mask_bits`` (candidates inside the first ``min_size``
    bytes of a chunk are skipped). Defaults target ~1.25 MiB chunks with
    hard [256 KiB, 4 MiB] bounds."""

    mask_bits: int = 20
    min_size: int = 256 << 10
    max_size: int = 4 << 20

    def __post_init__(self):
        if not (0 < self.min_size <= self.max_size):
            raise ValueError(f"bad CDC bounds [{self.min_size}, {self.max_size}]")
        if not (1 <= self.mask_bits <= 31):
            raise ValueError(f"bad mask_bits {self.mask_bits}")


@dataclass(frozen=True)
class Chunk:
    offset: int
    length: int
    sha256: str        # hex, no "sha256:" prefix

    @property
    def end(self) -> int:
        return self.offset + self.length


def _window_hashes(data: np.ndarray) -> np.ndarray:
    """H[i] = sum_{j<WINDOW} gear[data[i-j]] << j (mod 2^32), vectorized.

    Computed by log-doubling instead of one pass per window position:
    with H_k[i] = sum_{j<2^k} gear[data[i-j]] << j, the next level is
    H_{k+1}[i] = H_k[i] + (H_k[i - 2^k] << 2^k) — so the 32-byte window
    is ONE table gather plus log2(32) = 5 ping-ponged shifted-add passes
    (the naive form's one-gather-per-position measured ~10x slower).
    Positions with a partial window (i < WINDOW-1) use the available
    prefix — callers pass WINDOW-1 bytes of left context except at
    stream start, where the zero-padded prefix is itself deterministic."""
    n = len(data)
    h = _GEAR[data]
    if n < 2:
        return h
    tmp = np.empty_like(h)
    span = 1
    while span < min(WINDOW, n):
        np.left_shift(h[:-span], np.uint32(span), out=tmp[span:])
        tmp[span:] += h[span:]
        tmp[:span] = h[:span]
        h, tmp = tmp, h
        span *= 2
    return h


class GearChunker:
    """Streaming CDC chunker: ``feed()`` arbitrary byte chunks (any
    split), collect emitted ``Chunk``s from ``feed``'s return value (or
    ``chunks`` afterwards), then ``finish()`` for the tail. Offsets are
    absolute stream offsets; chunks are contiguous and exactly cover the
    stream."""

    def __init__(self, params: CDCParams | None = None):
        self.params = params or CDCParams()
        self.chunks: list[Chunk] = []
        self._tail = bytearray()        # bytes not yet emitted
        self._tail_start = 0            # absolute offset of _tail[0]
        self._scanned = 0               # absolute position hashed so far
        self._cands: list[int] = []     # absolute cut positions (chunk END)
        self._ci = 0                    # consumed prefix of _cands
        self._finished = False

    # -- feeding -----------------------------------------------------------

    def feed(self, data: bytes) -> list[Chunk]:
        """Consume ``data``; returns the chunks this call completed."""
        if self._finished:
            raise RuntimeError("feed() after finish()")
        if not data:
            return []
        self._tail += data
        self._scan()
        return self._emit()

    def finish(self) -> list[Chunk]:
        """End of stream: the remaining tail becomes the final chunk
        (shorter than min_size is legal only here)."""
        self._finished = True
        out = self._emit()
        if self._tail:
            out.append(self._cut(len(self._tail)))
        return out

    @property
    def consumed(self) -> int:
        return self._tail_start + len(self._tail)

    # -- internals ---------------------------------------------------------

    # One vectorized scan block: bounds the uint64 temporaries to
    # ~3 x 8 x 4 MiB regardless of how much one feed() delivers.
    _SCAN_BLOCK = 4 << 20

    def _scan(self) -> None:
        """Hash the not-yet-scanned region (with WINDOW-1 bytes of left
        context so boundaries are split-independent) and append new cut
        candidates. Processes in bounded blocks."""
        # Cut condition: the TOP mask_bits of the hash are zero. High
        # bits see the whole 32-byte window (bit k folds the last k+1
        # bytes), so the boundary context does not shrink with the mask.
        shift = np.uint32(32 - self.params.mask_bits)
        zero = np.uint32(0)
        while True:
            lo = self._scanned - self._tail_start   # first unscanned, tail-rel
            hi = min(len(self._tail), lo + self._SCAN_BLOCK)
            if hi <= lo:
                return
            ctx = min(lo, WINDOW - 1)
            region = np.frombuffer(
                memoryview(self._tail)[lo - ctx:hi], dtype=np.uint8)
            h = _window_hashes(region)[ctx:]
            for i in np.nonzero((h >> shift) == zero)[0]:
                # Cut AFTER the matching byte: chunk end = position + 1.
                self._cands.append(self._scanned + int(i) + 1)
            self._scanned = self._tail_start + hi

    def _emit(self) -> list[Chunk]:
        p = self.params
        out: list[Chunk] = []
        while True:
            start = self._tail_start
            # First candidate cut that respects min_size for this chunk.
            while (self._ci < len(self._cands)
                   and self._cands[self._ci] - start < p.min_size):
                self._ci += 1
            cut = -1
            if self._ci < len(self._cands):
                c = self._cands[self._ci]
                if c - start <= p.max_size:
                    cut = c - start
            if cut < 0 and self._scanned - start >= p.max_size:
                cut = p.max_size                    # forced cut at the bound
            if cut < 0:
                return out
            out.append(self._cut(cut))
        return out

    def _cut(self, length: int) -> Chunk:
        view = memoryview(self._tail)[:length]
        ck = Chunk(self._tail_start, length,
                   hashlib.sha256(view).hexdigest())
        del view
        del self._tail[:length]
        self._tail_start += length
        self.chunks.append(ck)
        return ck


def chunk_bytes(data: bytes, params: CDCParams | None = None) -> list[Chunk]:
    """One-shot chunking of in-memory content."""
    ch = GearChunker(params)
    ch.feed(data)
    ch.finish()
    return ch.chunks
