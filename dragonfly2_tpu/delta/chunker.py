"""Rolling-hash content-defined chunking (gear CDC).

The cut decision at byte ``i`` depends ONLY on the ``WINDOW`` bytes
ending at ``i`` (the gear hash is a shifted sum over a sliding window,
never reset at cut points), so identical content regions produce
identical chunk boundaries regardless of what precedes them — inserting
or deleting bytes re-chunks the file locally and every chunk outside the
edit neighborhood keeps its digest. That is the property the delta plane
buys dedup with: version N+1's manifest mostly names chunks version N
already landed.

Determinism contract: the gear table is derived from SHA-256 (no process
seed), the hash window is fixed, and ``feed()`` may split the stream
anywhere — the emitted chunk sequence is a pure function of (content,
params). tests/test_delta.py pins split-independence and the
shift-resistance property; tests/test_chunker_oracle.py pins that every
backend produces byte-identical cut points.

The candidate scan (hash every position, report the rare ones whose top
``mask_bits`` are zero) is the hot loop and sits behind a backend ladder
selected the way pkg/digest picks crc32c implementations:

  native  — dragonfly2_tpu/native/src/dfchunk.cc, interleaved scalar
            recurrences (~GB/s; ships the same SHA-256 gear table down)
  numpy   — log-doubling shifted-sum convolution (~tens of MiB/s)
  python  — per-byte rolling hash (correctness fallback)

``chunker_backend()`` reports the selection; DF_CHUNKER_BACKEND forces
one ladder rung (benchmarks pin numpy to measure the native speedup).
min/max/forced-cut selection (``_emit``) is shared by all backends, so a
backend can only ever change speed, never cut points.
"""

from __future__ import annotations

import hashlib
import os
from dataclasses import dataclass

try:
    import numpy as np
except ImportError:          # pragma: no cover - numpy is everywhere in CI
    np = None

from dragonfly2_tpu.pkg import metrics

# Sliding window of the gear hash: how many bytes influence a cut
# decision. The hash is the classic gear recurrence h = 2h + gear[b]
# carried mod 2^32, whose infinite-window form is EXACTLY a 32-byte
# window (older contributions shift out of the register) — so 32 is not
# a tuning choice, it is the arithmetic.
WINDOW = 32

# Gear table: 256 deterministic 32-bit values (sha256 of the byte value;
# NOT random.seed — two builds must always agree).
_GEAR_LIST = [
    int.from_bytes(hashlib.sha256(bytes([i])).digest()[:4], "little")
    for i in range(256)
]
_GEAR = np.array(_GEAR_LIST, dtype=np.uint32) if np is not None else None
_GEAR_BYTES = b"".join(v.to_bytes(4, "little") for v in _GEAR_LIST)

CHUNKER_BACKEND_ACTIVE = metrics.gauge(
    "delta_chunker_backend",
    "Selected CDC candidate-scan backend (1 = active; ladder "
    "native > numpy > python, see delta/chunker.py)", ("backend",))


@dataclass(frozen=True)
class CDCParams:
    """Chunking geometry. ``mask_bits`` sets the expected spacing of cut
    candidates (2^mask_bits bytes); the expected chunk size is
    ``min_size + 2^mask_bits`` (candidates inside the first ``min_size``
    bytes of a chunk are skipped). Defaults target ~1.25 MiB chunks with
    hard [256 KiB, 4 MiB] bounds."""

    mask_bits: int = 20
    min_size: int = 256 << 10
    max_size: int = 4 << 20

    def __post_init__(self):
        if not (0 < self.min_size <= self.max_size):
            raise ValueError(f"bad CDC bounds [{self.min_size}, {self.max_size}]")
        if not (1 <= self.mask_bits <= 31):
            raise ValueError(f"bad mask_bits {self.mask_bits}")


@dataclass(frozen=True)
class Chunk:
    offset: int
    length: int
    sha256: str        # hex, no "sha256:" prefix

    @property
    def end(self) -> int:
        return self.offset + self.length


def _window_hashes(data) -> "np.ndarray":
    """H[i] = sum_{j<WINDOW} gear[data[i-j]] << j (mod 2^32), vectorized.

    Computed by log-doubling instead of one pass per window position:
    with H_k[i] = sum_{j<2^k} gear[data[i-j]] << j, the next level is
    H_{k+1}[i] = H_k[i] + (H_k[i - 2^k] << 2^k) — so the 32-byte window
    is ONE table gather plus log2(32) = 5 ping-ponged shifted-add passes
    (the naive form's one-gather-per-position measured ~10x slower).
    Positions with a partial window (i < WINDOW-1) use the available
    prefix — callers pass WINDOW-1 bytes of left context except at
    stream start, where the zero-padded prefix is itself deterministic."""
    n = len(data)
    h = _GEAR[data]
    if n < 2:
        return h
    tmp = np.empty_like(h)
    span = 1
    while span < min(WINDOW, n):
        np.left_shift(h[:-span], np.uint32(span), out=tmp[span:])
        tmp[span:] += h[span:]
        tmp[:span] = h[:span]
        h, tmp = tmp, h
        span *= 2
    return h


# --------------------------------------------------------------------- #
# Candidate-scan backends. Each takes (region, ctx, mask_bits) — region
# is a bytes-like whose first ctx bytes are left context — and returns
# ascending region-relative indices (>= ctx) of bytes whose gear hash
# has its top mask_bits zero. Identical output is pinned by
# tests/test_chunker_oracle.py; _emit turns candidates into cuts.
# --------------------------------------------------------------------- #

def _scan_python(region, ctx: int, mask_bits: int) -> list[int]:
    limit = 1 << (32 - mask_bits)
    gear = _GEAR_LIST
    h = 0
    out = []
    for i, b in enumerate(memoryview(region)):
        h = ((h << 1) + gear[b]) & 0xFFFFFFFF
        if h < limit and i >= ctx:
            out.append(i)
    return out


def _scan_numpy(region, ctx: int, mask_bits: int) -> list[int]:
    data = np.frombuffer(region, dtype=np.uint8)
    h = _window_hashes(data)[ctx:]
    shift = np.uint32(32 - mask_bits)
    return [ctx + int(i)
            for i in np.nonzero((h >> shift) == np.uint32(0))[0]]


def _native_scanner():
    """The dfchunk.cc kernel as a scan function, or None. Self-checked
    against the pure-python reference on a deterministic vector before
    selection (mirrors pkg/digest's probe discipline)."""
    try:
        from dragonfly2_tpu.native import binding
    except ImportError:
        return None
    if not hasattr(binding, "chunk_scan"):
        return None      # stale prebuilt library without the kernel

    def scan(region, ctx: int, mask_bits: int) -> list[int]:
        return binding.chunk_scan(region, _GEAR_BYTES, mask_bits, ctx)

    probe = hashlib.sha256(b"dfchunk-probe").digest() * 256   # 8 KiB
    try:
        if scan(probe, 5, 8) != _scan_python(probe, 5, 8):
            return None
    except Exception:
        return None
    return scan


_scanner = None
_backend_name = "unset"


def _select_scanner():
    """Pick the fastest available backend (native > numpy > python),
    honoring DF_CHUNKER_BACKEND={native,numpy,python} to pin a rung."""
    global _scanner, _backend_name
    forced = os.environ.get("DF_CHUNKER_BACKEND", "").strip().lower()
    native = None if forced in ("numpy", "python") else _native_scanner()
    if native is not None:
        _scanner, _backend_name = native, "native"
    elif np is not None and forced != "python":
        _scanner, _backend_name = _scan_numpy, "numpy"
    else:
        _scanner, _backend_name = _scan_python, "python"
    CHUNKER_BACKEND_ACTIVE.labels(_backend_name).set(1)
    return _scanner


def chunker_backend() -> str:
    """Which candidate-scan implementation chunking uses:
    "native" (dfchunk.cc), "numpy", or "python"."""
    if _scanner is None:
        _select_scanner()
    return _backend_name


class GearChunker:
    """Streaming CDC chunker: ``feed()`` arbitrary byte chunks (any
    split), collect emitted ``Chunk``s from ``feed``'s return value (or
    ``chunks`` afterwards), then ``finish()`` for the tail. Offsets are
    absolute stream offsets; chunks are contiguous and exactly cover the
    stream."""

    def __init__(self, params: CDCParams | None = None):
        self.params = params or CDCParams()
        self.chunks: list[Chunk] = []
        self._tail = bytearray()        # bytes not yet emitted
        self._tail_start = 0            # absolute offset of _tail[0]
        self._scanned = 0               # absolute position hashed so far
        self._cands: list[int] = []     # absolute cut positions (chunk END)
        self._ci = 0                    # consumed prefix of _cands
        self._finished = False
        if _scanner is None:
            _select_scanner()

    # -- feeding -----------------------------------------------------------

    def feed(self, data: bytes) -> list[Chunk]:
        """Consume ``data``; returns the chunks this call completed."""
        if self._finished:
            raise RuntimeError("feed() after finish()")
        if not data:
            return []
        self._tail += data
        self._scan()
        return self._emit()

    def finish(self) -> list[Chunk]:
        """End of stream: the remaining tail becomes the final chunk
        (shorter than min_size is legal only here)."""
        self._finished = True
        out = self._emit()
        if self._tail:
            out.append(self._cut(len(self._tail)))
        return out

    @property
    def consumed(self) -> int:
        return self._tail_start + len(self._tail)

    # -- internals ---------------------------------------------------------

    # One scan block: bounds the numpy backend's uint64 temporaries to
    # ~3 x 8 x 4 MiB regardless of how much one feed() delivers.
    _SCAN_BLOCK = 4 << 20

    def _scan(self) -> None:
        """Scan the not-yet-scanned region (with WINDOW-1 bytes of left
        context so boundaries are split-independent) and append new cut
        candidates. Processes in bounded blocks through the selected
        backend; the cut condition — the TOP mask_bits of the hash are
        zero — sees the whole 32-byte window at every mask width."""
        scan = _scanner
        while True:
            lo = self._scanned - self._tail_start   # first unscanned, tail-rel
            hi = min(len(self._tail), lo + self._SCAN_BLOCK)
            if hi <= lo:
                return
            ctx = min(lo, WINDOW - 1)
            region = memoryview(self._tail)[lo - ctx:hi]
            for i in scan(region, ctx, self.params.mask_bits):
                # Cut AFTER the matching byte: chunk end = position + 1.
                self._cands.append(self._scanned + (i - ctx) + 1)
            self._scanned = self._tail_start + hi

    def _emit(self) -> list[Chunk]:
        p = self.params
        # Decide every cut first, then materialize them off one view and
        # trim the tail ONCE — the per-chunk `del tail[:length]` memmove
        # was O(tail x chunks) when a feed() completed many chunks.
        lengths: list[int] = []
        start = self._tail_start
        while True:
            # First candidate cut that respects min_size for this chunk.
            while (self._ci < len(self._cands)
                   and self._cands[self._ci] - start < p.min_size):
                self._ci += 1
            cut = -1
            if self._ci < len(self._cands):
                c = self._cands[self._ci]
                if c - start <= p.max_size:
                    cut = c - start
            if cut < 0 and self._scanned - start >= p.max_size:
                cut = p.max_size                    # forced cut at the bound
            if cut < 0:
                break
            lengths.append(cut)
            start += cut
        if not lengths:
            return []
        out: list[Chunk] = []
        mv = memoryview(self._tail)
        off = 0
        for length in lengths:
            ck = Chunk(self._tail_start + off, length,
                       hashlib.sha256(mv[off:off + length]).hexdigest())
            out.append(ck)
            self.chunks.append(ck)
            off += length
        del mv
        del self._tail[:off]
        self._tail_start += off
        return out

    def _cut(self, length: int) -> Chunk:
        view = memoryview(self._tail)[:length]
        ck = Chunk(self._tail_start, length,
                   hashlib.sha256(view).hexdigest())
        del view
        del self._tail[:length]
        self._tail_start += length
        self.chunks.append(ck)
        return ck


def chunk_bytes(data: bytes, params: CDCParams | None = None) -> list[Chunk]:
    """One-shot chunking of in-memory content."""
    ch = GearChunker(params)
    ch.feed(data)
    ch.finish()
    return ch.chunks
