"""dragonfly2_tpu — a TPU-native P2P content-distribution fabric.

A brand-new implementation of the capabilities of Dragonfly2 (reference:
/root/reference, d7y.io/dragonfly/v2 v2.2.0, Go), re-designed TPU-first:

- ``pkg/``       shared kernel: IDs, digests, piece math, errors, config,
                 logging, metrics, DAG, caches, rate limiting.
- ``rpc/``       drpc: asyncio msgpack-framed RPC (unary + bidi streams),
                 consistent-hash balancer, resolvers.
- ``proto/``     message schemas (dataclasses) modeled on the v2 protobuf API.
- ``source/``    pluggable origin clients keyed by URL scheme (http, file,
                 gcs, s3 — reference: pkg/source).
- ``storage/``   per-(task,peer) piece stores with metadata persistence
                 (reference: client/daemon/storage).
- ``daemon/``    the data-plane peer daemon: conductor, piece pipeline,
                 upload server, proxy, object-storage gateway, PEX
                 (reference: client/daemon).
- ``scheduler/`` control plane: resource FSMs + peer DAG, filter→score
                 scheduling, AnnouncePeer stream (reference: scheduler/).
- ``manager/``   global control plane: clusters, dynconfig, searcher,
                 preheat jobs (reference: manager/).
- ``client/``    dfget/dfcache/dfstore client libraries.
- ``ops/``       TPU compute: HBM piece sink, digest/verify kernels (JAX/Pallas).
- ``parallel/``  device-mesh plans: ICI ring broadcast of checkpoint shards,
                 pod topology model.
"""

__version__ = "0.1.0"
