"""Daemon dynconfig: resolve scheduler addresses (and seed peers) from the
manager, or serve the static local list.

Reference: client/config/dynconfig_manager.go:84-278 (manager source:
ListSchedulers via the searcher, observer notification into the scheduler
resolver) and dynconfig.go:185 (local source).
"""

from __future__ import annotations

from typing import Any

from dragonfly2_tpu.manager.client import ManagerClient
from dragonfly2_tpu.pkg import dflog
from dragonfly2_tpu.pkg.dynconfig import Dynconfig
from dragonfly2_tpu.pkg.types import NetAddr

log = dflog.get("daemon.dynconfig")


class DaemonDynconfig:
    """source='local': static addrs from config. source='manager': pull
    searcher-ranked schedulers from the manager and keep them fresh."""

    def __init__(self, *, local_addrs: list[str] | None = None,
                 manager_addr: str = "", host_info: dict[str, Any] | None = None,
                 refresh_interval: float = 10.0, cache_dir: str = ""):
        self.local_addrs = list(local_addrs or [])
        self.manager_addr = manager_addr
        self.host_info = host_info or {}
        self.client: ManagerClient | None = None
        self.dc: Dynconfig | None = None
        if manager_addr:
            host, _, port = manager_addr.rpartition(":")
            self.client = ManagerClient(NetAddr.tcp(host, int(port)))
            self.dc = Dynconfig("daemon", self._fetch,
                                refresh_interval=refresh_interval,
                                cache_dir=cache_dir)

    @property
    def source(self) -> str:
        return "manager" if self.client else "local"

    async def _fetch(self) -> dict[str, Any]:
        schedulers = await self.client.list_schedulers(
            hostname=self.host_info.get("hostname", ""),
            ip=self.host_info.get("ip", ""),
            idc=self.host_info.get("idc", ""),
            location=self.host_info.get("location", ""),
            pod=self.host_info.get("pod", ""))
        # Seed peers of our cluster ride along for object-storage
        # replication (reference client/config/dynconfig_manager.go:84-278
        # resolves seed peers + object-storage config in the same pull).
        seed_peers: list[dict[str, Any]] = []
        cluster_ids = {s.get("scheduler_cluster_id") for s in schedulers
                       if s.get("scheduler_cluster_id")}
        for cid in sorted(cluster_ids):
            try:
                seed_peers.extend(await self.client.list_seed_peers(cid))
            except Exception:
                pass
        return {"schedulers": schedulers, "seed_peers": seed_peers}

    def cached_seed_peers(self) -> list[dict[str, Any]]:
        """Last-fetched seed peers, non-blocking (replication fan-out)."""
        if self.dc is None:
            return []
        return list(self.dc.cached().get("seed_peers") or [])

    async def scheduler_addrs(self) -> list[str]:
        if self.dc is None:
            return self.local_addrs
        data = await self.dc.get()
        addrs = [f"{s['ip']}:{s['port']}" for s in data.get("schedulers", [])
                 if s.get("state") == "active"]
        return addrs or self.local_addrs

    def register(self, observer) -> None:
        if self.dc is not None:
            self.dc.register(observer)

    def serve(self) -> None:
        if self.dc is not None:
            self.dc.serve()

    async def stop(self) -> None:
        if self.dc is not None:
            self.dc.stop()
        if self.client is not None:
            await self.client.close()
