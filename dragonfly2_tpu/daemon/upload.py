"""Upload server: HTTP endpoint other peers hit for piece payloads.

Reference: client/daemon/upload/upload_manager.go — gin server with
``GET /download/:task_prefix/:task_id`` + Range header (:181-188), rate
limiting (WithLimiter :79). Piece payloads ride HTTP (not drpc) exactly like
the reference, so transfers stream zero-copy from the page cache via
sendfile-ish paths and any HTTP client can fetch.

Serving is the READ half of the zero-copy data plane (docs/ZERO_COPY.md):
both servers move piece bytes kernel→socket without them ever entering
Python — _PieceFileResponse rides aiohttp's sendfile, the native server
(native/src/dfupload.cc) does its own sendfile loop — so the daemon's
single hot core spends its cycles on the receive/verify side only.

Routes:
  GET /download/{task_prefix}/{task_id}?peerId=...          Range: bytes=a-b
  GET /download/{task_prefix}/{task_id}?peerId=...&pieceNum=N   (whole piece)
  GET /metrics, GET /healthy
"""

from __future__ import annotations

import asyncio
import threading

from aiohttp import web

from dragonfly2_tpu.pkg import dflog, metrics, tracing
from dragonfly2_tpu.pkg import flight as flightlib
from dragonfly2_tpu.pkg.piece import Range
from dragonfly2_tpu.pkg.ratelimit import Limiter
from dragonfly2_tpu.storage import StorageManager

log = dflog.get("daemon.upload")

UPLOAD_BYTES = metrics.counter("upload_bytes_total", "Piece bytes served to other peers")
UPLOAD_REQUESTS = metrics.counter("upload_requests_total", "Piece upload requests", ("result",))
CONCURRENT_UPLOADS = metrics.gauge("upload_concurrency", "In-flight piece uploads")


class _PieceFileResponse(web.FileResponse):
    """FileResponse serving exactly one byte window of the task data file
    via sendfile. The window rides a synthesized Range header injected at
    prepare time (FileResponse reads the REQUEST's Range), and prepare is
    made idempotent — aiohttp's finish_response prepares again after the
    handler returns, and the base class asserts on the second call.

    The transfer happens AFTER the handler returns (aiohttp prepares the
    response in finish_response), so this response owns the store pin and
    the upload-concurrency slot and releases them when the send is done —
    releasing in the handler would let GC rmtree the data file mid-
    sendfile."""

    def __init__(self, path, range_header: str | None, release,
                 content_total: int | None = None):
        super().__init__(path)
        self._df_range = range_header  # None → whole file, plain 200
        self._df_prepared = False
        self._df_release = release
        self._df_total = content_total

    def _df_done(self) -> None:
        release, self._df_release = self._df_release, None
        if release is not None:
            release()

    async def _start(self, request):
        # FileResponse derives Content-Range denominators from the FILE
        # size. While a task is in progress the data file is shorter than
        # the content (only a landed prefix/window exists), so the serve-
        # from-in-progress fast path would advertise a lying complete-
        # length; rewrite the denominator to the task's true content
        # length just before the headers go out.
        total = self._df_total
        cr = self.headers.get("Content-Range")
        if total is not None and total >= 0 and cr and "/" in cr:
            span, _, _ = cr.rpartition("/")
            self.headers["Content-Range"] = f"{span}/{total}"
        return await super()._start(request)

    async def prepare(self, request):
        if self._df_prepared:
            return self._payload_writer
        self._df_prepared = True
        try:
            if self._df_range is None:
                headers = {k: v for k, v in request.headers.items()
                           if k.lower() != "range"}
                return await super().prepare(request.clone(headers=headers))
            cloned = request.clone(headers={**request.headers,
                                            "Range": self._df_range})
            return await super().prepare(cloned)
        finally:
            self._df_done()


class UploadManager:
    def __init__(self, storage: StorageManager, *, rate_limit: int = 0,
                 concurrent_limit: int = 0, ssl_context=None,
                 qos_buckets=None):
        self.storage = storage
        self._ssl = ssl_context   # optional (m)TLS — reference WithTLS/certify
        self._rate_limit = rate_limit
        self.limiter = Limiter(rate_limit if rate_limit > 0 else float("inf"))
        # Tenant QoS plane (dragonfly2_tpu/qos.TenantBuckets): when set,
        # serve admission debits the requesting tenant's bucket instead
        # of the flat daemon limiter, and every served byte lands in
        # peer_upload_bytes_total{tenant}.
        self.qos_buckets = qos_buckets
        self.concurrent_limit = concurrent_limit
        self.concurrent = 0
        self._runner: web.AppRunner | None = None
        self._native_srv: int | None = None
        self._port = 0

    def _native_eligible(self, host: str):
        """The C++ server (native/src/dfupload.cc) serves plaintext HTTP
        only and has no token-bucket limiter: (m)TLS, rate-limited and
        tenant-QoS configs stay on the aiohttp path (per-tenant limiting
        and byte attribution live there). Returns the binding or None."""
        import ipaddress

        if (self._ssl is not None or self._rate_limit > 0
                or self.qos_buckets is not None):
            return None
        try:
            ipaddress.IPv4Address(host)
        except ValueError:
            return None
        from dragonfly2_tpu.storage.local_store import _native

        return _native()

    async def serve(self, host: str, port: int = 0) -> int:
        nb = self._native_eligible(host)
        if nb is not None:
            srv = nb.upload_start(host, port,
                                  concurrent_limit=self.concurrent_limit)
            self._native_srv = srv
            self._port = nb.upload_port(srv)
            # Mirror the piece map into the serving registry: replay what
            # exists (reloaded tasks), then stay current via observer
            # callbacks — requests never consult Python.
            self.storage.set_observer(_NativeServingIndex(nb, srv))
            log.info("upload server up (native)", port=self._port)
            return self._port
        app = web.Application()
        app.router.add_get("/download/{task_prefix}/{task_id}", self._download)
        app.router.add_get("/healthy", self._healthy)
        app.router.add_get("/metrics", self._metrics)
        self._runner = web.AppRunner(app, access_log=None)
        await self._runner.setup()
        site = web.TCPSite(self._runner, host, port, ssl_context=self._ssl)
        await site.start()
        self._port = site._server.sockets[0].getsockname()[1]
        log.info("upload server up", port=self._port, tls=self._ssl is not None)
        return self._port

    @property
    def port(self) -> int:
        return self._port

    def native_counters(self) -> dict | None:
        if self._native_srv is None:
            return None
        from dragonfly2_tpu.storage.local_store import _native

        return _native().upload_counters(self._native_srv)

    async def close(self) -> None:
        if self._native_srv is not None:
            from dragonfly2_tpu.storage.local_store import _native

            srv, self._native_srv = self._native_srv, None
            # Detach + barrier BEFORE the stop frees the handle: observer
            # callbacks arrive from executor threads (piece commits), and a
            # register racing upload_stop would call into freed memory.
            index = self.storage.observer
            self.storage.clear_observer()
            if isinstance(index, _NativeServingIndex):
                # May wait behind an in-flight callback's native call; keep
                # the event loop free.
                await asyncio.to_thread(index.close)
            # stop() joins serving threads; keep the event loop free.
            await asyncio.to_thread(_native().upload_stop, srv)
        if self._runner is not None:
            await self._runner.cleanup()

    # -- handlers ----------------------------------------------------------

    async def _download(self, request: web.Request) -> web.StreamResponse:
        # Adopt the requester's trace context from the piece HTTP hop
        # (piece_downloader injects it): the serving span joins the SAME
        # trace, so a pod download is one trace, not N disconnected ones.
        tp = request.headers.get(tracing.TRACEPARENT, "")
        with tracing.extract({tracing.TRACEPARENT: tp} if tp else None,
                             "upload.serve") as sp:
            return await self._download_traced(request, sp)

    async def _download_traced(self, request: web.Request,
                               sp) -> web.StreamResponse:
        task_id = request.match_info["task_id"]
        sp.set_attr("task", task_id[:16])
        store = self.storage.try_get(task_id)
        if store is None:
            UPLOAD_REQUESTS.labels("not_found").inc()
            raise web.HTTPNotFound(text=f"task {task_id} not found")
        if self.concurrent_limit and self.concurrent >= self.concurrent_limit:
            UPLOAD_REQUESTS.labels("throttled").inc()
            raise web.HTTPTooManyRequests()

        self.concurrent += 1
        CONCURRENT_UPLOADS.inc()
        store.pin()
        released = False

        def release() -> None:
            nonlocal released
            if not released:
                released = True
                store.unpin()
                self.concurrent -= 1
                CONCURRENT_UPLOADS.dec()

        try:
            piece_num = request.query.get("pieceNum")
            if piece_num is not None:
                try:
                    rec = store.metadata.pieces.get(int(piece_num))
                except ValueError:
                    UPLOAD_REQUESTS.labels("bad_request").inc()
                    raise web.HTTPBadRequest(
                        text=f"bad pieceNum {piece_num!r}")
                if rec is None:
                    UPLOAD_REQUESTS.labels("piece_missing").inc()
                    raise web.HTTPNotFound(text=f"piece {piece_num} not found")
                start, length = rec.offset, rec.size
            else:
                rng_header = request.headers.get("Range")
                if not rng_header:
                    UPLOAD_REQUESTS.labels("bad_request").inc()
                    raise web.HTTPBadRequest(text="Range or pieceNum required")
                try:
                    rng = Range.parse_http(rng_header, store.metadata.content_length)
                except ValueError as e:
                    UPLOAD_REQUESTS.labels("bad_request").inc()
                    raise web.HTTPBadRequest(text=str(e))
                if not store.covers_range(rng.start, rng.length):
                    UPLOAD_REQUESTS.labels("piece_missing").inc()
                    raise web.HTTPRequestRangeNotSatisfiable()
                start, length = rng.start, rng.length
            if self.qos_buckets is not None:
                # Per-tenant serve admission: the tenant's split of the
                # daemon cap, plus byte attribution. The flat limiter
                # still applies as the aggregate ceiling.
                await self.qos_buckets.wait(
                    request.query.get("tenant", ""), length)
            await self.limiter.wait(length)
            UPLOAD_BYTES.inc(length)
            UPLOAD_REQUESTS.labels("ok").inc()
            sp.set_attr("bytes", length)
            # Serving-side flight event: the parent's own timeline records
            # which pieces it handed out (pod autopsies correlate a child's
            # stall against the parent's serve log).
            flightlib.for_task(task_id).record(
                flightlib.EV_UPLOAD_SERVE,
                int(piece_num) if piece_num is not None else -1,
                float(length))
            # sendfile the byte range straight from the page cache — the
            # hot single-core cost in profiles was pread into Python bytes
            # plus the user→kernel copy in sendmsg (benchmarks/fanout_bench
            # --profile showed the serving side dominated by exactly that).
            # Pin + slot transfer to the response (released after the send).
            # content_total keeps Content-Range honest while the store is
            # still mid-download (in-progress pieces serve the same way).
            return _PieceFileResponse(
                store.data_path, f"bytes={start}-{start + length - 1}",
                release, content_total=store.metadata.content_length)
        except BaseException:
            release()
            raise

    async def _healthy(self, request: web.Request) -> web.Response:
        return web.Response(text="ok")

    async def _metrics(self, request: web.Request) -> web.Response:
        body, ctype = metrics.render()
        return web.Response(body=body, content_type=ctype.split(";")[0])


class _NativeServingIndex:
    """StorageManager observer mirroring task/piece state into the native
    upload server's registry. Pure ctypes calls guarded by the C side's
    mutex — safe from any thread (piece commits arrive from workers).

    The close() barrier upholds the binding layer's handle-ownership
    contract: callbacks may arrive from executor threads right up to
    teardown, so every native call holds a lock that close() takes before
    upload_stop frees the server — after close() returns, no callback can
    touch the dead handle (it sees _closed and returns)."""

    def __init__(self, nb, srv: int):
        self._nb = nb
        self._srv = srv
        self._mu = threading.Lock()
        self._closed = False

    def task_updated(self, store) -> None:
        m = store.metadata
        with self._mu:
            if self._closed:
                return
            self._nb.upload_register_task(self._srv, m.task_id,
                                          store.data_path,
                                          m.content_length, m.piece_size)

    def piece_recorded(self, task_id: str, rec) -> None:
        with self._mu:
            if self._closed:
                return
            self._nb.upload_register_piece(self._srv, task_id, rec.num,
                                           rec.offset, rec.size)

    def task_deleted(self, task_id: str) -> None:
        with self._mu:
            if self._closed:
                return
            self._nb.upload_unregister_task(self._srv, task_id)

    def close(self) -> None:
        """After this returns, no further native call will be made; any
        in-flight callback has completed."""
        with self._mu:
            self._closed = True
