"""Daemon drpc server: download service (unix sock) + peer service (TCP).

Reference: client/daemon/rpcserver/rpcserver.go — Download streaming file
task (:388), SyncPieceTasks serving children (:277), GetPieceTasks (:160),
StatTask/DeleteTask (:847+). The download service faces dfget on the local
host; the peer service faces other daemons (stage 3).
"""

from __future__ import annotations

import asyncio

from dragonfly2_tpu.daemon.peer.task_manager import FileTaskRequest, TaskManager
from dragonfly2_tpu.pkg import aio, dflog
from dragonfly2_tpu.pkg import flight as flightlib
from dragonfly2_tpu.pkg.errors import Code, DfError
from dragonfly2_tpu.pkg.piece import Range
from dragonfly2_tpu.pkg.types import NetAddr
from dragonfly2_tpu.proto.common import UrlMeta
from dragonfly2_tpu.rpc import RpcContext, Server, ServerStream

log = dflog.get("daemon.rpcserver")


class DaemonRpcServer:
    def __init__(self, task_manager: TaskManager):
        self.task_manager = task_manager
        self.download_server = Server("daemon.download")
        self.peer_server = Server("daemon.peer")
        self._register()

    def _register(self) -> None:
        self.download_server.register_stream("Daemon.Download", self._download)
        self.download_server.register_unary("Daemon.StatTask", self._stat_task)
        self.download_server.register_unary("Daemon.ImportTask", self._import_task)
        self.download_server.register_stream("Daemon.ExportTask", self._export_task)
        self.download_server.register_unary("Daemon.DeleteTask", self._delete_task)
        self.download_server.register_unary("Daemon.Health", self._health)
        self.download_server.register_unary("Daemon.FlightReport",
                                            self._flight_report)
        self.download_server.register_unary("Daemon.PodTimeline",
                                            self._pod_timeline)
        # Peer-facing service (reference rpcserver.go peer server): piece
        # availability sync for children + seed triggering by the scheduler.
        self.peer_server.register_stream("Peer.SyncPieceTasks", self._sync_piece_tasks)
        # Scheduler-side on-demand flight pull: a host that never shipped
        # its digest (crashed stream, old daemon) can still be merged
        # into the pod timeline.
        self.peer_server.register_unary("Daemon.FlightReport",
                                        self._flight_report)
        self.peer_server.register_unary("Peer.GetPieceTasks", self._get_piece_tasks)
        self.peer_server.register_unary("Peer.TriggerDownloadTask", self._trigger_download)
        self.peer_server.register_unary("Peer.StatTask", self._stat_task)
        self.peer_server.register_unary("Peer.DeleteTask", self._delete_task)
        self.peer_server.register_unary("Daemon.Health", self._health)

    async def serve_download(self, addr: NetAddr) -> None:
        await self.download_server.serve(addr)

    async def serve_peer(self, addr: NetAddr) -> None:
        await self.peer_server.serve(addr)

    async def close(self) -> None:
        await self.download_server.close()
        await self.peer_server.close()

    # -- handlers ----------------------------------------------------------

    async def _download(self, stream: ServerStream, ctx: RpcContext) -> None:
        """One file download; progress frames stream back to dfget
        (reference rpcserver.go:388 Download → :740 download)."""
        body = stream.open_body or {}
        url = body.get("url", "")
        output = body.get("output", "")
        device = body.get("device", "")
        # Output may be omitted only when the content terminates in a
        # device sink (--device=tpu): the result lives in HBM, not a path.
        if not url or (not output and device != "tpu"):
            raise DfError(Code.BadRequest, "url and output are required")
        req = FileTaskRequest(
            url=url,
            output=output,
            meta=UrlMeta.from_wire(body.get("meta")),
            disable_back_source=body.get("disable_back_source", False),
            device=device,
            pod_broadcast=bool(body.get("pod_broadcast")),
        )
        if req.meta.range:
            # Canonicalize at the wire chokepoint: the header is task
            # identity, and raw RPC clients must dedup with dfget /
            # preheat / device pulls of the same span.
            try:
                req.meta.range = Range.normalize_header(req.meta.range)
                req.range = Range.parse_http(req.meta.range)
            except ValueError as e:
                raise DfError(Code.BadRequest,
                              f"bad range {req.meta.range!r}: {e}")
        delta_base = body.get("delta_base", "")
        if delta_base:
            # Checkpoint-delta plane: copy chunks the local base version
            # already holds, fetch only changed chunks as ranged tasks
            # (delta/resolver.py; degrades to a plain download when the
            # delta path is not viable).
            progress_iter = self.task_manager.start_delta_task(
                req, delta_base)
        else:
            progress_iter = self.task_manager.start_file_task(req)
        async for progress in progress_iter:
            await stream.send(progress.to_wire())

    async def _stat_task(self, body, ctx: RpcContext):
        """Local task presence/completeness (reference rpcserver.go:847)."""
        task_id = (body or {}).get("task_id", "")
        store = self.task_manager.storage.try_get(task_id)
        if store is None:
            raise DfError(Code.PeerTaskNotFound, f"task {task_id} not found")
        m = store.metadata
        return {
            "task_id": m.task_id,
            "done": m.done,
            "content_length": m.content_length,
            "piece_count": len(m.pieces),
            "total_piece_count": m.total_piece_count,
            "digest": m.digest,
        }

    async def _import_task(self, body, ctx: RpcContext):
        """dfcache Import: local file → completed P2P task + scheduler
        announce (reference dfcache.go:112 Import, AnnounceTask)."""
        body = body or {}
        path = body.get("path", "")
        if not path:
            raise DfError(Code.BadRequest, "path required")
        req = self._cache_request(body)
        return await self.task_manager.import_task(
            path, req,
            persistent=bool(body.get("persistent")),
            replica_count=int(body.get("replica_count", 1)),
            ttl=float(body.get("ttl", 0)))

    async def _export_task(self, stream: ServerStream, ctx: RpcContext) -> None:
        """dfcache Export: land a cached task at an output path, pulling
        over P2P (never origin) when not local — reference dfcache.go:174."""
        body = stream.open_body or {}
        output = body.get("output", "")
        if not output:
            raise DfError(Code.BadRequest, "output required")
        req = self._cache_request(body)
        req.output = output
        req.disable_back_source = True
        async for progress in self.task_manager.start_file_task(req):
            await stream.send(progress.to_wire())

    @staticmethod
    def _cache_request(body: dict) -> "FileTaskRequest":
        """Cache-entry task identity: dfcache:// URL from the cache id, so
        import/export agree on the task id across hosts (reference dfcache
        computes the task id from the content id)."""
        from dragonfly2_tpu.daemon.peer.task_manager import FileTaskRequest
        from dragonfly2_tpu.proto.common import UrlMeta

        cache_id = body.get("cache_id", "")
        if not cache_id:
            raise DfError(Code.BadRequest, "cache_id required")
        meta = UrlMeta(tag=body.get("tag", ""),
                       application=body.get("application", ""),
                       digest=body.get("digest", ""))
        return FileTaskRequest(url=f"dfcache://{cache_id}", output="", meta=meta)

    async def _delete_task(self, body, ctx: RpcContext):
        """Refuses while the task is running or its store is pinned by an
        active stream/upload — same safety rule storage GC applies
        (storage/manager.py skips pinned stores)."""
        task_id = (body or {}).get("task_id", "")
        if self.task_manager.is_task_running(task_id):
            return {"ok": False, "reason": "task running"}
        store = self.task_manager.storage.try_get(task_id)
        if store is not None and store.pinned:
            return {"ok": False, "reason": "task store in use"}
        self.task_manager.storage.delete_task(task_id)
        if self.task_manager.pex is not None:
            self.task_manager.pex.remove_task(task_id)
        return {"ok": True}

    async def _health(self, body, ctx: RpcContext):
        return {"ok": True, "version": "0.1.0"}

    async def _flight_report(self, body, ctx: RpcContext):
        """Flight-recorder autopsy for a task this daemon ran: the phase
        breakdown + per-piece waterfall, JSON plus the rendered text
        (dfget --explain prints the latter — identical to the
        /debug/flight/<task_id>?format=text rendering) plus the compact
        digest the scheduler's pod lens merges on an on-demand pull."""
        task_id = (body or {}).get("task_id", "")
        tf = self.task_manager.flight.get(task_id)
        if tf is None:
            raise DfError(Code.PeerTaskNotFound,
                          f"no flight data for task {task_id}")
        report = flightlib.analyze(tf)
        return {"report": report,
                "text": flightlib.render_waterfall(report),
                "digest": flightlib.digest(tf)}

    async def _pod_timeline(self, body, ctx: RpcContext):
        """dfget --pod: proxy the merged cross-host timeline from the
        scheduler (the daemon owns the ring client; dfget only has the
        unix socket)."""
        sc = self.task_manager.scheduler_client
        if sc is None:
            raise DfError(Code.SchedError,
                          "no scheduler configured on this daemon")
        task_id = (body or {}).get("task_id", "")
        return await sc.unary(task_id, "Scheduler.PodTimeline",
                              {"task_id": task_id}, timeout=15.0,
                              idempotent=True)

    # -- peer service ------------------------------------------------------

    def _piece_snapshot(self, task_id: str) -> dict | None:
        store = self.task_manager.storage.try_get(task_id)
        if store is None:
            return None
        m = store.metadata
        return {
            "pieces": sorted(m.pieces.keys()),
            "total_piece_count": m.total_piece_count,
            "content_length": m.content_length,
            "piece_size": m.piece_size,
            "done": m.done,
            "digests": {n: p.digest for n, p in m.pieces.items() if p.digest},
        }

    async def _sync_piece_tasks(self, stream: ServerStream, ctx: RpcContext) -> None:
        """Serve piece availability to a child peer, pushing updates as
        pieces land (reference rpcserver.go:277 SyncPieceTasks +
        subscriber.go push)."""
        body = stream.open_body or {}
        task_id = body.get("task_id", "")
        snapshot = self._piece_snapshot(task_id)
        running = self.task_manager.is_task_running(task_id)
        if snapshot is None and not running:
            raise DfError(Code.StorageTaskNotFound, f"task {task_id} not on this peer")
        broker = self.task_manager.broker
        q = broker.subscribe(task_id)

        async def drain_keepalives() -> None:
            # Children send {interested: true} keep-alives on idle streams;
            # without a reader they would pool in the stream inbox for the
            # download's lifetime.
            while await stream.recv() is not None:
                pass

        drainer = asyncio.ensure_future(drain_keepalives())
        try:
            if snapshot is not None:
                await stream.send(snapshot)
                if snapshot["done"]:
                    return
            while True:
                event = await q.get()
                if event.failed:
                    raise DfError(Code.ClientPieceDownloadFail,
                                  "parent download failed")
                await stream.send({
                    "pieces": event.piece_nums,
                    "total_piece_count": event.total_piece_count,
                    "content_length": event.content_length,
                    "piece_size": event.piece_size,
                    "done": event.done,
                    "digests": event.digests,
                })
                if event.done:
                    return
        finally:
            drainer.cancel()
            broker.unsubscribe(task_id, q)

    async def _get_piece_tasks(self, body, ctx: RpcContext):
        """One-shot piece listing (reference rpcserver.go:160 GetPieceTasks)."""
        task_id = (body or {}).get("task_id", "")
        snapshot = self._piece_snapshot(task_id)
        if snapshot is None:
            raise DfError(Code.StorageTaskNotFound, f"task {task_id} not on this peer")
        return snapshot

    async def _trigger_download(self, body, ctx: RpcContext):
        """Scheduler asks this (seed) daemon to fetch a task from origin
        (reference seeder.go:56 ObtainSeeds / v2 DownloadTask)."""
        spec = body or {}
        if not spec.get("url"):
            raise DfError(Code.BadRequest, "url required")
        if spec.get("range"):
            # Validate BEFORE the ACK: a malformed span would otherwise
            # kill the spawned seed task with an unretrieved ValueError
            # while the triggering job burns its full wait timeout
            # against a task that never existed.
            try:
                spec["range"] = Range.normalize_header(spec["range"])
            except ValueError as e:
                raise DfError(Code.BadRequest,
                              f"bad range {spec.get('range')!r}: {e}")
        task_id = spec.get("task_id", "")
        already = bool(task_id and
                       self.task_manager.storage.find_completed_task(task_id) is not None)
        if (spec.get("device") == "tpu"
                or not (task_id and self.task_manager.is_task_running(task_id))):
            # Runs even when complete: the announce-only fast path re-reports
            # local pieces so the scheduler can hand this seed out as parent.
            # device=tpu triggers ALWAYS enter start_seed_task — its dedup
            # waits for an in-flight plain seed and still lands the HBM
            # copy; skipping here would swallow the device request.
            aio.spawn(self.task_manager.start_seed_task(spec))
        return {"ok": True, "already_complete": already}
