"""Daemon drpc server: download service (unix sock) + peer service (TCP).

Reference: client/daemon/rpcserver/rpcserver.go — Download streaming file
task (:388), SyncPieceTasks serving children (:277), GetPieceTasks (:160),
StatTask/DeleteTask (:847+). The download service faces dfget on the local
host; the peer service faces other daemons (stage 3).
"""

from __future__ import annotations

from dragonfly2_tpu.daemon.peer.task_manager import FileTaskRequest, TaskManager
from dragonfly2_tpu.pkg import dflog
from dragonfly2_tpu.pkg.errors import Code, DfError
from dragonfly2_tpu.pkg.piece import Range
from dragonfly2_tpu.pkg.types import NetAddr
from dragonfly2_tpu.proto.common import UrlMeta
from dragonfly2_tpu.rpc import RpcContext, Server, ServerStream

log = dflog.get("daemon.rpcserver")


class DaemonRpcServer:
    def __init__(self, task_manager: TaskManager):
        self.task_manager = task_manager
        self.download_server = Server("daemon.download")
        self.peer_server = Server("daemon.peer")
        self._register()

    def _register(self) -> None:
        self.download_server.register_stream("Daemon.Download", self._download)
        self.download_server.register_unary("Daemon.StatTask", self._stat_task)
        self.download_server.register_unary("Daemon.DeleteTask", self._delete_task)
        self.download_server.register_unary("Daemon.Health", self._health)

    async def serve_download(self, addr: NetAddr) -> None:
        await self.download_server.serve(addr)

    async def serve_peer(self, addr: NetAddr) -> None:
        await self.peer_server.serve(addr)

    async def close(self) -> None:
        await self.download_server.close()
        await self.peer_server.close()

    # -- handlers ----------------------------------------------------------

    async def _download(self, stream: ServerStream, ctx: RpcContext) -> None:
        """One file download; progress frames stream back to dfget
        (reference rpcserver.go:388 Download → :740 download)."""
        body = stream.open_body or {}
        url = body.get("url", "")
        output = body.get("output", "")
        if not url or not output:
            raise DfError(Code.BadRequest, "url and output are required")
        req = FileTaskRequest(
            url=url,
            output=output,
            meta=UrlMeta.from_wire(body.get("meta")),
            disable_back_source=body.get("disable_back_source", False),
        )
        if req.meta.range:
            req.range = Range.parse_http(req.meta.range)
        async for progress in self.task_manager.start_file_task(req):
            await stream.send(progress.to_wire())

    async def _stat_task(self, body, ctx: RpcContext):
        """Local task presence/completeness (reference rpcserver.go:847)."""
        task_id = (body or {}).get("task_id", "")
        store = self.task_manager.storage.try_get(task_id)
        if store is None:
            raise DfError(Code.PeerTaskNotFound, f"task {task_id} not found")
        m = store.metadata
        return {
            "task_id": m.task_id,
            "done": m.done,
            "content_length": m.content_length,
            "piece_count": len(m.pieces),
            "total_piece_count": m.total_piece_count,
            "digest": m.digest,
        }

    async def _delete_task(self, body, ctx: RpcContext):
        task_id = (body or {}).get("task_id", "")
        self.task_manager.storage.delete_task(task_id)
        return {"ok": True}

    async def _health(self, body, ctx: RpcContext):
        return {"ok": True, "version": "0.1.0"}
