"""Daemon configuration tree.

Reference: client/config/peerhost.go:46-85 (DaemonOption: scheduler, host,
download, upload, proxy, objectStorage, storage, announcer...) with YAML
loading (:91-110). Kept as nested dataclasses with a YAML/dict loader.
"""

from __future__ import annotations

import os
import socket
from dataclasses import dataclass, field

import yaml

from dragonfly2_tpu.pkg.dfpath import Dfpath
from dragonfly2_tpu.pkg.prof import ProfConfig
from dragonfly2_tpu.pkg.types import HostType, parse_size


def _local_ip() -> str:
    # UDP connect trick: no traffic actually sent.
    try:
        s = socket.socket(socket.AF_INET, socket.SOCK_DGRAM)
        s.connect(("10.255.255.255", 1))
        ip = s.getsockname()[0]
        s.close()
        return ip
    except OSError:
        return "127.0.0.1"


@dataclass
class HostOption:
    hostname: str = field(default_factory=socket.gethostname)
    ip: str = field(default_factory=_local_ip)
    idc: str = ""               # for TPU: the pod/cluster identifier
    location: str = ""          # "zone|pod|slice|host" affinity path
    tpu_slice: str = ""         # slice name within the pod (ICI domain)
    tpu_worker_index: int = -1  # worker index within the slice


@dataclass
class SchedulerOption:
    addrs: list[str] = field(default_factory=list)  # "host:port" drpc
    schedule_timeout: float = 30.0
    disable_auto_back_source: bool = False
    max_schedule_attempts: int = 5


@dataclass
class DownloadOption:
    rate_limit: int = 0             # bytes/sec, 0 = unlimited
    traffic_shaper: str = "plain"   # plain | sampling (reference trafficShaperType)
    piece_concurrency: int = 4      # origin range-group concurrency
    parent_concurrency: int = 4     # concurrent parent piece workers
    unix_sock: str = ""             # download gRPC analog (dfget attach)
    peer_port: int = 0              # TCP drpc for other peers (sync pieces)
    calculate_digest: bool = True
    prefetch: bool = False          # prefetch whole task on ranged requests
    concurrent_min_length: int = 32 << 20
    # Max pieces per coalesced pieces_finished announce message. The cap
    # is adaptive at the conductor: idle traffic still flushes single
    # reports immediately (latency path), backlog grows batches toward
    # this knob and recovery re-reports drain in knob-sized messages.
    report_batch: int = 32


@dataclass
class UploadOption:
    port: int = 0                   # HTTP piece upload server, 0 = ephemeral
    rate_limit: int = 0


@dataclass
class StorageOpt:
    task_ttl: float = 3 * 3600.0
    disk_gc_threshold: int = 0
    keep_storage: bool = True
    write_buffer_size: int = 4 << 20
    # Idle seconds before an un-expired store drops its data-file fd
    # (lazily reopened). 0 = follow gc_interval.
    fd_idle_close: float = 0.0


@dataclass
class ProxyOption:
    enabled: bool = False
    port: int = 0
    registry_mirror: str = ""       # remote registry URL to mirror
    rules: list[dict] = field(default_factory=list)  # {regex, use_dragonfly, direct}
    white_list_ports: list[int] = field(default_factory=lambda: [443, 80])
    max_concurrency: int = 0
    # HTTPS interception (reference proxy.go:471 handleHTTPS +
    # proxy_sni.go): terminate CONNECT tunnels with CA-forged leaf certs
    # so HTTPS registry pulls ride P2P. With empty cert paths a CA is
    # generated and persisted under the daemon work home ("ca/").
    hijack_https: bool = False
    ca_cert: str = ""               # PEM path of operator-supplied CA cert
    ca_key: str = ""                # PEM path of its private key
    hijack_hosts: list[str] = field(default_factory=list)  # regexes, [] = all
    sni_enabled: bool = False       # direct-TLS SNI listener
    sni_port: int = 0
    sni_hijack: bool = False        # terminate+serve instead of splice


@dataclass
class ObjectStorageOption:
    enabled: bool = False
    port: int = 0
    max_replicas: int = 3
    backend: str = "fs"             # fs | s3 | gcs | oss | obs
    # Backend constructor kwargs: fs {root}, s3/oss/obs {endpoint,
    # access_key, secret_key, region}, gcs {endpoint, project}.
    backend_options: dict = field(default_factory=dict)


@dataclass
class PexOption:
    """Gossip peer exchange (reference client/daemon/pex,
    peerExchange option peerhost.go:84)."""

    enabled: bool = False
    port: int = 0                   # UDP gossip port, 0 = ephemeral
    seeds: list[str] = field(default_factory=list)  # "host:port" bootstrap
    # Shared cluster secret: when set, every gossip datagram carries an
    # HMAC and unauthenticated packets are dropped (the role memberlist's
    # cluster encryption key plays in the reference).
    secret: str = ""


@dataclass
class QoSOption:
    """Tenant QoS plane (dragonfly2_tpu/qos): weighted-fair piece
    dispatch across concurrent tasks + per-tenant upload buckets under
    the daemon-wide cap. Off by default — with it on, piece serving
    stays on the aiohttp path (per-tenant accounting and limiting live
    there, same posture as ``upload.rate_limit > 0``)."""

    enabled: bool = False
    # WFQ gate slots shared by ALL tasks' piece workers; 0 = 2x
    # download.parent_concurrency, so a single task never feels the gate.
    dispatch_capacity: int = 0
    # Floor share of upload.rate_limit any one tenant keeps when many
    # are active (the traffic shaper's MIN_SHARE_FRACTION idiom).
    upload_min_share_fraction: float = 0.1


@dataclass
class TPUSinkOption:
    """--device=tpu sink: land verified pieces into TPU HBM as they
    verify (daemon/peer/device_sink.DeviceSinkManager; no reference
    analog — BASELINE.json north star). Requests opt in per task with
    ``device="tpu"`` (dfget --device tpu)."""

    enabled: bool = False
    mesh_shape: list[int] = field(default_factory=list)  # for shard_to_mesh
    batch_pieces: int = 8       # pieces staged per device dispatch
    max_tasks: int = 4          # concurrent HBM-resident tasks


@dataclass
class DaemonConfig:
    host: HostOption = field(default_factory=HostOption)
    scheduler: SchedulerOption = field(default_factory=SchedulerOption)
    download: DownloadOption = field(default_factory=DownloadOption)
    upload: UploadOption = field(default_factory=UploadOption)
    storage: StorageOpt = field(default_factory=StorageOpt)
    proxy: ProxyOption = field(default_factory=ProxyOption)
    object_storage: ObjectStorageOption = field(default_factory=ObjectStorageOption)
    pex: PexOption = field(default_factory=PexOption)
    tpu_sink: TPUSinkOption = field(default_factory=TPUSinkOption)
    qos: QoSOption = field(default_factory=QoSOption)
    # Runtime observatory (pkg/prof): always-on sampling profiler +
    # loop-lag probe + GC observatory behind /debug/prof*, plus the
    # daemon-side loop_lag SLO at /debug/slo.
    prof: ProfConfig = field(default_factory=ProfConfig)
    work_home: str = ""
    host_type: str = "normal"       # normal|super|strong|weak (seed tiers)
    alive_time: float = 0.0         # 0 = forever
    gc_interval: float = 60.0
    metrics_port: int = 0
    manager_addr: str = ""          # manager drpc for dynconfig (stage 4)
    seed_peer: bool = False
    # Flight-recorder post-mortem bundles kept on disk (newest-N rotation
    # in pkg/flight; a crash-looping task must not fill the log volume).
    flight_keep_bundles: int = 32
    # Chaos/test knob: skew every wall stamp this daemon reports (flight
    # start_wall, announce clock samples) by this many seconds — the pod
    # lens's clock alignment must then RECOVER the skew, and the e2e pins
    # that the reported error bound covers it.
    clock_offset_s: float = 0.0

    def __post_init__(self):
        if not self.work_home:
            self.work_home = Dfpath().root

    @property
    def dfpath(self) -> Dfpath:
        return Dfpath(self.work_home)

    @property
    def unix_sock(self) -> str:
        """Resolved lazily so work_home changes after construction move the
        socket with them."""
        return self.download.unix_sock or self.dfpath.daemon_sock

    @property
    def host_type_enum(self) -> HostType:
        if self.seed_peer and self.host_type == "normal":
            return HostType.SUPER_SEED
        return HostType.parse(self.host_type)

    @classmethod
    def from_dict(cls, d: dict) -> "DaemonConfig":
        cfg = cls()
        _merge_dataclass(cfg, d)
        cfg.__post_init__()
        return cfg

    @classmethod
    def load(cls, path: str) -> "DaemonConfig":
        with open(path) as f:
            data = yaml.safe_load(f) or {}
        return cls.from_dict(data)


def _merge_dataclass(obj, d: dict) -> None:
    """Recursive dict→dataclass merge; size strings like '100MiB' accepted
    for int fields ending in _limit/_size/_threshold."""
    for key, value in d.items():
        if not hasattr(obj, key):
            continue
        current = getattr(obj, key)
        if hasattr(current, "__dataclass_fields__") and isinstance(value, dict):
            _merge_dataclass(current, value)
        elif isinstance(current, int) and not isinstance(current, bool) and isinstance(value, str):
            setattr(obj, key, parse_size(value))
        else:
            setattr(obj, key, value)
