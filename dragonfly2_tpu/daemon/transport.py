"""P2P transport: turns HTTP GETs into stream peer tasks.

Reference: client/daemon/transport/transport.go — RoundTrip (:230) decides
P2P vs direct via regex rules, roundTripWithDragonfly (:259) starts a stream
task and plumbs range/tag/application through. Here the "RoundTripper" is an
async fetch() used by the proxy and the object-storage gateway.
"""

from __future__ import annotations

import os
import re
from dataclasses import dataclass, field

from dragonfly2_tpu.daemon.peer.task_manager import StreamTaskRequest, TaskManager
from dragonfly2_tpu.pkg import dflog
from dragonfly2_tpu.pkg.errors import Code, DfError
from dragonfly2_tpu.pkg.piece import Range
from dragonfly2_tpu.proto.common import UrlMeta

log = dflog.get("daemon.transport")

# Headers the reference strips/interprets before task identity is computed
# (transport.go pickHeader: tag/application/filter ride custom headers).
HDR_TAG = "X-Dragonfly-Tag"
HDR_APPLICATION = "X-Dragonfly-Application"
HDR_FILTER = "X-Dragonfly-Filter"
HDR_NO_P2P = "X-Dragonfly-No-P2P"

# Registry blob URLs are content-addressed -> always safe to P2P.
_BLOB_RE = re.compile(r"/v2/.+/blobs/sha256:[0-9a-f]{64}")


def _pop_header(headers: dict[str, str], name: str, default: str = "") -> str:
    """Case-insensitive pop (HTTP/2-originating hops lowercase names)."""
    lname = name.lower()
    for k in list(headers):
        if k.lower() == lname:
            return headers.pop(k)
    return default


@dataclass
class ProxyRule:
    """Reference config proxy rule: regex + direct/useHTTPS flags."""

    regex: str
    direct: bool = False           # match -> bypass P2P
    use_https: bool = False        # rewrite scheme when hijacking
    _compiled: re.Pattern = field(init=False, repr=False, default=None)

    def __post_init__(self):
        self._compiled = re.compile(self.regex)

    def matches(self, url: str) -> bool:
        return bool(self._compiled.search(url))


def rules_from_config(rule_dicts: list[dict]) -> list[ProxyRule]:
    """Build proxy rules from config dicts {regex, use_dragonfly, direct}.
    A rule bypasses P2P when direct=true OR use_dragonfly=false (reference
    proxy.go shouldUseDragonfly honors both spellings)."""
    return [ProxyRule(regex=r.get("regex", ""),
                      direct=bool(r.get("direct", False))
                      or not r.get("use_dragonfly", True),
                      use_https=bool(r.get("use_https", False)))
            for r in rule_dicts if r.get("regex")]


class P2PTransport:
    def __init__(self, task_manager: TaskManager, *, rules: list[ProxyRule] | None = None,
                 default_tag: str = ""):
        self.task_manager = task_manager
        self.rules = rules or []
        self.default_tag = default_tag

    def should_use_p2p(self, method: str, url: str,
                       headers: dict[str, str] | None = None) -> bool:
        """shouldUseDragonfly (reference proxy.go:662-699): only GETs; rules
        decide, registry blobs always qualify."""
        if method.upper() != "GET":
            return False
        if headers and any(k.lower() == HDR_NO_P2P.lower()
                           and str(v).lower() in ("1", "true")
                           for k, v in headers.items()):
            return False
        for rule in self.rules:
            if rule.matches(url):
                return not rule.direct
        return bool(_BLOB_RE.search(url))

    @staticmethod
    def sendfile_window(attrs: dict, rng, total: int):
        """(store, offset, count) when a fetch's response can be served by
        sendfile straight off the local store's data file — the fast path
        shared by the proxy and the object gateway. Two eligible shapes:

          - COMPLETED store (all pieces landed, file exactly the content):
            whole-object or any in-bounds range.
          - IN-PROGRESS store + a range whose bytes have all LANDED
            (``covers_range``): pieces sit at their final offsets and
            landed bytes are immutable, so the window rides sendfile while
            the rest of the task is still downloading — a parent
            mid-download never iterates served bytes through Python.

        None when the bytes must stream through the piece iterator: no
        store exposed, unknown total, an uncovered window, or an empty one
        (loop.sendfile rejects count=0, and a 0-byte body needs no fast
        path). Callers own pin/unpin around the actual send."""
        store = attrs.get("local_store")
        if store is None or total < 0:
            return None
        m = store.metadata
        complete = False
        if m.done or store.is_complete():
            # File size must equal the content exactly: a sparse tail or a
            # stale truncation would corrupt whole-object Content-Length.
            try:
                complete = os.path.getsize(store.data_path) == total
            except OSError:
                return None
        if rng is None:
            return (store, 0, total) if complete and total > 0 else None
        count = min(rng.length, max(total - rng.start, 0))
        if count <= 0:
            return None
        if complete or store.covers_range(rng.start, count):
            return store, rng.start, count
        return None

    async def fetch(self, url: str, headers: dict[str, str] | None = None):
        """Fetch through the P2P fabric. Returns (attrs, body_iterator).
        Raises DfError on task failure before the first byte."""
        headers = dict(headers or {})
        rng = None
        range_header = _pop_header(headers, "Range")
        if range_header:
            try:
                rng = Range.parse_http(range_header)
            except ValueError as e:
                raise DfError(Code.BadRequest, f"bad range: {e}")
        meta = UrlMeta(
            tag=_pop_header(headers, HDR_TAG, self.default_tag),
            application=_pop_header(headers, HDR_APPLICATION),
            filter=_pop_header(headers, HDR_FILTER),
            header=headers,
        )
        req = StreamTaskRequest(url=url, meta=meta, range=rng)
        # attrs["range"] is set by the task manager: open-ended ranges come
        # back resolved against the content length when it is known.
        return await self.task_manager.start_stream_task(req)
