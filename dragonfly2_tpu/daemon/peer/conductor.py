"""Peer task conductor: orchestrates one P2P download.

Reference: client/daemon/peer/peertask_conductor.go (1636 LoC) — the
concurrency web tying together: the scheduler AnnouncePeer stream
(register :255, receive loop :673), the P2P piece pull (pullPieces :533)
with N download workers (:1009-1077 init, :1043 downloadPieceWorker hot
loop), per-parent synchronizer streams, back-to-source fallback
(backSource :503), piece result reporting (:1252-1314) and completion
(done/fail :1378+).

Flow:
  run() → announce register → dispatch on scheduler response:
    empty_task        → create empty content, finish
    need_back_source  → piece_manager.download_source, announcing pieces
    normal_task       → sync parents, spawn piece workers, fetch pieces
                        over HTTP, report results, reschedule on starvation
"""

from __future__ import annotations

import asyncio
import os
from collections import deque

import msgpack

from dragonfly2_tpu.daemon.peer.piece_dispatcher import (
    PieceAssignment,
    PieceDispatcher,
    parent_key,
)
from dragonfly2_tpu.daemon.peer.piece_downloader import (
    PieceDownloader,
    failure_reason,
)
from dragonfly2_tpu.daemon.peer.piece_manager import PieceManager
from dragonfly2_tpu.daemon.peer.synchronizer import PieceTaskSynchronizer
from dragonfly2_tpu.pkg import dflog, metrics
from dragonfly2_tpu.pkg import flight as flightlib
from dragonfly2_tpu.pkg import retry as retrylib
from dragonfly2_tpu.pkg.errors import Code, DfError
from dragonfly2_tpu.pkg.piece import PieceInfo, Range, compute_piece_count
from dragonfly2_tpu.pkg.ratelimit import Limiter
from dragonfly2_tpu.proto import reportcodec
from dragonfly2_tpu import qos as qoslib
from dragonfly2_tpu.storage.local_store import LocalTaskStore

log = dflog.get("peer.conductor")

PIECE_DOWNLOAD_COUNT = metrics.counter(
    "peer_piece_download_total", "P2P piece downloads", ("result",))
BACK_SOURCE_COUNT = metrics.counter(
    "peer_back_source_total", "Tasks that fell back to origin")
# Typed degradation telemetry: every piece failure by reason code, parent
# quarantine entries by the reason that tipped them, and announce-stream
# recoveries. These are what the chaos e2e (and operators) read to see
# WHICH degradation path fired, not just that something failed.
PIECE_FAIL_REASON = metrics.counter(
    "peer_piece_failures_total",
    "P2P piece failures by typed reason code", ("reason",))
PARENT_QUARANTINE_COUNT = metrics.counter(
    "peer_parent_quarantine_total",
    "Parents entering the daemon-wide quarantine, by tipping reason",
    ("reason",))
ANNOUNCE_RECONNECT_COUNT = metrics.counter(
    "peer_announce_reconnects_total",
    "Mid-download announce-stream recovery attempts", ("result",))
# The striped-broadcast yardstick: P2P piece bytes split by parent
# locality — intra rides the ICI fabric, cross is real DCN traffic,
# unlabeled means either end lacked TPU coordinates. fanout_bench --stripe
# scrapes this per daemon for the per-host-DCN-bytes acceptance bound.
PIECE_BYTES = metrics.counter(
    "peer_piece_bytes_total",
    "P2P piece bytes downloaded, by parent ICI locality",
    ("locality",))
# Announce-wire weight: serialized msgpack bytes this daemon exchanged
# with the scheduler over announce streams. The packed-report encoding
# exists to shrink ``sent`` — ingest_wire_bench publishes the ratio.
ANNOUNCE_BYTES = metrics.counter(
    "peer_announce_bytes_total",
    "Serialized announce-stream traffic with the scheduler, by direction "
    "(sent = reports/registers, recv = schedule pushes and answers)",
    ("direction",))

MAX_RESCHEDULES = 8


class PeerTaskConductor:
    def __init__(
        self,
        *,
        task_id: str,
        peer_id: str,
        url: str,
        store: LocalTaskStore,
        scheduler_client,
        piece_manager: PieceManager,
        host_info: dict,
        meta: dict | None = None,
        is_seed: bool = False,
        piece_parallelism: int = 4,
        limiter: Limiter | None = None,
        on_piece=None,
        disable_back_source: bool = False,
        local_range_source=None,
        quarantine=None,
        flight=None,
        wfq=None,
        report_batch: int = 32,
    ):
        self.task_id = task_id
        self.peer_id = peer_id
        self.url = url
        self.store = store
        self.scheduler_client = scheduler_client
        self.piece_manager = piece_manager
        self.host_info = host_info
        self.meta = meta or {}
        self.is_seed = is_seed
        self.piece_parallelism = piece_parallelism
        self.limiter = limiter or Limiter()
        self.on_piece = on_piece
        self.disable_back_source = disable_back_source
        # async (store, on_piece) -> bool: fill a ranged store from a
        # LOCAL covering parent task instead of origin (task_manager
        # import_range_from_local_parent) — the warm-seed path for
        # scheduler-triggered ranged seeds.
        self.local_range_source = local_range_source
        # Ranged task (task id encodes the range): the content of THIS task
        # is the slice, and a back-source demotion must fetch exactly it —
        # dropping the range here once fetched (and emitted) the whole
        # object for a 1 MiB request. Derived from the ONE range
        # representation (meta["range"], also what registers with the
        # scheduler) so no caller can desynchronize the two.
        range_header = self.meta.get("range", "")
        self.content_range = (Range.parse_http(range_header)
                              if range_header else None)

        # Daemon-wide bad-parent quarantine (pkg/quarantine), shared across
        # conductors via the task manager; None = no quarantine filter.
        self.quarantine = quarantine
        # Flight recorder: this task's bounded event ring (pkg/flight) —
        # every choke point below stamps it so /debug/flight can autopsy
        # the download after the fact. Injectable so embedded multi-daemon
        # tests can keep per-daemon recorders (and per-daemon wall
        # offsets); defaults to the process-wide recorder.
        self.flight = flight if flight is not None \
            else flightlib.for_task(task_id)
        # Announce-path clock samples ([t0, t1, sched_echo] on this
        # host's anchored wall clock): each register/reconnect answer
        # that carries the scheduler's ``sched_wall`` yields one; they
        # ship inside the terminal flight digest so the scheduler's pod
        # lens can align this host's timeline. Bounded.
        self._clock_samples: list = []
        self.dispatcher = PieceDispatcher(quarantine=quarantine,
                                          flight=self.flight)
        self.downloader = PieceDownloader()
        # Tenant QoS plane (dragonfly2_tpu/qos): the daemon-wide WFQ
        # dispatch gate shared across conductors (None = ungated), plus
        # this task's attribution identity. The normalized tenant rides
        # every upstream piece request as a query param so the serving
        # peer can account and rate-split per tenant.
        self.wfq = wfq
        self.tenant = qoslib.normalize_tenant(self.meta.get("tenant"))
        self._qos_priority = int(self.meta.get("priority", 3) or 3)
        self.synchronizer: PieceTaskSynchronizer | None = None
        # Striped slice broadcast: this host's ICI domain, and the bytes
        # pulled per parent locality (intra = same slice / ICI, cross =
        # DCN, unlabeled = no coordinates on one end). The task manager
        # snapshots locality_bytes for benches/tests.
        self.own_slice = (host_info or {}).get("tpu_slice", "") or ""
        self.locality_bytes = {"intra": 0, "cross": 0, "unlabeled": 0}
        self._stream = None
        self._reschedules = 0
        self._from_p2p = False
        self._report_lock = asyncio.Lock()
        self._resched_lock = asyncio.Lock()
        self._sched_update = asyncio.Event()   # receiver loop applied a push
        self._need_back_source = False
        # Piece-finished reports coalesce into pieces_finished batches: the
        # first report flushes immediately (the scheduler's "peer became a
        # usable parent" wakeup must not lag), subsequent ones within the
        # flush window ride one message. Peer-to-peer piece DISCOVERY does
        # not ride these reports at all (the synchronizer syncs piece maps
        # parent-direct), so batching costs scheduling metadata freshness
        # only, bounded by the window. The cap is adaptive by
        # construction: idle traffic flushes singles (wait <= 0 on the
        # first report), backlog grows batches toward ``report_batch``
        # (DaemonConfig download.report_batch) and a recovery re-report
        # drains in report_batch-sized messages instead of one giant one.
        self.report_batch = max(1, int(report_batch))
        self._pending_reports: deque = deque()
        self._flush_task: asyncio.Task | None = None
        self._last_flush = 0.0
        # Wire capability learned from stamped scheduler answers: packed
        # piece-report batches + resume bitmaps (proto/reportcodec).
        # Refreshed on every register/reconnect answer so failover to an
        # older scheduler downgrades the encoding.
        self._packed_ok = False
        # Mid-download announce-stream recovery state: the register body
        # (saved for re-registration), the serialized-reconnect lock, and
        # the terminal flag that stops recovery racing teardown.
        self._open_body: dict | None = None
        self._announce_lock = asyncio.Lock()
        self._announce_done = False
        self._stream_reconnects = 0
        # Ring-rebuild re-homing: set when dynconfig moved this task's
        # ownership to a different live member — the next successful
        # reconnect books as result="rehomed" instead of "ok".
        self._rehome_pending = False

    # ------------------------------------------------------------------ #

    async def run(self) -> None:
        """Complete the task into self.store, or raise DfError."""
        open_body = {
            "host": self.host_info,
            "peer_id": self.peer_id,
            "task_id": self.task_id,
            "url": self.url,
            "tag": self.meta.get("tag", ""),
            "application": self.meta.get("application", ""),
            "digest": self.meta.get("digest", ""),
            "filters": self.meta.get("filters") or [],
            "header": self.meta.get("header") or {},
            "priority": self.meta.get("priority", 3),
            "tenant": self.meta.get("tenant", ""),
            "range": self.meta.get("range", ""),
            "is_seed": self.is_seed,
            "disable_back_source": self.disable_back_source,
            "pod_broadcast": bool(self.meta.get("pod_broadcast")),
        }
        self._open_body = open_body
        # Registration phase: any transport failure BEFORE a scheduler
        # answer arrives (connect refused, connect-then-drop, silence)
        # demotes to back-to-source instead of failing the task (reference
        # behavior — the piece store still gets populated for reuse/PEX,
        # and clients without source-fallback permission still succeed).
        # A scheduler-SENT rejection (schedule_failed) stays fatal via the
        # dispatch below.
        # Ring-rebuild observation (dynconfig scheduler-set changes):
        # when ownership moves to a different LIVE member, drain and
        # re-home instead of riding the stale shard until it dies.
        watch = getattr(self.scheduler_client, "watch_ring", None)
        if watch is not None:
            watch(self.task_id, self._on_ring_change)
        msg = None
        register_error = "scheduler closed stream at register"
        self.flight.record(flightlib.EV_REGISTER)
        t0_clock = self.flight.wall_now()
        try:
            self._stream = await self.scheduler_client.open_announce_stream(
                open_body)
            reg: dict = {"type": "register"}
            if self.store.metadata.pieces:
                # Daemon restart with a partial store (or a re-run over
                # persisted pieces): the scheduler rebuilds our state
                # instead of treating us as fresh.
                reg["resume"] = self._resume_state()
            await self._stream.send(reg)
            msg = await self._stream.recv(timeout=60.0)
            self._note_clock_sample(t0_clock, msg)
        except DfError as e:
            if self.disable_back_source:
                await self._teardown()
                raise
            register_error = str(e)
        if msg is None:
            self.flight.record(flightlib.EV_SCHEDULED, -1, 0.0, "unavailable")
            if not self.disable_back_source:
                log.warning("scheduler unavailable at register; "
                            "degrading to back-to-source",
                            task=self.task_id[:16], error=register_error)
            if self.disable_back_source:
                await self._teardown()
                raise DfError(Code.SchedError,
                              "scheduler unavailable at register")
            try:
                await self._back_source()
            finally:
                await self._teardown()
            return
        self.flight.record(flightlib.EV_SCHEDULED, -1, 0.0,
                           str(msg.get("type", "")))
        try:
            await self._dispatch_schedule(msg)
        except BaseException:
            await self._safe_send({"type": "download_failed"})
            raise
        finally:
            await self._teardown()

    async def _dispatch_schedule(self, msg: dict) -> None:
        """Dispatch the scheduler's answer to a register/reschedule."""
        kind = msg.get("type")
        if kind == "empty_task":
            await self._finish_empty()
        elif kind == "tiny_task":
            await self._finish_tiny(msg)
        elif kind == "small_task":
            await self._finish_small(msg)
        elif kind == "need_back_source":
            await self._back_source()
        elif kind == "normal_task":
            await self._pull_pieces_p2p(msg)
        elif kind == "schedule_failed":
            raise DfError(Code.SchedError, msg.get("reason", "schedule failed"))
        else:
            raise DfError(Code.SchedError, f"unexpected scheduler response {kind}")

    @property
    def from_p2p(self) -> bool:
        return self._from_p2p

    # -- empty (reference storeEmptyPeerTask :595) -------------------------

    async def _finish_empty(self) -> None:
        self.store.update_task(content_length=0, total_piece_count=0, piece_size=1)
        await self._safe_send({"type": "download_finished", "content_length": 0})

    # -- tiny: content inlined by the scheduler (ref storeTinyPeerTask :569)

    async def _finish_tiny(self, msg: dict) -> None:
        content = bytes(msg.get("content") or b"")
        self._from_p2p = True
        self.store.update_task(content_length=len(content),
                               piece_size=max(len(content), 1),
                               total_piece_count=1)
        if 0 not in self.store.metadata.pieces:
            self.store.write_piece(0, content)
        await self._safe_send({"type": "download_finished",
                               "content_length": len(content),
                               "piece_size": max(len(content), 1),
                               "total_piece_count": 1})

    # -- small: one direct parent + piece 0 (ref pullSinglePiece :904) -----

    async def _finish_small(self, msg: dict) -> None:
        task_wire = msg.get("task") or {}
        parent = msg.get("parent") or {}
        piece = PieceInfo.from_wire(msg.get("piece") or {})
        host = parent.get("host") or {}
        self._apply_task_meta(task_wire)
        try:
            if piece.piece_num not in self.store.metadata.pieces:
                chunks, size, cost_ms, received_digest = \
                    await self.downloader.download_piece(
                        host.get("ip", ""), host.get("upload_port", 0),
                        self.task_id, piece.piece_num,
                        src_peer_id=parent.get("id", ""),
                        expected_size=piece.range_size,
                        expected_digest=piece.digest)
                await self.limiter.wait(size)
                rec = self.store.write_piece_chunks(
                    piece.piece_num, chunks, received_digest,
                    expected_digest=piece.digest, cost_ms=cost_ms)
                self.flight.record(flightlib.EV_LANDED, piece.piece_num,
                                   float(cost_ms))
                await self._report_piece(rec, parent_id=parent.get("id", ""))
                if self.on_piece is not None:
                    await self.on_piece(self.store, rec)
            self._from_p2p = True
            await self._safe_send({
                "type": "download_finished",
                "content_length": self.store.metadata.content_length,
                "piece_size": self.store.metadata.piece_size,
                "total_piece_count": self.store.metadata.total_piece_count,
            })
        except DfError as e:
            # The handed-out parent was bad: ask for a reschedule and run
            # whatever the scheduler answers (normal/back-source path).
            log.warning("small-task direct pull failed, rescheduling",
                        task=self.task_id[:16], error=str(e))
            await self._safe_send({"type": "reschedule",
                                   "blocklist": [parent.get("id", "")]})
            nxt = await self._stream.recv(timeout=60.0)
            if nxt is None:
                raise DfError(Code.SchedError,
                              "scheduler closed stream after small-task retry")
            if nxt.get("type") == "small_task":
                # Don't ping-pong between bad small parents forever.
                raise DfError(Code.ClientPieceDownloadFail,
                              "small-task retry returned another direct parent")
            await self._dispatch_schedule(nxt)

    # -- back-to-source (reference backSource :503) ------------------------

    async def _back_source(self) -> None:
        self.flight.record(flightlib.EV_BACK_SOURCE)
        # Announce-only fast path: content already complete locally (seed
        # re-announce after a scheduler restart) — report pieces, no origin.
        if self.store.metadata.done and self.store.is_complete():
            m = self.store.metadata
            await self._safe_send({
                "type": "download_started",
                "content_length": m.content_length,
                "piece_size": m.piece_size,
                "total_piece_count": m.total_piece_count,
            })
            for rec in self.store.get_pieces():
                await self._report_piece(rec, parent_id="")
            await self._safe_send({
                "type": "download_finished",
                "content_length": m.content_length,
                "piece_size": m.piece_size,
                "total_piece_count": m.total_piece_count,
            })
            return

        started_sent = False

        async def on_piece(store: LocalTaskStore, rec) -> None:
            nonlocal started_sent
            if not started_sent and store.metadata.piece_size > 0:
                started_sent = True
                await self._safe_send({
                    "type": "download_started",
                    "content_length": store.metadata.content_length,
                    "piece_size": store.metadata.piece_size,
                    "total_piece_count": store.metadata.total_piece_count,
                })
            await self._report_piece(rec, parent_id="")
            if self.on_piece is not None:
                await self.on_piece(store, rec)

        # A ranged slice a LOCAL parent store covers imports warm — the
        # scheduler-triggered ranged seed on a preheated host never
        # re-touches origin. This is not a back-source: it runs BEFORE
        # the disable gate (origin stays off the table) and is neither
        # counted nor logged as one.
        imported = (self.content_range is not None
                    and self.local_range_source is not None
                    and await self.local_range_source(self.store, on_piece))
        if not imported:
            if self.disable_back_source:
                # dfget --disable-back-source / dfcache export: origin is
                # off the table, fail instead (reference
                # peertask_conductor needBackSource vs disableBackSource).
                raise DfError(Code.ClientBackSourceError,
                              "scheduler demanded back-to-source but it "
                              "is disabled")
            BACK_SOURCE_COUNT.inc()
            log.info("back-to-source", task=self.task_id[:16],
                     seed=self.is_seed)
            if LocalTaskStore.completion_digest_applies(
                    self.meta.get("digest", ""),
                    self.content_range is not None):
                # Self-computed pieces are never certifiable: the
                # completion re-hash is certain; overlap it with the
                # transfer.
                self.store.start_prefix_hasher(self.meta.get("digest", ""))
            await self.piece_manager.download_source(
                self.store, self.url, self.meta.get("header") or {},
                content_range=self.content_range,
                on_piece=on_piece, limiter=self.limiter,
            )
        await self._safe_send({
            "type": "download_finished",
            "content_length": self.store.metadata.content_length,
            "piece_size": self.store.metadata.piece_size,
            "total_piece_count": self.store.metadata.total_piece_count,
        })

    # -- P2P pull (reference pullPiecesWithP2P :552) -----------------------

    async def _pull_pieces_p2p(self, schedule_msg: dict) -> None:
        self._from_p2p = True
        self._apply_task_meta(schedule_msg.get("task") or {})
        # Dead parents need no extra hook here: the synchronizer's
        # drop_parent marks them blocked, and the next starvation pass
        # sends them in the reschedule blocklist (ref reportInvalidPeer).
        self.synchronizer = PieceTaskSynchronizer(
            self.task_id, self.peer_id, self.dispatcher,
            own_slice=self.own_slice)
        self.synchronizer.sync_parents(schedule_msg.get("parents") or [])
        self._apply_stripe(schedule_msg.get("stripe"))
        # Resume support: pieces already on disk need no re-download.
        self.dispatcher.mark_known_downloaded(self.store.metadata.pieces.keys())

        receiver = asyncio.ensure_future(self._receive_scheduler_loop())
        workers = [asyncio.ensure_future(self._piece_worker(i))
                   for i in range(self.piece_parallelism)]
        try:
            try:
                await asyncio.gather(*workers)
            except BaseException:
                # First failure cancels siblings so they can't race teardown.
                for w in workers:
                    w.cancel()
                await asyncio.gather(*workers, return_exceptions=True)
                raise
            if self._need_back_source and not self._complete():
                # Scheduler demoted us mid-flight: finish the remainder from
                # origin (pieces already on disk are skipped).
                await self._back_source()
                return
            if not self._complete():
                raise DfError(Code.ClientPieceDownloadFail,
                              f"p2p download stalled at "
                              f"{self.dispatcher.downloaded_count()} pieces")
            # A completed parent's digest map can certify the
            # completion-time re-hash skip (the store compares what each
            # piece was verified against to the map). Every done parent's
            # map is tried, and when none verifies yet the bounded wait
            # keeps running — a corrupt early finisher can't mask an
            # honest parent whose done is still in flight.
            await self._await_certification()
            await self._safe_send({
                "type": "download_finished",
                "content_length": self.store.metadata.content_length,
                "piece_size": self.store.metadata.piece_size,
                "total_piece_count": self.store.metadata.total_piece_count,
            })
        finally:
            receiver.cancel()

    async def _await_certification(self) -> bool:
        """Cold-race closer: in a fan-out the children's last pieces land
        moments before the seed's own completion gate (the seed validates
        the whole-content digest BEFORE its sync streams say done), so
        each child would pay a redundant whole-content re-hash that the
        warm path skips. Waiting — bounded near the break-even point —
        turns N children × O(content) hashing into the seed's one
        validation. No provenance change: this only gives the parent's
        done a chance to arrive on the already-open sync stream;
        store.apply_certification (the single scan-and-install point)
        decides whether the skip engages, so only a map that actually
        certifies ends the wait — a corrupt parent's done must not eat
        the budget an honest parent's in-flight done could still use.
        Returns True when a verifying map was installed."""
        if not LocalTaskStore.completion_digest_applies(
                self.meta.get("digest", ""), self.content_range is not None):
            return False  # no completion re-hash would run: nothing to save
        content = self.store.metadata.content_length
        if content <= 0:
            return False
        if not self.store.pieces_verified_against_digests():
            # Some piece landed without a verified-against digest: no
            # certified map can ever engage the skip — waiting is futile.
            return False
        loop = asyncio.get_running_loop()
        deadline = loop.time() + self._cert_wait_bound(content)
        disp = self.dispatcher
        while disp.pending_certifiers():
            remaining = deadline - loop.time()
            if remaining <= 0:
                break  # deadline-edge done still gets the final attempt
            disp.certified_event.clear()
            if self.store.apply_certification(disp.certified_digest_maps()):
                return True
            try:
                await asyncio.wait_for(disp.certified_event.wait(), remaining)
            except asyncio.TimeoutError:
                break
        return self.store.apply_certification(disp.certified_digest_maps())

    @staticmethod
    def _cert_wait_bound(content_length: int) -> float:
        """Wait budget: 50 ms done-propagation epsilon + 2× the ~1 GBps
        solo hash estimate. The 2× is deliberate: the alternative to
        waiting is N children hashing CONCURRENTLY on shared cores (each
        paying ~N× the solo cost), while the wait is idle CPU that lets
        the one certifier finish sooner — so the worst case (no done ever
        arrives) loses ~the hash cost, and the common case saves all N."""
        return min(3.0, 0.05 + 2 * content_length / 1.0e9)

    def _note_clock_sample(self, t0: float, msg: "dict | None") -> None:
        """Round-trip clock sample from a register/reconnect answer that
        carried the scheduler's ``sched_wall`` echo: t0/t1 on this host's
        anchored wall clock bracket the exchange, so the NTP midpoint
        error is bounded by (t1-t0)/2 no matter how asymmetric the two
        legs were. Ships inside the terminal flight digest."""
        if not msg:
            return
        self._note_recv(msg)
        # Capability negotiation rides the same stamped answers: refresh
        # on EVERY register/reconnect answer (not just the first) so a
        # failover to an older scheduler drops back to the dict wire.
        self._packed_ok = bool(msg.get("packed_reports"))
        echo = msg.get("sched_wall")
        if not isinstance(echo, (int, float)) or echo <= 0:
            return
        self._clock_samples.append(
            (t0, self.flight.wall_now(), float(echo)))
        del self._clock_samples[:-4]

    def _apply_stripe(self, stripe: dict | None) -> None:
        """Enter/reshuffle/exit stripe mode from a scheduler handout. The
        plan's mates ride a dedicated field (not the parent DAG — mutual
        intra-slice serving would be a DAG cycle): sync them like parents,
        marked same_slice, so non-stripe pieces fill intra-slice while the
        conductor DCN-fetches only its own stripe."""
        if stripe and int(stripe.get("slice_size", 0)) >= 2:
            self.flight.record(flightlib.EV_STRIPE, -1,
                               float(stripe["slice_size"]), "applied")
            self.dispatcher.set_stripe(int(stripe["slice_size"]),
                                       int(stripe.get("slice_rank", -1)))
            mates = stripe.get("mates") or []
            if mates and self.synchronizer is not None:
                self.synchronizer.sync_parents(mates)
            log.info("stripe plan applied", task=self.task_id[:16],
                     slice_size=stripe["slice_size"],
                     slice_rank=stripe.get("slice_rank"), mates=len(mates))
        else:
            if self.dispatcher.stripe is not None:
                self.flight.record(flightlib.EV_STRIPE, -1, 0.0, "cleared")
            self.dispatcher.clear_stripe()

    def _note_piece_failure(self, parent, err: DfError) -> str:
        """Typed failure accounting: classify the error, feed the
        daemon-wide quarantine, emit the reason-coded metric. Returns the
        reason string for the scheduler report."""
        reason = failure_reason(err)
        PIECE_FAIL_REASON.labels(reason).inc()
        if self.quarantine is not None:
            if self.quarantine.penalize(parent_key(parent), reason):
                PARENT_QUARANTINE_COUNT.labels(reason).inc()
                self.flight.record(
                    flightlib.EV_QUARANTINE, -1, 0.0,
                    f"{parent_key(parent)}|{reason}")
                log.warning("parent quarantined",
                            parent=parent.peer_id[:24],
                            endpoint=parent_key(parent), reason=reason,
                            task=self.task_id[:16])
                self.dispatcher._wakeup.set()
        return reason

    def _parent_locality(self, parent) -> str:
        if not self.own_slice or not parent.tpu_slice:
            return "unlabeled"
        if parent.same_slice or parent.tpu_slice == self.own_slice:
            return "intra"
        return "cross"

    def _note_piece_bytes(self, parent, size: int) -> None:
        if size <= 0:
            return
        key = self._parent_locality(parent)
        self.locality_bytes[key] += size
        PIECE_BYTES.labels(key).inc(size)

    def _apply_task_meta(self, task_wire: dict) -> None:
        cl = task_wire.get("content_length", -1)
        ps = task_wire.get("piece_size", 0)
        tp = task_wire.get("total_piece_count", -1)
        if cl >= 0 and ps > 0 and tp < 0:
            tp = compute_piece_count(cl, ps)
        self.store.update_task(content_length=cl if cl >= 0 else None,
                               piece_size=ps if ps > 0 else None,
                               total_piece_count=tp if tp >= 0 else None)
        self.dispatcher.content_length = self.store.metadata.content_length
        self.dispatcher.piece_size = self.store.metadata.piece_size
        if self.store.metadata.total_piece_count >= 0:
            self.dispatcher.total_piece_count = self.store.metadata.total_piece_count

    def _complete(self) -> bool:
        m = self.store.metadata
        if m.total_piece_count < 0 and self.dispatcher.total_piece_count >= 0:
            self.store.update_task(
                total_piece_count=self.dispatcher.total_piece_count,
                content_length=self.dispatcher.content_length
                if self.dispatcher.content_length >= 0 else None,
                piece_size=self.dispatcher.piece_size
                if self.dispatcher.piece_size > 0 else None,
            )
        return m.total_piece_count >= 0 and self.store.is_complete()

    async def _receive_scheduler_loop(self) -> None:
        """The ONLY reader of the scheduler stream after registration:
        applies pushed parent sets / back-source demotions and signals
        waiters (reference receivePeerPacket :673). A stream death
        MID-DOWNLOAD (scheduler crash/restart, net partition) is not
        terminal: the piece workers keep pulling from their live parents
        while this loop reconnects with ring failover, re-registers
        preserving completed pieces, and flushes the buffered reports —
        only an exhausted reconnect budget demotes to back-to-source."""
        try:
            while True:
                try:
                    msg = await self._stream.recv()
                except DfError:
                    msg = None   # stream lost: same recovery as a close
                if msg is None:
                    if self._announce_done or self._complete():
                        return
                    if await self._recover_announce_stream():
                        continue
                    self._degrade_after_scheduler_loss()
                    return
                self._note_recv(msg)
                kind = msg.get("type")
                self.flight.record(flightlib.EV_SCHED_PUSH, -1, 0.0,
                                   str(kind))
                if kind == "normal_task":
                    self._apply_task_meta(msg.get("task") or {})
                    if self.synchronizer is not None:
                        self.synchronizer.sync_parents(msg.get("parents") or [])
                    self._apply_stripe(msg.get("stripe"))
                    self._sched_update.set()
                elif kind in ("need_back_source", "schedule_failed"):
                    if kind == "need_back_source":
                        self._need_back_source = True
                    # drop_parent (not a bare blocked=True) so both waiter
                    # classes wake: dispatcher.get() AND a completion-time
                    # _await_certification that can now never be certified.
                    for pid in list(self.dispatcher.parents):
                        self.dispatcher.drop_parent(pid)
                    self._sched_update.set()
        except asyncio.CancelledError:
            pass

    # Announce-stream recovery budget: attempts per disruption. With the
    # ANNOUNCE backoff policy the whole budget spans a few seconds — long
    # enough for a scheduler restart, short enough that origin fallback
    # still beats a wedged transfer. MAX_STREAM_RECONNECTS caps the
    # task-lifetime total: a perpetually flapping scheduler must
    # eventually push the task to the degradation path, not hold the
    # receiver in a reconnect loop forever.
    RECONNECT_BUDGET = 4
    MAX_STREAM_RECONNECTS = 8

    def _resume_state(self) -> dict:
        """This task's full local state for a (re-)register: landed piece
        bitset, task geometry, the verified content digest once the store
        completed (mid-flight the per-piece digests ride the idempotent
        re-report instead), stripe membership and the pod-broadcast flag.
        A failover ring member — or a restarted scheduler — rebuilds its
        Task/Peer FSMs from this instead of treating us as fresh."""
        m = self.store.metadata
        nums = sorted(m.pieces.keys())
        resume: dict = {
            "piece_nums": nums,
            "content_length": m.content_length,
            "piece_size": m.piece_size,
            "total_piece_count": m.total_piece_count,
            "prefix_digest": m.digest or "",
            "pod_broadcast": bool(self.meta.get("pod_broadcast")),
        }
        if self._packed_ok and len(nums) >= 16:
            # Negotiated bitmap form: a restart storm re-registers with
            # one bit per piece instead of a msgpack int list. Density
            # gate keeps pathologically sparse sets on the list form.
            bitmap = reportcodec.nums_to_bitmap(nums)
            if len(bitmap) <= 2 * len(nums):
                resume["piece_bitmap"] = bitmap
                resume["piece_nums"] = []
        stripe = self.dispatcher.stripe
        if stripe is not None:
            resume["stripe"] = {"slice_size": stripe[0],
                                "slice_rank": stripe[1]}
        return resume

    def _on_ring_change(self, new_owner: str) -> None:
        """SchedulerClient ring-rebuild callback: this task's ownership
        moved to a different live member (the old one may be perfectly
        healthy — just no longer owning). Drain gracefully and re-home:
        flush buffered reports to the old member, close the stream, and
        let the receiver loop's recovery path reconnect — the ring now
        resolves to the new owner, and the re-register carries resume
        state so the new member adopts the task mid-flight."""
        if self._announce_done:
            return
        self._rehome_pending = True
        log.info("task ownership moved; re-homing announce stream",
                 task=self.task_id[:16], new_owner=new_owner)
        asyncio.ensure_future(self._rehome())

    async def _rehome(self) -> None:
        try:
            await self._flush_reports()
        except Exception:
            pass  # stream already dying: recovery re-reports anyway
        stream = self._stream
        if stream is not None and not stream.closed:
            await stream.close()
        # The receiver loop's recv now returns None → recovery reconnects
        # on the rebuilt ring (and books result="rehomed").

    def _degrade_after_scheduler_loss(self) -> None:
        """Reconnect budget exhausted: the schedulerless endgame. With
        origin allowed the workers hand the remainder to back-to-source
        (pieces on disk are kept); without it they ride out their current
        parents and fail via the starvation path if those run dry."""
        log.warning("announce stream unrecoverable; degrading",
                    task=self.task_id[:16],
                    back_source=not self.disable_back_source)
        if not self.disable_back_source:
            self._need_back_source = True
            for pid in list(self.dispatcher.parents):
                self.dispatcher.drop_parent(pid)
        self._sched_update.set()

    async def _recover_announce_stream(self) -> bool:
        """Reopen the announce stream (ring failover lives in
        scheduler_client), re-register, re-report completed pieces, flush
        buffered piece reports. Returns False when the budget is spent or
        the scheduler authoritatively rejected us."""
        async with self._announce_lock:
            if self._announce_done:
                return False
            if self._stream is not None and not self._stream.closed:
                return True   # a racing caller already recovered it
            if self._stream_reconnects >= self.MAX_STREAM_RECONNECTS:
                ANNOUNCE_RECONNECT_COUNT.labels("exhausted").inc()
                return False
            policy = retrylib.ANNOUNCE
            for attempt in range(self.RECONNECT_BUDGET):
                await asyncio.sleep(policy.delay(attempt))
                if self._announce_done:
                    return False
                try:
                    t0_clock = self.flight.wall_now()
                    stream = await self.scheduler_client.open_announce_stream(
                        self._open_body)
                    # Re-register with FULL resume state: a failover ring
                    # member (or restarted scheduler) rebuilds Task/Peer
                    # FSMs from it instead of demoting us to origin.
                    await stream.send({"type": "register",
                                       "resume": self._resume_state()})
                    msg = await stream.recv(timeout=30.0)
                    self._note_clock_sample(t0_clock, msg)
                except DfError as e:
                    ANNOUNCE_RECONNECT_COUNT.labels("retry").inc()
                    self.flight.record(flightlib.EV_RECONNECT, -1, 0.0,
                                       "retry")
                    log.warning("announce reconnect failed",
                                task=self.task_id[:16], attempt=attempt,
                                error=str(e))
                    continue
                if msg is None:
                    ANNOUNCE_RECONNECT_COUNT.labels("retry").inc()
                    continue
                old, self._stream = self._stream, stream
                if old is not None:
                    await old.close()
                self._stream_reconnects += 1
                kind = msg.get("type")
                if kind == "normal_task":
                    self._apply_task_meta(msg.get("task") or {})
                    if self.synchronizer is not None:
                        self.synchronizer.sync_parents(
                            msg.get("parents") or [])
                    self._apply_stripe(msg.get("stripe"))
                elif kind == "need_back_source":
                    self._need_back_source = True
                    for pid in list(self.dispatcher.parents):
                        self.dispatcher.drop_parent(pid)
                elif kind == "schedule_failed":
                    # An ANSWER, not an outage: the scheduler's verdict
                    # stands; fall through to degradation.
                    ANNOUNCE_RECONNECT_COUNT.labels("rejected").inc()
                    self._sched_update.set()
                    return False
                self._sched_update.set()
                # Re-register preserving completed pieces: a restarted
                # scheduler (or a failover ring member) has no idea what
                # this peer already holds — report every landed piece so
                # it becomes a usable parent again immediately. The
                # scheduler applies reports idempotently, so overlap with
                # still-buffered reports is harmless.
                for rec in self.store.get_pieces():
                    self._pending_reports.append({
                        "piece_num": rec.num,
                        "range_start": rec.offset,
                        "range_size": rec.size,
                        "digest": rec.digest,
                        "download_cost_ms": rec.cost_ms,
                        "dst_peer_id": "",
                    })
                await self._flush_reports()
                outcome = "rehomed" if self._rehome_pending else "ok"
                self._rehome_pending = False
                ANNOUNCE_RECONNECT_COUNT.labels(outcome).inc()
                self.flight.record(flightlib.EV_RECONNECT, -1, 0.0, outcome)
                log.info("announce stream recovered",
                         task=self.task_id[:16], attempt=attempt,
                         result=outcome,
                         reconnects=self._stream_reconnects)
                return True
            ANNOUNCE_RECONNECT_COUNT.labels("exhausted").inc()
            self.flight.record(flightlib.EV_RECONNECT, -1, 0.0, "exhausted")
            return False

    # Coalescing bound: one ranged GET covers up to this many contiguous
    # pieces (32 MiB at the default 4 MiB piece size). Availability gates
    # real run lengths — a warming parent advertises pieces incrementally,
    # so cold-chain runs stay short while warm pulls ride full spans.
    # Env-overridable for A/B measurement on noisy shared hosts.
    SPAN_MAX_PIECES = int(os.environ.get("DF_SPAN_MAX_PIECES", "8"))

    async def _piece_worker(self, index: int) -> None:
        """Hot loop (reference downloadPieceWorker :1043)."""
        while True:
            if self._complete() or self._need_back_source:
                return
            assignment = await self.dispatcher.get(timeout=10.0)
            if assignment is None:
                if self._complete() or self._need_back_source:
                    return
                if not await self._handle_starvation():
                    return
                continue
            run = self.dispatcher.extend_run(assignment, self.SPAN_MAX_PIECES)
            if self.wfq is None:
                await self._dispatch_assignment(assignment, run)
                continue
            # QoS gate: the assignment (a per-task reservation) is held
            # while this worker waits its DWRR turn, so cross-task piece
            # ISSUE order follows class weights while per-task dispatcher
            # state stays untouched. Acquired after dispatcher.get() so a
            # parked worker never pins a slot through starvation waits.
            await self.wfq.acquire(self._qos_priority)
            try:
                await self._dispatch_assignment(assignment, run)
            finally:
                self.wfq.release()

    async def _dispatch_assignment(self, assignment: PieceAssignment,
                                   run: list[PieceAssignment]) -> None:
        if len(run) > 1 and await self._download_run(run):
            return
        for extra in run[1:]:
            # Span path ineligible: hand the reservations back and pull
            # the head piece the per-piece way.
            self.dispatcher.release_assignment(extra)
        await self._download_one(assignment)

    async def _download_run(self, run: list[PieceAssignment]) -> bool:
        """One coalesced ranged fetch; returns False when the downloader
        deemed the span ineligible (caller falls back per-piece). Piece
        results arrive through the streaming callback as each lands, so
        progress frames and broker piece discovery stay piece-granular."""
        from dragonfly2_tpu.daemon.peer.piece_downloader import is_parent_gone

        p = run[0].parent
        penalized: list = []   # error OBJECTS — an id() set would alias a
        # freed error's reused address to a fresh distinct failure

        async def on_result(a: PieceAssignment, rec, err) -> None:
            if rec is not None:
                self.dispatcher.report_success(a, rec.cost_ms)
                PIECE_DOWNLOAD_COUNT.labels("ok").inc()
                self._note_piece_bytes(p, rec.size)
                self.flight.record(flightlib.EV_LANDED, a.piece_num,
                                   float(rec.cost_ms),
                                   self._parent_locality(p))
                await self._report_piece(rec, parent_id=p.peer_id)
                if self.on_piece is not None:
                    await self.on_piece(self.store, rec)
            else:
                PIECE_DOWNLOAD_COUNT.labels("fail").inc()
                gone = is_parent_gone(err)
                # One span-level event (429, 416, dead stream) arrives as
                # the SAME error object for every affected piece: penalize
                # the parent once — per-piece penalties would double the
                # cost EWMA 8x and block a parent over a single temporary
                # throttle. Distinct errors (per-piece crc mismatches)
                # still count individually, matching the per-piece path.
                if any(e is err for e in penalized):
                    self.dispatcher.release_assignment(a)
                    reason = failure_reason(err)
                else:
                    penalized.append(err)
                    self.dispatcher.report_failure(a, parent_gone=gone)
                    reason = self._note_piece_failure(p, err)
                self.flight.record(flightlib.EV_FAILED, a.piece_num, 0.0,
                                   reason)
                await self._safe_send({
                    "type": "piece_failed",
                    "piece_num": a.piece_num,
                    "parent_id": p.peer_id,
                    "temporary": not gone,
                    "reason": reason,
                })

        return await self.downloader.download_span_to_store(
            p.ip, p.upload_port, self.task_id, run, self.store,
            src_peer_id=self.peer_id, limiter=self.limiter,
            on_result=on_result, tenant=self.tenant)

    async def _download_one(self, assignment: PieceAssignment) -> None:
        from dragonfly2_tpu.daemon.peer.piece_downloader import (
            is_parent_gone,
            pull_one_piece,
        )

        p = assignment.parent
        try:
            rec = await pull_one_piece(
                self.downloader, self.store, self.dispatcher, assignment,
                task_id=self.task_id, peer_id=self.peer_id,
                limiter=self.limiter, tenant=self.tenant)
            self.dispatcher.report_success(assignment, rec.cost_ms)
            PIECE_DOWNLOAD_COUNT.labels("ok").inc()
            self._note_piece_bytes(p, rec.size)
            self.flight.record(flightlib.EV_LANDED, assignment.piece_num,
                               float(rec.cost_ms), self._parent_locality(p))
            await self._report_piece(rec, parent_id=p.peer_id)
            if self.on_piece is not None:
                await self.on_piece(self.store, rec)
        except DfError as e:
            PIECE_DOWNLOAD_COUNT.labels("fail").inc()
            gone = is_parent_gone(e)
            self.dispatcher.report_failure(assignment, parent_gone=gone)
            reason = self._note_piece_failure(p, e)
            self.flight.record(flightlib.EV_FAILED, assignment.piece_num,
                               0.0, reason)
            await self._safe_send({
                "type": "piece_failed",
                "piece_num": assignment.piece_num,
                "parent_id": p.peer_id,
                "temporary": not gone,
                "reason": reason,
            })

    async def _handle_starvation(self) -> bool:
        """No assignable pieces: ask the scheduler for new parents. Only one
        worker at a time runs the reschedule dance; the scheduler's answer
        arrives through the receiver loop. Returns False when the worker
        should exit (back-source takeover or terminal starvation)."""
        async with self._resched_lock:
            if self._complete() or self._need_back_source:
                return False
            # Another worker may have already refreshed the parent set
            # (peek only — try_get would leak an in-flight reservation). An
            # active parent with nothing assignable does NOT count: missing
            # pieces held only by dead parents must still trigger reschedule.
            if self.dispatcher.has_assignable():
                return True
            self._reschedules += 1
            if self._reschedules > MAX_RESCHEDULES:
                raise DfError(Code.ClientScheduleTimeout,
                              f"starved after {MAX_RESCHEDULES} reschedules")
            blocklist = self.dispatcher.unusable_parent_ids()
            self._sched_update.clear()
            self.flight.record(flightlib.EV_RESCHEDULE, -1, 0.0,
                               "starvation")
            await self._safe_send({"type": "reschedule", "blocklist": blocklist,
                                   "description": "piece starvation"})
            try:
                # Longer than the scheduler's 30s seed-patience hold: a
                # reschedule during a slow seed fetch must outwait it, not
                # deterministically tie and abort.
                await asyncio.wait_for(self._sched_update.wait(), timeout=60.0)
            except asyncio.TimeoutError:
                raise DfError(Code.SchedError, "scheduler silent during reschedule")
            self.flight.record(flightlib.EV_SCHED_ANSWER)
            return not self._need_back_source

    # -- reporting ---------------------------------------------------------

    _REPORT_FLUSH_S = 0.05

    async def _report_piece(self, rec, parent_id: str) -> None:
        report = {
            "piece_num": rec.num,
            "range_start": rec.offset,
            "range_size": rec.size,
            "digest": rec.digest,
            "download_cost_ms": rec.cost_ms,
            "dst_peer_id": parent_id,
        }
        # Per-phase timings ride the report so the scheduler can attribute
        # stragglers per host (flight.PodAggregator, /debug/pod/<task>).
        timings = self.flight.piece_report_timings(rec.num)
        if timings:
            report["timings"] = timings
        self._pending_reports.append(report)
        if self._flush_task is None or self._flush_task.done():
            self._flush_task = asyncio.ensure_future(self._flush_soon())

    async def _flush_soon(self) -> None:
        # Loop until drained: a report appended while _flush_reports is
        # mid-send sees this task as not-done and schedules nothing — the
        # re-check here is what keeps it from stranding past the window.
        loop = asyncio.get_running_loop()
        while True:
            wait = self._last_flush + self._REPORT_FLUSH_S - loop.time()
            if wait > 0 and len(self._pending_reports) < self.report_batch:
                # Under backlog (a full batch already waiting) skip the
                # coalescing window — it only exists to grow batches.
                await asyncio.sleep(wait)
            if not await self._flush_reports():
                # Stream down: reports stay BUFFERED (not dropped) for the
                # announce-recovery flush; spinning here would just burn
                # the loop until the receiver finishes reconnecting.
                return
            if not self._pending_reports:
                return

    def _batch_msg(self, batch: list) -> dict:
        """The wire form of one report batch: packed columns when the
        scheduler negotiated them AND the encoder can represent the batch
        exactly (it refuses anything lossy — see reportcodec); otherwise
        the legacy per-piece dict list."""
        if len(batch) == 1:
            return {"type": "piece_finished", "piece": batch[0]}
        if self._packed_ok:
            packed = reportcodec.encode_reports(batch)
            if packed is not None:
                return {"type": "pieces_finished", "packed": packed}
        return {"type": "pieces_finished", "pieces": batch}

    async def _flush_reports(self) -> bool:
        """Send buffered piece reports, draining the queue in
        report_batch-capped messages. Returns False when the stream was
        down — the unsent batch is RESTORED in order, not dropped, so the
        reports survive for the announce-stream recovery path to flush."""
        async with self._report_lock:
            pending = self._pending_reports
            while pending:
                cap = min(self.report_batch, len(pending))
                batch = [pending.popleft() for _ in range(cap)]
                self._last_flush = asyncio.get_running_loop().time()
                try:
                    sent = await self._safe_send(self._batch_msg(batch))
                except BaseException:
                    # A cancellation (teardown racing a flush) must not
                    # drop the popped batch: restore it — in order, O(batch)
                    # not O(queue) — so the teardown's own final flush
                    # still reports these pieces.
                    pending.extendleft(reversed(batch))
                    raise
                if not sent:
                    pending.extendleft(reversed(batch))
                    return False
            return True

    @staticmethod
    def _note_recv(msg: dict) -> None:
        """Book a received announce message's serialized weight (the
        recv half of peer_announce_bytes_total)."""
        try:
            ANNOUNCE_BYTES.labels("recv").inc(
                len(msgpack.packb(msg, use_bin_type=True)))
        except Exception:
            pass   # accounting must never break the stream

    async def _safe_send(self, msg: dict) -> bool:
        """Send on the announce stream; returns False when the stream is
        down (the receiver loop owns reconnection — callers must not race
        it with their own)."""
        # Scheduler-visible ordering: buffered piece reports precede any
        # terminal or reschedule message (the scheduler's piece counts must
        # be current when it acts on those).
        if msg.get("type") in ("download_finished", "reschedule",
                               "download_failed"):
            await self._flush_reports()
        if msg.get("type") in ("download_finished", "download_failed") \
                and "flight" not in msg:
            # Flight shipping: the terminal announce message carries the
            # compact bounded digest of this task's event ring (plus the
            # clock samples) so the scheduler's pod lens can merge a
            # cross-host timeline without a pull round-trip per host.
            # Advisory — a digest failure must never fail the task path.
            try:
                msg["flight"] = flightlib.digest(
                    self.flight, clock_samples=self._clock_samples)
            except Exception:
                log.warning("flight digest failed",
                            task=self.task_id[:16], exc_info=True)
        stream = self._stream
        if stream is None or stream.closed:
            return False
        try:
            await stream.send(msg)
            ANNOUNCE_BYTES.labels("sent").inc(
                len(msgpack.packb(msg, use_bin_type=True)))
            return True
        except DfError:
            return False

    async def _teardown(self) -> None:
        self._announce_done = True   # recovery must not race teardown
        unwatch = getattr(self.scheduler_client, "unwatch_ring", None)
        if unwatch is not None:
            unwatch(self.task_id)
        if self._flush_task is not None and not self._flush_task.done():
            self._flush_task.cancel()
        await self._flush_reports()
        if self.synchronizer is not None:
            await self.synchronizer.close()
        await self.downloader.close()
        if self._stream is not None:
            await self._stream.close()
