"""Traffic shaper: total-bandwidth sharing across concurrent tasks.

Reference: client/daemon/peer/traffic_shaper.go — ``plain`` gives every
task the same shared limiter (:65-110); ``sampling`` samples per-task bytes
every interval and re-splits the total proportionally to observed need
(:125+), so one hot checkpoint pull doesn't starve under an even split and
idle tasks release their bandwidth.
"""

from __future__ import annotations

import asyncio

from dragonfly2_tpu.pkg import dflog
from dragonfly2_tpu.pkg.ratelimit import INF, Limiter

log = dflog.get("peer.traffic_shaper")

TYPE_PLAIN = "plain"
TYPE_SAMPLING = "sampling"

DEFAULT_SAMPLING_INTERVAL = 1.0
# Floor share of an active-but-idle task: keeps it able to ramp back up
# (reference traffic_shaper.go uses a per-task default of total/10).
MIN_SHARE_FRACTION = 0.1


class _TaskLimiter(Limiter):
    """Per-task limiter that counts bytes granted in the current window."""

    def __init__(self, limit: float):
        super().__init__(limit)
        self.window_bytes = 0

    async def wait(self, n: int = 1) -> float:
        waited = await super().wait(n)
        self.window_bytes += n
        return waited

    def take_window(self) -> int:
        used, self.window_bytes = self.window_bytes, 0
        return used


class TrafficShaper:
    def __init__(self, total_rate: float = INF, *,
                 algorithm: str = TYPE_PLAIN,
                 sampling_interval: float = DEFAULT_SAMPLING_INTERVAL):
        if algorithm not in (TYPE_PLAIN, TYPE_SAMPLING):
            # A config typo must not stop the daemon: fall back to the plain
            # shaper like the reference (traffic_shaper.go:59).
            log.warning("unknown traffic shaper algorithm, using plain",
                        algorithm=algorithm)
            algorithm = TYPE_PLAIN
        self.algorithm = algorithm
        self.total_rate = total_rate
        self.sampling_interval = sampling_interval
        self._shared = Limiter(total_rate)
        self._tasks: dict[str, _TaskLimiter] = {}
        self._loop_task: asyncio.Task | None = None

    # -- task lifecycle ----------------------------------------------------

    def start_task(self, task_id: str) -> Limiter:
        """Limiter a task's transfers must ride. plain → the one shared
        bucket; sampling → a per-task bucket re-tuned by the sampler."""
        if self.algorithm == TYPE_PLAIN or self.total_rate == INF:
            return self._shared
        lim = self._tasks.get(task_id)
        if lim is None:
            lim = _TaskLimiter(self._fair_share(len(self._tasks) + 1))
            self._tasks[task_id] = lim
            self._rebalance_even()
        return lim

    def finish_task(self, task_id: str) -> None:
        if self._tasks.pop(task_id, None) is not None and self._tasks:
            self._rebalance_even()

    def _fair_share(self, n: int) -> float:
        return self.total_rate / max(1, n)

    def _rebalance_even(self) -> None:
        """New/finished task: reset to an even split; the sampler skews it
        toward observed need at the next tick."""
        share = self._fair_share(len(self._tasks))
        for lim in self._tasks.values():
            lim.set_limit(share)

    # -- sampling loop (reference :125+) -----------------------------------

    def serve(self) -> None:
        if self.algorithm == TYPE_SAMPLING and self._loop_task is None:
            self._loop_task = asyncio.create_task(self._loop())

    def stop(self) -> None:
        if self._loop_task is not None:
            self._loop_task.cancel()
            self._loop_task = None

    async def _loop(self) -> None:
        while True:
            await asyncio.sleep(self.sampling_interval)
            self.reallocate()

    def reallocate(self) -> None:
        """Split total_rate across tasks proportionally to bytes moved in
        the last window, with a floor so starved tasks can recover."""
        if not self._tasks or self.total_rate == INF:
            return
        usages = {tid: lim.take_window() for tid, lim in self._tasks.items()}
        total_used = sum(usages.values())
        n = len(self._tasks)
        floor = self.total_rate * MIN_SHARE_FRACTION / n
        if total_used == 0:
            self._rebalance_even()
            return
        distributable = self.total_rate - floor * n
        for tid, lim in self._tasks.items():
            share = floor + distributable * (usages[tid] / total_used)
            lim.set_limit(share)
        log.debug("reallocated bandwidth", tasks=n, total_used=total_used)
