"""Piece dispatcher: decides which (piece, parent) to fetch next.

Reference: client/daemon/peer/piece_dispatcher.go — per-parent smoothed
score, sorted with probability (1 - randomRatio) else shuffled (:89-168);
skips pieces already downloaded. Availability arrives from the per-parent
synchronizers; workers pull assignments here.
"""

from __future__ import annotations

import asyncio
import random
from dataclasses import dataclass, field

from dragonfly2_tpu.pkg import dflog
from dragonfly2_tpu.pkg import flight as flightlib

log = dflog.get("peer.piece_dispatcher")

EWMA_ALPHA = 0.3
RANDOM_RATIO = 0.1  # reference defaultRandomRatio: explore parents
# Cost EWMAs within this factor of the fastest holder count as tied:
# the tie breaks on current in-flight assignment count, so equally-fast
# holders share load instead of herding onto one.
NEAR_TIE_RATIO = 1.25


@dataclass
class ParentInfo:
    peer_id: str
    ip: str
    upload_port: int
    pieces: set[int] = field(default_factory=set)
    cost_ewma_ms: float = 100.0    # optimistic start
    failures: int = 0
    blocked: bool = False
    # ICI locality: this parent shares the local host's tpu_slice, so
    # pulls from it ride the intra-slice fabric, not the DCN NIC. In
    # stripe mode it is the ONLY class allowed to serve non-stripe pieces.
    same_slice: bool = False
    tpu_slice: str = ""
    # Assignments currently in flight against this parent (tie-breaker).
    inflight: int = 0


@dataclass
class PieceAssignment:
    piece_num: int
    parent: ParentInfo
    expected_size: int = -1
    digest: str = ""   # parent-advertised "algo:encoded"; verified on write


def parent_key(p: ParentInfo) -> str:
    """Daemon-wide quarantine key: the serving endpoint, not the per-task
    peer id — a parent that served corrupt bytes for task A is equally
    untrusted for task B, and a restarted peer id must not reset it."""
    return f"{p.ip}:{p.upload_port}"


class PieceDispatcher:
    def __init__(self, *, max_parent_failures: int = 3, quarantine=None,
                 flight: "flightlib.TaskFlight | None" = None):
        # Daemon-wide decaying-penalty blocklist (pkg/quarantine
        # ParentQuarantine), shared across conductors; None = no filter.
        self.quarantine = quarantine
        # Optional flight-recorder handle (the owning conductor's): parent
        # topology changes are part of the task's black-box timeline.
        self.flight = flight
        self.parents: dict[str, ParentInfo] = {}
        self._total_piece_count = -1
        self.piece_size = 0
        self.content_length = -1
        self._done: set[int] = set()
        self._inflight: set[int] = set()
        self.piece_digests: dict[int, str] = {}
        # Per-parent digest maps + the set of parents whose sync stream
        # reported done (see certified_digest_maps for why provenance,
        # not a merged view, drives the re-hash-skip decision).
        self.parent_digests: dict[str, dict[int, str]] = {}
        self.done_parents: set[str] = set()
        # Incremental ready-tracking: O(1) amortized per assignment instead
        # of rescanning all pieces (a 100 GiB task is ~25k pieces).
        self._needed: set[int] = set()
        self._heap: list[int] = []
        self._max_parent_failures = max_parent_failures
        self._wakeup = asyncio.Event()
        # Set whenever the certification picture changes (a parent reports
        # done, or a potential certifier drops): completion-time waiters
        # (conductor._await_certification) re-evaluate on each set.
        self.certified_event = asyncio.Event()
        # Striped slice broadcast wanted-set (scheduler stripe plan):
        # size<=1 = unstriped. In stripe mode only pieces with
        # piece_num % size == rank may be assigned to cross-slice (DCN)
        # parents; every other piece fills intra-slice.
        self._stripe_size = 0
        self._stripe_rank = -1

    # -- stripe mode -------------------------------------------------------

    @property
    def stripe(self) -> "tuple[int, int] | None":
        if self._stripe_size >= 2:
            return (self._stripe_size, self._stripe_rank)
        return None

    def set_stripe(self, slice_size: int, slice_rank: int) -> None:
        """Enter (or reshuffle) stripe mode. Changing the plan re-opens
        pieces whose assignability changed, so reservations waiting on a
        dead mate's stripe release cleanly onto the new plan."""
        if slice_size < 2 or not (0 <= slice_rank < slice_size):
            self.clear_stripe()
            return
        if (slice_size, slice_rank) == (self._stripe_size, self._stripe_rank):
            return
        self._stripe_size, self._stripe_rank = slice_size, slice_rank
        self._wakeup.set()

    def clear_stripe(self) -> None:
        """Unstriped fallback (lone host / scheduler stopped striping):
        every piece becomes DCN-assignable again."""
        if self._stripe_size:
            self._stripe_size, self._stripe_rank = 0, -1
            self._wakeup.set()

    def in_stripe(self, piece_num: int) -> bool:
        """Does this host DCN-fetch ``piece_num`` under the current plan?
        True for everything when unstriped."""
        if self._stripe_size < 2:
            return True
        return piece_num % self._stripe_size == self._stripe_rank

    @property
    def total_piece_count(self) -> int:
        return self._total_piece_count

    @total_piece_count.setter
    def total_piece_count(self, value: int) -> None:
        if value >= 0 and value != self._total_piece_count:
            self._total_piece_count = value
            self._add_needed(range(value))
        elif value >= 0:
            self._total_piece_count = value

    def _add_needed(self, nums) -> None:
        import heapq

        for n in nums:
            if n not in self._done and n not in self._inflight and n not in self._needed:
                self._needed.add(n)
                heapq.heappush(self._heap, n)

    # -- topology updates --------------------------------------------------

    def upsert_parent(self, peer_id: str, ip: str, upload_port: int,
                      *, same_slice: bool = False,
                      tpu_slice: str = "") -> ParentInfo:
        p = self.parents.get(peer_id)
        if p is None:
            p = ParentInfo(peer_id, ip, upload_port,
                           same_slice=same_slice, tpu_slice=tpu_slice)
            self.parents[peer_id] = p
            self._wakeup.set()
        else:
            p.ip, p.upload_port = ip, upload_port
            p.blocked = False
            p.same_slice = p.same_slice or same_slice
            p.tpu_slice = p.tpu_slice or tpu_slice
        return p

    def drop_parent(self, peer_id: str) -> None:
        p = self.parents.get(peer_id)
        if p is not None:
            p.blocked = True
            if self.flight is not None:
                self.flight.record(flightlib.EV_PARENT_DROP, -1, 0.0,
                                   peer_id)
        self._wakeup.set()
        self.certified_event.set()

    def active_parents(self) -> list[ParentInfo]:
        # Quarantine is consulted live (it decays): a parent quarantined a
        # minute ago re-enters selection the moment its window lapses,
        # with no topology push needed.
        q = self.quarantine
        return [p for p in self.parents.values()
                if not p.blocked
                and (q is None or not q.is_quarantined(parent_key(p)))]

    def unusable_parent_ids(self) -> list[str]:
        """Blocked or currently-quarantined parents — the reschedule
        blocklist (the scheduler must not hand these right back)."""
        q = self.quarantine
        return [pid for pid, p in self.parents.items()
                if p.blocked
                or (q is not None and q.is_quarantined(parent_key(p)))]

    def note_parent_done(self, peer_id: str) -> None:
        """The sync stream saw done=True from this parent: its completion
        gate passed (seed: full-digest validation; intermediate peer: its
        own certified chain)."""
        self.done_parents.add(peer_id)
        self.certified_event.set()

    def certified_digest_maps(self) -> "list[dict[int, str]]":
        """EVERY done parent's non-empty digest map. Provenance matters:
        a still-downloading back-sourcing parent's announced digests are
        self-computed and uncertified — the re-hash-skip decision must
        compare the digests pieces were actually verified against to a
        VALIDATED parent's map, never to the merged view (a corrupt
        parent's entries would otherwise be laundered by an honest
        parent's done). The consumer (store.apply_certification) tries
        each map: a corrupt parent that happens to complete first must
        not mask an honest completed parent's certification."""
        return [m for pid in self.done_parents
                if (m := self.parent_digests.get(pid))]

    def pending_certifiers(self) -> bool:
        """Could a certification still arrive? True while some unblocked
        parent's sync stream has not yet reported done — its completion
        gate may pass any moment and its digest map would then certify
        this peer's re-hash skip."""
        return any(not p.blocked and pid not in self.done_parents
                   for pid, p in self.parents.items())

    def seed_shared_digests(self, digests: "dict[int, str] | None") -> None:
        """Merge scheduler-RELAYED digests into the shared map only:
        they inform landing verification for assignments made before the
        parent's own sync snapshot arrives, but they carry no provenance
        — they must never enter parent_digests (a first-reporter-poisoned
        relay attributed to an honest parent would be laundered into its
        certified map)."""
        for n, d in (digests or {}).items():
            if d:
                self.piece_digests.setdefault(int(n), d)

    def on_parent_pieces(self, peer_id: str, piece_nums: list[int],
                         total_piece_count: int = -1, content_length: int = -1,
                         piece_size: int = 0,
                         digests: dict[int, str] | None = None) -> None:
        p = self.parents.get(peer_id)
        if p is None:
            return
        p.pieces.update(piece_nums)
        if digests:
            per_parent = self.parent_digests.setdefault(peer_id, {})
            for n, d in digests.items():
                if d:
                    self.piece_digests[int(n)] = d
                    per_parent[int(n)] = d
        if total_piece_count >= 0:
            self.total_piece_count = total_piece_count
        if self._total_piece_count < 0:
            # Unknown total: advertised pieces define the known universe.
            self._add_needed(piece_nums)
        if content_length >= 0:
            self.content_length = content_length
        if piece_size > 0:
            self.piece_size = piece_size
        self._wakeup.set()

    # -- results -----------------------------------------------------------

    def mark_downloaded(self, piece_num: int) -> None:
        self._done.add(piece_num)
        self._inflight.discard(piece_num)
        self._needed.discard(piece_num)
        self._wakeup.set()

    def mark_known_downloaded(self, piece_nums) -> None:
        self._done.update(piece_nums)
        self._needed -= set(piece_nums)

    def report_success(self, assignment: PieceAssignment, cost_ms: int) -> None:
        p = assignment.parent
        p.cost_ewma_ms = (1 - EWMA_ALPHA) * p.cost_ewma_ms + EWMA_ALPHA * cost_ms
        p.failures = 0
        p.inflight = max(0, p.inflight - 1)
        self.mark_downloaded(assignment.piece_num)

    def report_failure(self, assignment: PieceAssignment, *, parent_gone: bool = False) -> None:
        p = assignment.parent
        p.failures += 1
        p.inflight = max(0, p.inflight - 1)
        p.cost_ewma_ms *= 2  # punish
        if parent_gone or p.failures >= self._max_parent_failures:
            p.blocked = True
        self._inflight.discard(assignment.piece_num)
        self._add_needed([assignment.piece_num])
        self._wakeup.set()

    # -- completion --------------------------------------------------------

    def is_complete(self) -> bool:
        return self.total_piece_count >= 0 and len(self._done) >= self.total_piece_count

    def no_usable_parents(self) -> bool:
        return not self.active_parents()

    def downloaded_count(self) -> int:
        return len(self._done)

    # -- assignment (reference getDesiredReq :104-168) ---------------------

    def _holders(self, piece_num: int) -> list[ParentInfo]:
        """Eligible holders under the stripe wanted-set: non-stripe pieces
        may ONLY come from same-slice parents (never DCN-assigned); stripe
        pieces prefer a same-slice holder when one exists (a mate that
        already has the piece beats re-crossing the DCN for it)."""
        holders = [p for p in self.active_parents() if piece_num in p.pieces]
        if self._stripe_size < 2:
            return holders
        intra = [p for p in holders if p.same_slice]
        if not self.in_stripe(piece_num):
            return intra
        return intra or holders

    def _pick_parent(self, piece_num: int) -> ParentInfo | None:
        holders = self._holders(piece_num)
        if not holders:
            return None
        if random.random() < RANDOM_RATIO:
            return random.choice(holders)
        best = min(p.cost_ewma_ms for p in holders)
        near = [p for p in holders if p.cost_ewma_ms <= best * NEAR_TIE_RATIO]
        # Near-ties break on current in-flight load, so equally-fast
        # holders share assignments instead of the min() herding every
        # piece onto the single lowest-EWMA parent.
        return min(near, key=lambda p: (p.inflight, p.cost_ewma_ms))

    def has_assignable(self) -> bool:
        """Non-mutating peek: could try_get() return an assignment now?"""
        return any(self._holders(n) for n in self._needed)

    def try_get(self) -> PieceAssignment | None:
        """Lowest-numbered needed piece with a live holder; unheld pieces go
        back on the heap (O(log n) amortized)."""
        import heapq

        deferred: list[int] = []
        found: PieceAssignment | None = None
        while self._heap:
            n = heapq.heappop(self._heap)
            if n not in self._needed:
                continue  # stale entry (downloaded meanwhile)
            parent = self._pick_parent(n)
            if parent is None:
                deferred.append(n)
                continue
            self._needed.discard(n)
            self._inflight.add(n)
            parent.inflight += 1
            expected = -1
            if self.piece_size > 0 and self.content_length >= 0:
                from dragonfly2_tpu.pkg.piece import piece_length

                expected = piece_length(n, self.piece_size, self.content_length)
            found = PieceAssignment(n, parent, expected,
                                    digest=self.piece_digests.get(n, ""))
            break
        for n in deferred:
            heapq.heappush(self._heap, n)
        return found

    def extend_run(self, a: PieceAssignment,
                   max_len: int) -> list[PieceAssignment]:
        """Greedily extend ``a`` into a CONTIGUOUS run of needed pieces the
        same parent already advertises, for one coalesced ranged fetch
        (reference moves pieces one GET each — peertask_conductor.go:1043;
        the TPU-first win is one native socket→crc→pwrite loop per run).
        Only pieces whose digest the native path can verify on the fly
        (crc32c or none) extend the run, so a mixed-digest task does not
        bounce between span attempts and per-piece fallbacks. Extended
        pieces are reserved (inflight) exactly like try_get's."""
        run = [a]
        p = a.parent
        if (self.piece_size <= 0 or self.content_length < 0
                or p.blocked or max_len <= 1):
            return run
        if a.digest and not a.digest.startswith("crc32c:"):
            # The head piece itself would make the span ineligible: don't
            # reserve extras just to release them (a 25k-piece sha256 task
            # would churn reserve/release on every piece).
            return run
        from dragonfly2_tpu.storage.local_store import _native

        if _native() is None:
            return run  # span fetch is native-only; avoid churn without it
        from dragonfly2_tpu.pkg.piece import piece_length

        n = a.piece_num + 1
        while len(run) < max_len and n in self._needed and n in p.pieces:
            if self._stripe_size >= 2 and not p.same_slice \
                    and not self.in_stripe(n):
                # Wanted-set boundary: a DCN parent's span must not spill
                # into a mate's stripe (stripes interleave mod S, so cross
                # runs naturally cap at one piece — intra runs stay long).
                break
            digest = self.piece_digests.get(n, "")
            if digest and not digest.startswith("crc32c:"):
                break
            self._needed.discard(n)
            self._inflight.add(n)
            p.inflight += 1
            run.append(PieceAssignment(
                n, p, piece_length(n, self.piece_size, self.content_length),
                digest=digest))
            n += 1
        return run

    def release_assignment(self, a: PieceAssignment) -> None:
        """Hand an unfetched reservation back (span fallback): no failure
        accounting — the piece simply becomes assignable again."""
        a.parent.inflight = max(0, a.parent.inflight - 1)
        self._inflight.discard(a.piece_num)
        self._add_needed([a.piece_num])
        self._wakeup.set()

    async def get(self, timeout: float = 30.0) -> PieceAssignment | None:
        """Next assignment; None when the task is complete or no parents can
        serve anything new within ``timeout`` (caller decides to reschedule)."""
        deadline = asyncio.get_running_loop().time() + timeout
        while True:
            if self.is_complete():
                return None
            assignment = self.try_get()
            if assignment is not None:
                return assignment
            remaining = deadline - asyncio.get_running_loop().time()
            if remaining <= 0 or self.no_usable_parents():
                return None
            self._wakeup.clear()
            try:
                await asyncio.wait_for(self._wakeup.wait(), min(remaining, 1.0))
            except asyncio.TimeoutError:
                pass
