"""Piece dispatcher: decides which (piece, parent) to fetch next.

Reference: client/daemon/peer/piece_dispatcher.go — per-parent smoothed
score, sorted with probability (1 - randomRatio) else shuffled (:89-168);
skips pieces already downloaded. Availability arrives from the per-parent
synchronizers; workers pull assignments here.
"""

from __future__ import annotations

import asyncio
import random
from dataclasses import dataclass, field

from dragonfly2_tpu.pkg import dflog

log = dflog.get("peer.piece_dispatcher")

EWMA_ALPHA = 0.3
RANDOM_RATIO = 0.1  # reference defaultRandomRatio: explore parents


@dataclass
class ParentInfo:
    peer_id: str
    ip: str
    upload_port: int
    pieces: set[int] = field(default_factory=set)
    cost_ewma_ms: float = 100.0    # optimistic start
    failures: int = 0
    blocked: bool = False


@dataclass
class PieceAssignment:
    piece_num: int
    parent: ParentInfo
    expected_size: int = -1


class PieceDispatcher:
    def __init__(self, *, max_parent_failures: int = 3):
        self.parents: dict[str, ParentInfo] = {}
        self.total_piece_count = -1
        self.piece_size = 0
        self.content_length = -1
        self._done: set[int] = set()
        self._inflight: set[int] = set()
        self._max_parent_failures = max_parent_failures
        self._wakeup = asyncio.Event()

    # -- topology updates --------------------------------------------------

    def upsert_parent(self, peer_id: str, ip: str, upload_port: int) -> ParentInfo:
        p = self.parents.get(peer_id)
        if p is None:
            p = ParentInfo(peer_id, ip, upload_port)
            self.parents[peer_id] = p
            self._wakeup.set()
        else:
            p.ip, p.upload_port = ip, upload_port
            p.blocked = False
        return p

    def drop_parent(self, peer_id: str) -> None:
        p = self.parents.get(peer_id)
        if p is not None:
            p.blocked = True
        self._wakeup.set()

    def active_parents(self) -> list[ParentInfo]:
        return [p for p in self.parents.values() if not p.blocked]

    def on_parent_pieces(self, peer_id: str, piece_nums: list[int],
                         total_piece_count: int = -1, content_length: int = -1,
                         piece_size: int = 0) -> None:
        p = self.parents.get(peer_id)
        if p is None:
            return
        p.pieces.update(piece_nums)
        if total_piece_count >= 0:
            self.total_piece_count = total_piece_count
        if content_length >= 0:
            self.content_length = content_length
        if piece_size > 0:
            self.piece_size = piece_size
        self._wakeup.set()

    # -- results -----------------------------------------------------------

    def mark_downloaded(self, piece_num: int) -> None:
        self._done.add(piece_num)
        self._inflight.discard(piece_num)
        self._wakeup.set()

    def mark_known_downloaded(self, piece_nums) -> None:
        self._done.update(piece_nums)

    def report_success(self, assignment: PieceAssignment, cost_ms: int) -> None:
        p = assignment.parent
        p.cost_ewma_ms = (1 - EWMA_ALPHA) * p.cost_ewma_ms + EWMA_ALPHA * cost_ms
        p.failures = 0
        self.mark_downloaded(assignment.piece_num)

    def report_failure(self, assignment: PieceAssignment, *, parent_gone: bool = False) -> None:
        p = assignment.parent
        p.failures += 1
        p.cost_ewma_ms *= 2  # punish
        if parent_gone or p.failures >= self._max_parent_failures:
            p.blocked = True
        self._inflight.discard(assignment.piece_num)
        self._wakeup.set()

    # -- completion --------------------------------------------------------

    def is_complete(self) -> bool:
        return self.total_piece_count >= 0 and len(self._done) >= self.total_piece_count

    def no_usable_parents(self) -> bool:
        return not self.active_parents()

    def downloaded_count(self) -> int:
        return len(self._done)

    # -- assignment (reference getDesiredReq :104-168) ---------------------

    def _candidate_pieces(self) -> list[int]:
        if self.total_piece_count >= 0:
            universe = range(self.total_piece_count)
            missing = [n for n in universe if n not in self._done and n not in self._inflight]
        else:
            advertised: set[int] = set()
            for p in self.active_parents():
                advertised |= p.pieces
            missing = sorted(advertised - self._done - self._inflight)
        return missing

    def _pick_parent(self, piece_num: int) -> ParentInfo | None:
        holders = [p for p in self.active_parents() if piece_num in p.pieces]
        if not holders:
            return None
        if random.random() < RANDOM_RATIO:
            return random.choice(holders)
        return min(holders, key=lambda p: p.cost_ewma_ms)

    def has_assignable(self) -> bool:
        """Non-mutating peek: could try_get() return an assignment now?"""
        for piece_num in self._candidate_pieces():
            if any(piece_num in p.pieces for p in self.active_parents()):
                return True
        return False

    def try_get(self) -> PieceAssignment | None:
        for piece_num in self._candidate_pieces():
            parent = self._pick_parent(piece_num)
            if parent is None:
                continue
            self._inflight.add(piece_num)
            expected = -1
            if self.piece_size > 0 and self.content_length >= 0:
                from dragonfly2_tpu.pkg.piece import piece_length

                expected = piece_length(piece_num, self.piece_size, self.content_length)
            return PieceAssignment(piece_num, parent, expected)
        return None

    async def get(self, timeout: float = 30.0) -> PieceAssignment | None:
        """Next assignment; None when the task is complete or no parents can
        serve anything new within ``timeout`` (caller decides to reschedule)."""
        deadline = asyncio.get_running_loop().time() + timeout
        while True:
            if self.is_complete():
                return None
            assignment = self.try_get()
            if assignment is not None:
                return assignment
            remaining = deadline - asyncio.get_running_loop().time()
            if remaining <= 0 or self.no_usable_parents():
                return None
            self._wakeup.clear()
            try:
                await asyncio.wait_for(self._wakeup.wait(), min(remaining, 1.0))
            except asyncio.TimeoutError:
                pass
