"""Peer task manager: task front-end, dedup, reuse, conductors.

Reference: client/daemon/peer/peertask_manager.go — StartFileTask (:328),
StartSeedTask (:401), conductor dedup (getOrCreatePeerTaskConductor :201),
Subscribe (:439) via the piece broker; peertask_reuse.go for local reuse.
"""

from __future__ import annotations

import asyncio
import time
from dataclasses import dataclass, field
from typing import AsyncIterator

from dragonfly2_tpu.daemon.peer.broker import PieceBroker, PieceEvent
from dragonfly2_tpu.daemon.peer.piece_manager import PieceManager
from dragonfly2_tpu.pkg import aio, dflog, idgen, metrics
from dragonfly2_tpu.pkg import flight as flightlib
from dragonfly2_tpu.pkg.errors import Code, DfError, StorageError, describe
from dragonfly2_tpu.pkg.piece import (
    Range,
    compute_piece_count,
    compute_piece_size,
)
from dragonfly2_tpu.pkg.ratelimit import Limiter
from dragonfly2_tpu.proto.common import UrlMeta
from dragonfly2_tpu.storage import (
    LocalTaskStore,
    StorageManager,
    TaskStoreMetadata,
)
from dragonfly2_tpu.storage.local_store import (
    acquire_read_buffer,
    release_read_buffer,
)

log = dflog.get("peer.task_manager")

# Completion-time whole-content digest decision: "skipped" = the certified
# piece chain proved it (warm path / cold-race wait succeeded); "hashed" =
# the O(content) re-hash ran. The skipped:hashed ratio is the fleet-visible
# measure of how often the certification chain is doing its job.
COMPLETION_REHASH = metrics.counter(
    "peer_completion_rehash_total",
    "Completion-time whole-content digest decisions", ("result",))


@dataclass
class FileTaskRequest:
    url: str
    output: str
    meta: UrlMeta = field(default_factory=UrlMeta)
    peer_id: str = ""
    disable_back_source: bool = False
    range: Range | None = None
    # Terminal device: "" = disk only; "tpu" additionally lands verified
    # pieces into an HBM sink (daemon/peer/device_sink.py) as they arrive.
    device: str = ""
    # Striped slice broadcast: register the task as a pod broadcast so the
    # scheduler stripes the DCN pull across same-slice hosts (1/S of the
    # bytes each; the rest fills intra-slice).
    pod_broadcast: bool = False

    def task_id(self) -> str:
        return idgen.task_id_v1(
            self.url,
            digest=self.meta.digest,
            tag=self.meta.tag,
            application=self.meta.application,
            filters=self.meta.filter,
            range_header=self.meta.range,
        )

    def parent_task_id(self) -> str:
        """Whole-content task id for ranged requests (reference
        task_id.go:40-44) — the store partial/completed reuse looks up."""
        return idgen.parent_task_id_v1(
            self.url,
            digest=self.meta.digest,
            tag=self.meta.tag,
            application=self.meta.application,
            filters=self.meta.filter,
        )


@dataclass
class StreamTaskRequest:
    """Stream task: ordered bytes delivered as pieces land (reference
    peertask_stream.go). The task id excludes the range so concurrent ranged
    readers share one underlying whole-content task."""

    url: str
    meta: UrlMeta = field(default_factory=UrlMeta)
    peer_id: str = ""
    range: Range | None = None          # bytes to emit (None = everything)
    disable_back_source: bool = False

    def task_id(self) -> str:
        return idgen.task_id_v1(
            self.url,
            digest=self.meta.digest,
            tag=self.meta.tag,
            application=self.meta.application,
            filters=self.meta.filter,
        )


@dataclass
class FileTaskProgress:
    state: str                  # running | done | failed
    task_id: str = ""
    peer_id: str = ""
    content_length: int = -1
    completed_length: int = 0
    piece_count: int = 0
    total_piece_count: int = -1
    digest: str = ""
    error: dict | None = None
    from_reuse: bool = False
    from_p2p: bool = False
    # True when the content also landed in a device sink and passed
    # on-device verification (device="tpu" requests).
    device_verified: bool = False

    def to_wire(self) -> dict:
        return {
            "state": self.state,
            "task_id": self.task_id,
            "peer_id": self.peer_id,
            "content_length": self.content_length,
            "completed_length": self.completed_length,
            "piece_count": self.piece_count,
            "total_piece_count": self.total_piece_count,
            "digest": self.digest,
            "error": self.error,
            "from_reuse": self.from_reuse,
            "from_p2p": self.from_p2p,
            "device_verified": self.device_verified,
        }


class _RunningTask:
    def __init__(self, store):
        self.store = store
        self.done = asyncio.Event()
        self.error: DfError | None = None


class TaskManager:
    """Front-end for file/stream/seed tasks; owns conductor dedup and the
    piece broker."""

    def __init__(
        self,
        storage: StorageManager,
        piece_manager: PieceManager,
        *,
        host_ip: str = "127.0.0.1",
        scheduler_client=None,
        conductor_factory=None,
        total_rate_limit: int = 0,
        host_wire=None,
        traffic_shaper: str = "plain",
        pex=None,
        prefetch: bool = False,
        device_sinks=None,
        flight=None,
    ):
        self.storage = storage
        self.piece_manager = piece_manager
        # HBM terminal store (daemon/peer/device_sink.DeviceSinkManager) —
        # present iff TPUSinkOption.enabled; requests select it per task
        # via FileTaskRequest.device == "tpu".
        self.device_sinks = device_sinks
        # Ranged-request prefetch: a range miss also kicks off a background
        # whole-task download (reference peertask_manager.go:288).
        self.prefetch = prefetch
        self.host_ip = host_ip
        self.scheduler_client = scheduler_client
        self.conductor_factory = conductor_factory
        # () -> AnnounceHost-shaped dict (or {} before the daemon starts);
        # used to advertise imported tasks under the daemon's one identity.
        self.host_wire = host_wire
        # Gossip peer exchange (daemon/pex.py): schedulerless peer discovery
        # + task-possession broadcast (reference client/daemon/pex/).
        self.pex = pex
        from dragonfly2_tpu.daemon.peer.traffic_shaper import TrafficShaper
        from dragonfly2_tpu.pkg.quarantine import ParentQuarantine

        # Daemon-wide bad-parent quarantine: ONE decaying-penalty registry
        # shared by every conductor (and the PEX pull path), keyed by the
        # parent's serving endpoint — a parent that served corrupt bytes
        # for one task is not trusted for the next.
        self.quarantine = ParentQuarantine()
        self.shaper = TrafficShaper(
            total_rate_limit if total_rate_limit > 0 else float("inf"),
            algorithm=traffic_shaper)
        # Shared bucket (plain algorithm / non-task transfers).
        self.limiter = self.shaper._shared
        self.broker = PieceBroker()
        # Flight recorder (pkg/flight): the bounded task index; download
        # paths stamp events, terminal paths finish the flight
        # (histograms + post-mortem dump on failure). Injectable so
        # embedded multi-daemon tests keep per-daemon recorders; real
        # daemons share the process-wide one.
        self.flight = flight if flight is not None else flightlib.recorder()
        self._running: dict[str, _RunningTask] = {}
        # Last completed P2P pull's bytes per parent locality
        # (conductor.locality_bytes), keyed by task id — the striped
        # e2e/bench per-host DCN-bytes readout. Bounded: small dicts,
        # overwritten per task id, cleared with the entry cap below.
        self.locality_bytes: dict[str, dict] = {}
        # Last delta landing's byte/chunk accounting per task id
        # (delta/resolver.py): reused vs fetched bytes, corrupt-base
        # refetches. Same bounding discipline as locality_bytes.
        self.delta_stats: dict[str, dict] = {}

    # -- shared download core ---------------------------------------------

    async def _run_download(self, task_id: str, peer_id: str, req: FileTaskRequest,
                            store, progress_q: "_ProgressAggregator | None",
                            *, is_seed: bool = False) -> bool:
        """Run the download into ``store``; returns from_p2p. Publishes piece
        events to the broker so SyncPieceTasks children see pieces live."""

        # Ranged tasks land too: the store's piece grid is slice-relative
        # (download_source treats the range as the content), so the sink's
        # geometry is simply the slice's. This is what sharded checkpoint
        # pulls ride — each host lands only its own tensors' byte ranges
        # (client/device.py download_sharded).
        sink_wanted = (req.device == "tpu" and self.device_sinks is not None)
        tf = self.flight.task(task_id)

        async def on_piece(st, rec) -> None:
            m = st.metadata
            self.broker.publish(task_id, PieceEvent(
                [rec.num], m.total_piece_count, m.content_length, m.piece_size,
                digests={rec.num: rec.digest}))
            if sink_wanted:
                # Land into HBM as the piece verifies — by completion the
                # device buffer only awaits the final on-device check.
                tf.record(flightlib.EV_HBM_START, rec.num)
                await self.device_sinks.on_piece(task_id, st, rec)
                tf.record(flightlib.EV_HBM_LANDED, rec.num)
            if progress_q is not None:
                await progress_q.on_piece(st, rec)

        use_p2p = self.scheduler_client is not None and self.conductor_factory is not None
        limiter = self.shaper.start_task(task_id)
        try:
            if use_p2p:
                conductor = self.conductor_factory(
                    task_id=task_id, peer_id=peer_id, request=req, store=store,
                    on_piece=on_piece, is_seed=is_seed, limiter=limiter,
                )
                try:
                    await conductor.run()
                finally:
                    if len(self.locality_bytes) > 256:
                        self.locality_bytes.clear()
                    self.locality_bytes[task_id] = dict(
                        getattr(conductor, "locality_bytes", {}) or {})
                return conductor.from_p2p
            if self.pex is not None:
                # Schedulerless P2P: gossip told us who holds this task.
                # A failed attempt (stale holders, mid-transfer stall) falls
                # through to back-source rather than failing the task.
                try:
                    if await self._pex_download(task_id, peer_id, store,
                                                on_piece, limiter):
                        return True
                except DfError as e:
                    if req.disable_back_source:
                        raise
                    log.warning("pex download failed, falling back to source",
                                task_id=task_id[:16], error=str(e))
            # A ranged task whose slice a LOCAL parent store already
            # covers imports it without touching origin (not a
            # back-source at all — allowed even when origin is off
            # the table).
            if await self.import_range_from_local_parent(store, req,
                                                         on_piece):
                return False
            if req.disable_back_source:
                raise DfError(Code.ClientBackSourceError,
                              "no scheduler and back-to-source disabled")
            if LocalTaskStore.completion_digest_applies(
                    req.meta.digest, req.range is not None):
                # Back-source pieces are self-computed — no parent map can
                # ever certify them — so the completion re-hash is certain:
                # overlap it with the download (storage _PrefixHasher).
                store.start_prefix_hasher(req.meta.digest)
            await self.piece_manager.download_source(
                store, req.url, req.meta.header,
                content_range=req.range,
                on_piece=on_piece,
                limiter=limiter,
            )
            return False
        finally:
            self.shaper.finish_task(task_id)

    async def _pex_download(self, task_id: str, peer_id: str, store,
                            on_piece, limiter) -> bool:
        """Pull every piece from PEX-discovered holders (no scheduler in the
        loop — reference pex/peer_exchange.go's scheduler-free path). Returns
        False when gossip knows no live holder; raises only on mid-transfer
        failure with no usable parent left."""
        from dragonfly2_tpu.daemon.peer.piece_dispatcher import PieceDispatcher
        from dragonfly2_tpu.daemon.peer.piece_downloader import (
            PieceDownloader,
            is_parent_gone,
            pull_one_piece,
        )
        from dragonfly2_tpu.daemon.peer.synchronizer import PieceTaskSynchronizer

        holders = self.pex.find_holders(task_id)
        holders = [m for m in holders if m.peer_port and m.upload_port]
        if not holders:
            return False
        dispatcher = PieceDispatcher(quarantine=self.quarantine)
        synchronizer = PieceTaskSynchronizer(task_id, peer_id, dispatcher)
        downloader = PieceDownloader()
        dispatcher.mark_known_downloaded(store.metadata.pieces.keys())
        synchronizer.sync_parents([
            {"id": m.node_id,
             "host": {"ip": m.ip, "port": m.peer_port,
                      "upload_port": m.upload_port}}
            for m in holders])
        log.info("pex download", task_id=task_id[:16], holders=len(holders))

        async def worker() -> None:
            while not dispatcher.is_complete():
                assignment = await dispatcher.get(timeout=15.0)
                if assignment is None:
                    if dispatcher.is_complete():
                        return
                    raise DfError(Code.ClientPieceDownloadFail,
                                  "pex download stalled (no usable holders)")
                try:
                    rec = await pull_one_piece(
                        downloader, store, dispatcher, assignment,
                        task_id=task_id, peer_id=peer_id, limiter=limiter)
                except DfError as e:
                    dispatcher.report_failure(assignment,
                                              parent_gone=is_parent_gone(e))
                    from dragonfly2_tpu.daemon.peer.piece_downloader import (
                        failure_reason,
                    )
                    from dragonfly2_tpu.daemon.peer.piece_dispatcher import (
                        parent_key,
                    )

                    self.quarantine.penalize(parent_key(assignment.parent),
                                             failure_reason(e))
                    continue
                dispatcher.report_success(assignment, rec.cost_ms)
                await on_piece(store, rec)

        try:
            workers = [asyncio.ensure_future(worker()) for _ in range(4)]
            try:
                await asyncio.gather(*workers)
            except BaseException:
                for w in workers:
                    w.cancel()
                await asyncio.gather(*workers, return_exceptions=True)
                raise
        finally:
            await synchronizer.close()
            await downloader.close()
        if not dispatcher.is_complete():
            raise DfError(Code.ClientPieceDownloadFail, "pex download incomplete")
        if (store.metadata.content_length < 0
                and dispatcher.content_length >= 0):
            store.update_task(content_length=dispatcher.content_length)
        return True

    def _pex_announce(self, task_id: str) -> None:
        if self.pex is not None:
            self.pex.add_task(task_id)

    # -- import / export (dfcache — reference client/dfcache + ImportFile) --

    async def import_task(self, path: str, req: "FileTaskRequest", *,
                          persistent: bool = False, replica_count: int = 1,
                          ttl: float = 0.0) -> dict:
        """Import a local file as a completed P2P task (reference
        piece_manager.go:662 ImportFile + dfcache Import). With
        ``persistent``, the scheduler records it as a persistent cache task
        and replicates it to ``replica_count`` hosts (reference
        UploadPersistentCacheTask* family, service_v2.go:1726-1895)."""
        task_id = req.task_id()
        peer_id = req.peer_id or idgen.peer_id_v1(self.host_ip)
        if persistent:
            await self._persistent_call(
                "Scheduler.UploadPersistentCacheTaskStarted", task_id, peer_id,
                {"url": req.url, "tag": req.meta.tag,
                 "application": req.meta.application,
                 "replica_count": replica_count, "ttl": ttl,
                 "digest": req.meta.digest})
        try:
            result = await self._import_local(path, req, task_id, peer_id)
        except BaseException:
            if persistent:
                try:
                    # Best-effort: a scheduler/network error here must not
                    # mask the real import failure.
                    await self._persistent_call(
                        "Scheduler.UploadPersistentCacheTaskFailed",
                        task_id, peer_id, {})
                except Exception as notify_err:
                    log.warning("persistent-failed notify failed",
                                error=str(notify_err))
            raise
        if persistent:
            await self._persistent_call(
                "Scheduler.UploadPersistentCacheTaskFinished", task_id, peer_id,
                {"content_length": result["content_length"],
                 "piece_size": result.get("piece_size", 0),
                 "total_piece_count": result.get("total_piece_count", -1)})
        return result

    async def _persistent_call(self, method: str, task_id: str, peer_id: str,
                               extra: dict) -> None:
        if self.scheduler_client is None:
            raise DfError(Code.BadRequest,
                          "persistent import needs a scheduler connection")
        host_info = self.host_wire() if self.host_wire is not None else {}
        host_info.pop("telemetry", None)
        await self.scheduler_client.unary(
            task_id, method,
            {"task_id": task_id, "peer_id": peer_id,
             "host": host_info, **extra})

    async def _import_local(self, path: str, req: "FileTaskRequest",
                            task_id: str, peer_id: str) -> dict:
        existing = self.storage.find_completed_task(task_id)
        if existing is None:
            store = self.storage.register_task(TaskStoreMetadata(
                task_id=task_id, peer_id=peer_id, url=req.url,
                tag=req.meta.tag, application=req.meta.application))
            with store:
                try:
                    await self.piece_manager.import_file(store, path)
                    if req.meta.digest:
                        # Whole-content hash: off the loop (hashlib releases
                        # the GIL; inline it stalls every active transfer).
                        await asyncio.to_thread(
                            store.validate_digest, req.meta.digest)
                        store.metadata.digest = req.meta.digest
                    store.mark_done()
                    self._pex_announce(task_id)
                except BaseException:
                    # A half-imported store must not be resumed by a retry:
                    # stale piece records would outlive a changed source file
                    # (start_file_task applies the same rule).
                    store.mark_invalid()
                    raise
        else:
            store = existing
        await self._announce_local_task(store, task_id, peer_id)
        return {"task_id": task_id, "peer_id": peer_id,
                "pieces": len(store.metadata.pieces),
                "piece_size": store.metadata.piece_size,
                "total_piece_count": store.metadata.total_piece_count,
                "content_length": store.metadata.content_length}

    async def _announce_local_task(self, store, task_id: str, peer_id: str) -> None:
        """Tell the scheduler this host holds the complete task so it can be
        scheduled as a parent (Scheduler.AnnounceTask)."""
        if self.scheduler_client is None or self.host_wire is None:
            return
        try:
            host_info = self.host_wire()
            if not host_info:
                return
            host_info.pop("telemetry", None)
            m = store.metadata
            await self.scheduler_client.announce_task({
                "task_id": task_id, "peer_id": peer_id, "url": m.url,
                "tag": m.tag, "application": m.application, "host": host_info,
                "content_length": m.content_length, "piece_size": m.piece_size,
                "total_piece_count": m.total_piece_count,
                "piece_nums": sorted(m.pieces.keys()),
            })
        except Exception as e:
            log.warning("announce_task failed", task_id=task_id[:16], error=str(e))

    # -- file task (reference peertask_manager.go:328) ---------------------

    async def start_file_task(self, req: FileTaskRequest) -> AsyncIterator[FileTaskProgress]:
        task_id = req.task_id()
        peer_id = req.peer_id or idgen.peer_id_v1(self.host_ip)

        # 1. Reuse: completed local task (reference peertask_reuse.go:50).
        reused = self.storage.find_completed_task(task_id)
        if reused is not None:
            log.info("reusing completed task", task_id=task_id[:16])
            if req.output:
                # Pin across the off-loop copy: the await yields, and an
                # unpinned store can be GC-reclaimed mid-hardlink.
                with reused:
                    await asyncio.to_thread(reused.store_to, req.output)
            try:
                dev = await self._finalize_device(req, task_id, reused)
            except DfError as e:
                yield FileTaskProgress(state="failed", task_id=task_id,
                                       peer_id=peer_id, error=e.to_wire())
                return
            yield self._final_progress(reused, task_id, peer_id,
                                       from_reuse=True, device_verified=dev)
            return

        # 1b. Ranged request: serve the slice off the whole-content parent
        # task when its pieces cover the range — completed OR partial
        # (reference peertask_reuse.go:234 + FindPartialCompletedTask).
        # Device requests skip this (the export path is file-only; a
        # fresh ranged task below lands into the sink), and so do
        # output-less requests (gateway ranged prefetch: nothing to
        # export — the fresh ranged task imports from the warm parent
        # via _covering_local_parent instead). The local parent keeps
        # serving its pieces to other peers either way.
        if req.meta.range and req.device != "tpu" and req.output:
            covering = self._covering_local_parent(req)
            if covering is not None:
                parent, rng = covering
                log.info("reusing ranged slice from parent task",
                         parent=parent.metadata.task_id[:16],
                         start=rng.start, length=rng.length)
                with parent:
                    await asyncio.to_thread(parent.export_range, req.output,
                                            rng.start, rng.length)
                yield FileTaskProgress(
                    state="done", task_id=task_id, peer_id=peer_id,
                    content_length=rng.length, completed_length=rng.length,
                    piece_count=0, total_piece_count=0, from_reuse=True)
                return
            # Miss: the ranged task downloads just its delta below; with
            # prefetch on, the whole task starts in the background so the
            # next overlapping range hits the parent store.
            self._maybe_prefetch(req.parent_task_id(), req)

        # 2. Dedup: piggyback on a running conductor for the same task
        # (reference getOrCreatePeerTaskConductor :201).
        running = self._running.get(task_id)
        if running is not None:
            log.info("waiting on running task", task_id=task_id[:16])
            await running.done.wait()
            if running.error is not None:
                yield FileTaskProgress(state="failed", task_id=task_id, peer_id=peer_id,
                                       error=running.error.to_wire())
                return
            store = self.storage.find_completed_task(task_id)
            if store is None:
                yield FileTaskProgress(
                    state="failed", task_id=task_id, peer_id=peer_id,
                    error=DfError(Code.UnknownError, "dedup race: no store").to_wire())
                return
            if req.output:
                with store:
                    await asyncio.to_thread(store.store_to, req.output)
            try:
                dev = await self._finalize_device(req, task_id, store)
            except DfError as e:
                yield FileTaskProgress(state="failed", task_id=task_id,
                                       peer_id=peer_id, error=e.to_wire())
                return
            yield self._final_progress(store, task_id, peer_id,
                                       from_reuse=True, device_verified=dev)
            return

        store = self.storage.register_task(
            TaskStoreMetadata(
                task_id=task_id,
                peer_id=peer_id,
                url=req.url,
                tag=req.meta.tag,
                application=req.meta.application,
                header=dict(req.meta.header),
            )
        )
        run = _RunningTask(store)
        self._running[task_id] = run
        progress_q = _ProgressAggregator(task_id, peer_id, store)
        store.pin()
        from_p2p = False
        download = asyncio.ensure_future(
            self._run_download(task_id, peer_id, req, store, progress_q))
        try:
            async for p in self._stream_progress(download, progress_q):
                yield p
            from_p2p = download.result()
            # Verify + land output inside the same failure envelope.
            await self._finalize_content_digest(req, store)
            store.mark_done()
            self.flight.finish_task(task_id, "done")
            self._pex_announce(task_id)
            if req.output:
                await asyncio.to_thread(store.store_to, req.output)
        except DfError as e:
            self._discard_sink(req, task_id)
            store.mark_invalid()
            run.error = e
            self.flight.finish_task(task_id, "failed", note=str(e))
            self.broker.publish(task_id, PieceEvent([], failed=True))
            yield FileTaskProgress(state="failed", task_id=task_id, peer_id=peer_id,
                                   error=e.to_wire())
            return
        except Exception as e:  # pragma: no cover - defensive
            log.error("file task crashed", exc_info=True)
            self._discard_sink(req, task_id)
            store.mark_invalid()
            run.error = DfError(Code.UnknownError, describe(e))
            self.flight.finish_task(task_id, "failed", note=describe(e))
            self.broker.publish(task_id, PieceEvent([], failed=True))
            yield FileTaskProgress(state="failed", task_id=task_id, peer_id=peer_id,
                                   error=run.error.to_wire())
            return
        finally:
            # Early generator close (client disconnect) must not leave the
            # download running against an unpinned, deregistered store.
            if not download.done():
                download.cancel()
                try:
                    await download
                except BaseException:
                    pass
                if run.error is None:
                    run.error = DfError(Code.ClientContextCanceled,
                                        "download aborted by client")
                self._discard_sink(req, task_id)
                store.mark_invalid()
                self.flight.finish_task(task_id, "failed",
                                        note=str(run.error))
                self.broker.publish(task_id, PieceEvent([], failed=True))
            store.unpin()
            run.done.set()
            self._running.pop(task_id, None)

        self.broker.publish(task_id, PieceEvent(
            [], store.metadata.total_piece_count, store.metadata.content_length,
            store.metadata.piece_size, done=True))

        # Device finalize AFTER the disk result is final: a corrupt DEVICE
        # copy fails this requesting stream only — the store is complete,
        # digest-verified, announced, and reusable (dedup waiters and
        # future requests are served from disk).
        try:
            device_verified = await self._finalize_device(req, task_id, store)
        except DfError as e:
            yield FileTaskProgress(state="failed", task_id=task_id,
                                   peer_id=peer_id, error=e.to_wire())
            return
        yield self._final_progress(store, task_id, peer_id, from_p2p=from_p2p,
                                   device_verified=device_verified)

    # -- delta task (checkpoint-delta plane, delta/resolver.py) ------------

    async def start_delta_task(self, req: FileTaskRequest,
                               base_task_id: str) -> AsyncIterator[FileTaskProgress]:
        """Land ``req`` as a delta against the locally-landed base task:
        chunks the base already holds are copied (and digest-verified)
        locally; only changed chunks cross the wire as ranged P2P tasks.
        Degrades to a plain ``start_file_task`` whenever the delta path
        is not viable (no base, no published manifest, zero overlap)."""
        from dragonfly2_tpu.delta.resolver import run_delta_task

        async for p in run_delta_task(self, req, base_task_id):
            yield p

    # -- seed task (reference StartSeedTask :401 + seeder ObtainSeeds) -----

    async def start_seed_task(self, spec: dict) -> None:
        """Seed this daemon with a task (scheduler trigger). Runs inline;
        callers fire it as a background task."""
        try:
            # Canonical form before ANYTHING hashes it: a raw trigger span
            # ('0-7') must land under the same task id as client pulls of
            # 'bytes=0-7' or the warmed store never dedups. Defensive even
            # though the RPC chokepoint validates: this runs in a spawned
            # task where an escape would be an unretrieved exception.
            norm_range = Range.normalize_header(spec.get("range", ""))
        except ValueError as e:
            log.warning("seed trigger with malformed range dropped",
                        range=str(spec.get("range"))[:64], error=str(e)[:100])
            return
        meta = UrlMeta(
            digest=spec.get("digest", ""),
            tag=spec.get("tag", ""),
            application=spec.get("application", ""),
            header=spec.get("header") or {},
            filter="&".join(spec.get("filters") or []),
            range=norm_range,
            # QoS: a triggered preheat keeps the triggering caller's
            # tenant/priority so its pieces dispatch and account like
            # any other pull of that tenant's.
            priority=int(spec.get("priority", 3) or 3),
            tenant=spec.get("tenant", ""),
        )
        # seed=False: run as a normal peer (persistent-cache replication —
        # the scheduler wants this host to PULL from peers, not re-seed from
        # origin; dfcache:// tasks have no origin at all).
        is_seed = spec.get("seed", True)
        req = FileTaskRequest(url=spec.get("url", ""), output="", meta=meta,
                              disable_back_source=bool(
                                  spec.get("disable_back_source")),
                              device=spec.get("device", ""),
                              pod_broadcast=bool(spec.get("pod_broadcast")))
        if meta.range:
            req.range = Range.parse_http(meta.range)
        task_id = spec.get("task_id") or req.task_id()
        running = self._running.get(task_id)
        if running is not None:
            # Already seeding. A device=tpu trigger must still land the
            # content in HBM (device is not part of the task identity, so a
            # plain seed in flight would otherwise silently swallow it):
            # wait for the running download, then finalize the sink.
            if req.device != "tpu":
                return
            await running.done.wait()
            if running.error is None:
                store = self.storage.find_completed_task(task_id)
                if store is not None:
                    await self._finalize_device_for_seed(req, task_id, store)
            return
        peer_id = (idgen.seed_peer_id_v1(self.host_ip) if is_seed
                   else idgen.peer_id_v1(self.host_ip))

        store = self.storage.register_task(
            TaskStoreMetadata(task_id=task_id, peer_id=peer_id, url=req.url,
                              tag=meta.tag, application=meta.application,
                              header=dict(meta.header)))
        run = _RunningTask(store)
        self._running[task_id] = run
        store.pin()
        try:
            await self._run_download(task_id, peer_id, req, store, None,
                                     is_seed=is_seed)
            # The seed is the TRUST ANCHOR of the piece-digest chain: its
            # back-sourced pieces carry self-computed crcs (never
            # certified), so the helper's re-hash branch proves the full
            # digest HERE, before announce — otherwise a corrupted origin
            # response would fan out pod-wide under per-piece digests that
            # faithfully match the corruption.
            await self._finalize_content_digest(req, store)
            store.mark_done()
            self.flight.finish_task(task_id, "done")
            # Disk result is final: announce and publish FIRST (peers and
            # dedup waiters must not stall behind the HBM backfill — the
            # device copy cannot affect the disk result either way).
            self._pex_announce(task_id)
            self.broker.publish(task_id, PieceEvent(
                [], store.metadata.total_piece_count, store.metadata.content_length,
                store.metadata.piece_size, done=True))
            device_verified = await self._finalize_device_for_seed(
                req, task_id, store)
            log.info("seed task complete", task_id=task_id[:16],
                     pieces=len(store.metadata.pieces),
                     **({"device_verified": device_verified}
                        if req.device else {}))
        except Exception as e:
            log.error("seed task failed", error=describe(e))
            store.mark_invalid()
            run.error = e if isinstance(e, DfError) else DfError(Code.UnknownError, describe(e))
            self.flight.finish_task(task_id, "failed", note=describe(e))
            self.broker.publish(task_id, PieceEvent([], failed=True))
        finally:
            store.unpin()
            run.done.set()
            self._running.pop(task_id, None)

    # -- stream task (reference StartStreamTask :357, peertask_stream.go) --

    class _StreamBody:
        """Ordered-piece stream body that releases its broker subscription
        even when aclose()d before the first iteration — an unstarted async
        generator's finally never runs (PEP 525), which would leak the
        queue for the lifetime of the daemon."""

        def __init__(self, broker, task_id: str, gen, q):
            self._broker = broker
            self._task_id = task_id
            self._gen = gen
            self._q = q

        def __aiter__(self):
            return self

        async def __anext__(self):
            return await self._gen.__anext__()

        async def aclose(self) -> None:
            try:
                await self._gen.aclose()
            finally:
                # Idempotent: the generator's own finally also unsubscribes
                # when it got far enough to run.
                self._broker.unsubscribe(self._task_id, self._q)

    async def start_stream_task(self, req: StreamTaskRequest):
        """Returns (attrs, body_iterator). attrs carries task/peer id,
        content_length (may be -1 for unknown-length origins until done) and
        reuse flags; the iterator yields ordered byte chunks as pieces land
        (reference peertask_stream.go:274 writeOrderedPieces)."""
        task_id = req.task_id()
        peer_id = req.peer_id or idgen.peer_id_v1(self.host_ip)

        store = self.storage.find_completed_task(task_id)
        if store is not None:
            attrs = self._stream_attrs(store, task_id, peer_id, from_reuse=True)
            rng = self._resolve_range(req.range, attrs["content_length"])
            attrs["range"] = rng
            # Completed-store reuse: expose the store so HTTP gateways can
            # sendfile the window instead of iterating bytes through Python
            # (daemon/objectstorage.py warm path).
            attrs["local_store"] = store
            return attrs, self._stream_from_store(store, rng)

        # Ranged stream against a partially-downloaded task: serve straight
        # off the store when the range's pieces already landed (reference
        # tryReuseStreamPeerTask :234 partial reuse).
        if req.range is not None:
            partial = self.storage.find_partial_completed_task(task_id)
            if partial is not None and partial.metadata.piece_size > 0:
                rng = self._resolve_range(req.range,
                                          partial.metadata.content_length)
                if (rng is not None and rng.length > 0
                        and partial.covers_range(rng.start, rng.length)):
                    attrs = self._stream_attrs(partial, task_id, peer_id,
                                               from_reuse=True)
                    attrs["range"] = rng
                    # Landed window of an in-progress task: expose the
                    # store so HTTP gateways sendfile the covered range
                    # (sendfile_window re-checks coverage) instead of
                    # iterating bytes through Python.
                    attrs["local_store"] = partial
                    return attrs, self._stream_from_store(partial, rng)

        q = self.broker.subscribe(task_id)
        run = self._running.get(task_id)
        if run is None:
            # The task may have completed between the reuse check and the
            # subscribe — re-check before starting a fresh download.
            store = self.storage.find_completed_task(task_id)
            if store is not None:
                self.broker.unsubscribe(task_id, q)
                attrs = self._stream_attrs(store, task_id, peer_id, from_reuse=True)
                rng = self._resolve_range(req.range, attrs["content_length"])
                attrs["range"] = rng
                attrs["local_store"] = store
                return attrs, self._stream_from_store(store, rng)
            file_req = FileTaskRequest(
                url=req.url, output="", meta=req.meta, peer_id=peer_id,
                disable_back_source=req.disable_back_source)
            store = self.storage.register_task(TaskStoreMetadata(
                task_id=task_id, peer_id=peer_id, url=req.url,
                tag=req.meta.tag, application=req.meta.application,
                header=dict(req.meta.header)))
            run = _RunningTask(store)
            self._running[task_id] = run
            store.pin()
            aio.spawn(
                self._run_background_download(task_id, peer_id, file_req, store, run))
        else:
            store = run.store

        # Wait for enough metadata to answer headers: content length, the
        # first piece, or a terminal event.
        try:
            while (store.metadata.content_length < 0
                   and not store.has_piece(0)
                   and run.error is None and not run.done.is_set()):
                ev = await q.get()
                if ev.failed:
                    break
        except asyncio.CancelledError:
            self.broker.unsubscribe(task_id, q)
            raise
        if run.error is not None:
            self.broker.unsubscribe(task_id, q)
            raise run.error
        attrs = self._stream_attrs(store, task_id, peer_id)
        rng = self._resolve_range(req.range, attrs["content_length"])
        attrs["range"] = rng
        # In-progress store exposed: if the requested window's pieces have
        # already landed by the time the gateway/proxy picks a serving
        # strategy, sendfile_window lets it skip the Python iterator
        # entirely; otherwise it falls back to the ordered stream below.
        attrs["local_store"] = store
        return attrs, self._StreamBody(
            self.broker, task_id, self._stream_ordered(task_id, store, run, q, rng), q)

    @staticmethod
    def _resolve_range(rng: Range | None, content_length: int) -> Range | None:
        """Open-ended ranges (``bytes=N-`` parsed as length=-1) resolve to
        [start, content_length) once the length is known; with an
        unknown-length origin the open end means "to EOF"."""
        if rng is not None and rng.length < 0 and content_length >= 0:
            return Range(rng.start, max(0, content_length - rng.start))
        return rng

    def _maybe_prefetch(self, parent_id: str, req: FileTaskRequest) -> None:
        """Kick off a background whole-task download after a ranged-request
        miss (reference peertask_manager.go:288 prefetch)."""
        if not self.prefetch or parent_id in self._running:
            return
        if self.storage.find_completed_task(parent_id) is not None:
            return
        from dataclasses import replace

        meta = replace(req.meta, range="", header=dict(req.meta.header))
        meta.header.pop("Range", None)
        peer_id = idgen.peer_id_v1(self.host_ip)
        file_req = FileTaskRequest(url=req.url, output="", meta=meta,
                                   peer_id=peer_id)
        store = self.storage.register_task(TaskStoreMetadata(
            task_id=parent_id, peer_id=peer_id, url=req.url, tag=meta.tag,
            application=meta.application, header=dict(meta.header)))
        run = _RunningTask(store)
        self._running[parent_id] = run
        store.pin()
        log.info("prefetching whole task for ranged request",
                 task=parent_id[:16])
        aio.spawn(self._run_background_download(
            parent_id, peer_id, file_req, store, run))

    async def _run_background_download(self, task_id: str, peer_id: str,
                                       req: FileTaskRequest, store, run: _RunningTask) -> None:
        """Download driver for stream tasks (no output file, no progress
        aggregator; completion is observed through the broker)."""
        try:
            await self._run_download(task_id, peer_id, req, store, None)
            await self._finalize_content_digest(req, store)
            store.mark_done()
            self.flight.finish_task(task_id, "done")
            self._pex_announce(task_id)
            self.broker.publish(task_id, PieceEvent(
                [], store.metadata.total_piece_count,
                store.metadata.content_length, store.metadata.piece_size,
                done=True))
        except DfError as e:
            store.mark_invalid()
            run.error = e
            self.flight.finish_task(task_id, "failed", note=str(e))
            self.broker.publish(task_id, PieceEvent([], failed=True))
        except Exception as e:  # pragma: no cover - defensive
            log.error("stream download crashed", exc_info=True)
            store.mark_invalid()
            run.error = DfError(Code.UnknownError, describe(e))
            self.flight.finish_task(task_id, "failed", note=describe(e))
            self.broker.publish(task_id, PieceEvent([], failed=True))
        finally:
            store.unpin()
            run.done.set()
            self._running.pop(task_id, None)

    def _stream_attrs(self, store, task_id: str, peer_id: str, *,
                      from_reuse: bool = False) -> dict:
        m = store.metadata
        return {
            "task_id": task_id,
            "peer_id": peer_id,
            "content_length": m.content_length,
            "piece_size": m.piece_size,
            "total_piece_count": m.total_piece_count,
            "from_reuse": from_reuse,
        }

    # Bound on one coalesced span read/yield: two fleet-default (4 MiB)
    # pieces per submission; small-piece tasks batch many more.
    _STREAM_SPAN = 8 << 20

    async def _stream_from_store(self, store, rng: Range | None) -> AsyncIterator[bytes]:
        """Completed task: emit the requested window straight off disk in
        bounded spans (pooled preadv — contiguous on a complete store),
        touching only the bytes that intersect the range. Yielded chunks
        are BORROWED pooled views, valid until the consumer asks for the
        next chunk (docs/ZERO_COPY.md rule 6); retainers must copy."""
        store.pin()
        try:
            m = store.metadata
            end = m.content_length if m.content_length >= 0 else \
                store.disk_usage()
            start = 0
            if rng is not None:
                start = min(rng.start, end)
                if rng.length >= 0:
                    end = min(end, rng.start + rng.length)
            span = max(m.piece_size, 1 << 20)
            off = start
            while off < end:
                take = min(span, end - off)
                chunk = await asyncio.to_thread(store.read_range, off, take)
                try:
                    yield chunk
                finally:
                    # Runs when the consumer resumes us (it is done with
                    # the view) or closes the generator: either way the
                    # buffer recycles for the next span.
                    release_read_buffer(chunk)
                off += take
        finally:
            store.unpin()

    async def _stream_ordered(self, task_id: str, store, run: _RunningTask,
                              q: asyncio.Queue, rng: Range | None) -> AsyncIterator[bytes]:
        """Running task: emit pieces in order as they land; pieces ahead of
        the contiguous frontier wait in the store until the gap fills.
        Adjacent landed pieces coalesce into ONE bounded pooled preadv
        (batched submission) instead of a bytes() allocation per piece;
        yielded chunks are borrowed pooled views (docs/ZERO_COPY.md
        rule 6), valid until the next chunk is requested."""
        next_num = 0
        store.pin()
        try:
            while True:
                m = store.metadata
                while store.has_piece(next_num):
                    # Pieces wholly before the range advance the frontier
                    # without touching disk.
                    if (rng is not None and m.piece_size > 0
                            and (next_num + 1) * m.piece_size <= rng.start):
                        next_num += 1
                        continue
                    # Coalesce the landed run starting at next_num into one
                    # span, bounded by _STREAM_SPAN and the range end.
                    first = m.pieces[next_num]
                    lo, hi = first.offset, first.offset + first.size
                    last = next_num
                    while hi - lo < self._STREAM_SPAN:
                        nxt = m.pieces.get(last + 1)
                        if nxt is None:
                            break
                        if rng is not None and rng.length >= 0 and \
                                hi >= rng.start + rng.length:
                            break
                        hi = nxt.offset + nxt.size
                        last = nxt.num
                    if rng is not None:
                        lo = max(lo, rng.start)
                        if rng.length >= 0:
                            hi = min(hi, rng.start + rng.length)
                    if hi > lo:
                        chunk = await asyncio.to_thread(
                            store.read_range, lo, hi - lo)
                        try:
                            yield chunk
                        finally:
                            release_read_buffer(chunk)
                    next_num = last + 1
                    # Past the requested range: nothing further to emit
                    # (open-ended ranges run to EOF).
                    if rng is not None and rng.length >= 0 and m.piece_size > 0 and \
                            next_num * m.piece_size >= rng.start + rng.length:
                        return
                if run.error is not None:
                    raise run.error
                if m.total_piece_count >= 0 and next_num >= m.total_piece_count:
                    return
                if run.done.is_set() and not store.has_piece(next_num):
                    # Completed without the piece we need -> invalidated.
                    raise DfError(Code.UnknownError, "stream task ended short")
                ev = await q.get()
                if ev.failed and run.error is not None:
                    raise run.error
        finally:
            store.unpin()
            self.broker.unsubscribe(task_id, q)

    def is_task_running(self, task_id: str) -> bool:
        return task_id in self._running

    # -- helpers -----------------------------------------------------------

    def _final_progress(self, store, task_id: str, peer_id: str, *,
                        from_reuse: bool = False, from_p2p: bool = False,
                        device_verified: bool = False) -> FileTaskProgress:
        m = store.metadata
        return FileTaskProgress(
            state="done",
            task_id=task_id,
            peer_id=peer_id,
            content_length=m.content_length,
            completed_length=store.downloaded_bytes(),
            piece_count=len(m.pieces),
            total_piece_count=m.total_piece_count,
            digest=m.digest,
            from_reuse=from_reuse,
            from_p2p=from_p2p,
            device_verified=device_verified,
        )

    def _discard_sink(self, req: "FileTaskRequest", task_id: str) -> None:
        """Drop a partially-landed sink on any failure/abort path: a stale
        resident sink could otherwise shadow a later retry's bytes."""
        if req.device and self.device_sinks is not None:
            self.device_sinks.discard(task_id)

    def _covering_local_parent(self, req):
        """(parent_store, resolved_range) when a LOCAL completed/partial
        parent task covers ``req``'s range, else None. The ONE
        parent-coverage gate — the ranged-reuse export (step 1b) and the
        ranged import share it, so their eligibility can never fork."""
        if not req.meta.range:
            return None
        parent_id = req.parent_task_id()
        parent = (self.storage.find_completed_task(parent_id)
                  or self.storage.find_partial_completed_task(parent_id))
        if parent is None or parent.metadata.piece_size <= 0:
            return None
        total = parent.metadata.content_length
        try:
            rng = Range.parse_http(req.meta.range, total)
        except ValueError:
            return None
        if rng is None:
            return None
        # Clamp EOF-overshooting spans exactly like download_source does
        # before fetching: origin clamps 'bytes=0-262143' on a 100 KiB
        # object, so the warm local parent must serve the same clamped
        # slice — otherwise every overshooting range (the header guess on
        # a small checkpoint, a generous user range) skips the warm store
        # and re-touches origin.
        length = rng.length
        if total >= 0:
            length = min(length, max(0, total - rng.start))
        if length <= 0 or not parent.covers_range(rng.start, length):
            return None
        return parent, Range(rng.start, length)

    async def import_range_from_local_parent(self, store, req, on_piece) -> bool:
        """Ranged back-source shortcut: when THIS daemon already holds a
        whole-content (or covering partial) parent task, the slice
        imports from the local store instead of touching origin.

        This is what makes plain whole-file preheats compose with
        sharded pulls: a ranged task is a distinct task id, so without
        this every span the scheduler triggers on a warm seed would
        re-fetch from origin despite the seed holding every byte.
        Imported pieces flow through ``on_piece`` like downloaded ones
        (piece reports, device-sink landings, progress). Returns True
        when the ranged store completed from the parent; any import
        failure (e.g. a parent truncated under its metadata) returns
        False so the caller falls back to origin — the pre-feature
        recovery path must survive the optimization."""
        covering = self._covering_local_parent(req)
        if covering is None:
            return False
        parent, rng = covering
        piece_size = store.metadata.piece_size or compute_piece_size(rng.length)
        store.update_task(content_length=rng.length, piece_size=piece_size,
                          total_piece_count=compute_piece_count(
                              rng.length, piece_size))
        log.info("ranged task imports from local parent",
                 task=store.metadata.task_id[:16],
                 parent=parent.metadata.task_id[:16],
                 start=rng.start, length=rng.length)
        try:
            with parent:  # pin: GC must not reclaim the parent mid-import
                # ONE pooled buffer reused for every piece of the import:
                # read_into fills it in place (unified read path), the
                # write lands (and digests) straight from it.
                buf = acquire_read_buffer(piece_size)
                try:
                    for n in range(store.metadata.total_piece_count):
                        if n in store.metadata.pieces:
                            continue   # resume semantics match back-source
                        off = n * piece_size
                        size = min(piece_size, rng.length - off)
                        await asyncio.to_thread(
                            parent.read_into, rng.start + off, size, buf)
                        rec = await asyncio.to_thread(
                            store.write_piece, n, buf[:size])
                        if on_piece is not None:
                            await on_piece(store, rec)
                finally:
                    release_read_buffer(buf)
        except (StorageError, OSError) as e:
            log.warning("local range import failed; falling back to origin",
                        task=store.metadata.task_id[:16], error=str(e)[:200])
            return False
        return store.is_complete()

    async def _finalize_content_digest(self, req: "FileTaskRequest",
                                       store) -> None:
        """THE single completion-digest decision point (every download
        path calls this; the skip precondition must never fork). Ranged
        tasks skip entirely — the digest names the full object, the store
        holds a slice. Complete tasks either (a) skip the O(content)
        re-hash when every piece's verified-against digest matches a
        certified parent's map (pieces_all_digest_verified — provenance-
        checked, anchored at the seed's full validation), or (b) re-hash
        off-loop (a whole-content sha256 of a multi-GB task would freeze
        this daemon's serving for seconds)."""
        if not LocalTaskStore.completion_digest_applies(
                req.meta.digest, req.range is not None):
            return
        if store.pieces_all_digest_verified():
            COMPLETION_REHASH.labels("skipped").inc()
        else:
            COMPLETION_REHASH.labels("hashed").inc()
            tf = self.flight.task(store.metadata.task_id)
            tf.record(flightlib.EV_VERIFY_START)
            await asyncio.to_thread(store.validate_digest, req.meta.digest)
            tf.record(flightlib.EV_VERIFIED)
        store.metadata.digest = req.meta.digest

    async def _finalize_device_for_seed(self, req: "FileTaskRequest",
                                        task_id: str, store) -> bool:
        """Seed/preheat variant of _finalize_device: device-copy corruption
        must NOT fail the task — the disk result is already digest-verified
        and peers depend on it (the finalize contract: fail only a
        requesting stream, and a preheat has none). Degrades to disk-only
        warm-up, loudly."""
        try:
            with store:  # pin: finalize preads run in executor threads
                return await self._finalize_device(req, task_id, store)
        except Exception as e:
            # Broad by contract: ANY escape here would reach the seed
            # task's generic handler, which marks the digest-verified,
            # already-PEX-announced disk store invalid — destroying a good
            # store peers depend on (advisor round 3). The partial sink is
            # discarded: a DeviceSinkError arrives pre-discarded, but e.g.
            # an OSError from a backfill pread would otherwise leave an
            # unverified content-sized HBM buffer parked in a sink slot.
            if self.device_sinks is not None:
                self.device_sinks.discard(task_id)
            log.error("device sink finalize failed; disk warm-up stands",
                      task_id=task_id[:16], error=describe(e))
            return False

    async def _finalize_device(self, req: "FileTaskRequest", task_id: str,
                               store) -> bool:
        """Run the device-sink completion for a ``device='tpu'`` request:
        backfill + on-device verify. Sink *unavailability* (cap reached,
        misaligned pieces, option disabled) degrades to disk-only — the
        file result is already digest-verified. Device-copy CORRUPTION
        raises: silently handing back a bad buffer would defeat
        verify-on-land. The DISK store stays valid either way — callers
        must fail only the requesting stream, not the task."""
        if req.device != "tpu":
            return False
        if self.device_sinks is None:
            log.warning("device=tpu requested but sink disabled "
                        "(TPUSinkOption.enabled=false)", task_id=task_id[:16])
            return False
        from dragonfly2_tpu.daemon.peer.device_sink import DeviceSinkError

        try:
            return await self.device_sinks.finalize(task_id, store) is not None
        except DeviceSinkError as e:
            self.device_sinks.discard(task_id)
            raise DfError(Code.ClientPieceDownloadFail,
                          f"device sink verification failed: {e}")

    async def _stream_progress(self, task: asyncio.Task, progress_q: "_ProgressAggregator"):
        while True:
            snap = await progress_q.next_or_done(task)
            if snap is not None:
                yield snap
            if task.done():
                task.result()  # re-raise
                while (s := progress_q.try_next()) is not None:
                    yield s
                return


class _ProgressAggregator:
    def __init__(self, task_id: str, peer_id: str, store):
        self.task_id = task_id
        self.peer_id = peer_id
        self.store = store
        self._event = asyncio.Event()
        self._last_report = 0.0

    async def on_piece(self, store, rec) -> None:
        self._event.set()

    def _snapshot(self) -> FileTaskProgress:
        m = self.store.metadata
        return FileTaskProgress(
            state="running",
            task_id=self.task_id,
            peer_id=self.peer_id,
            content_length=m.content_length,
            completed_length=self.store.downloaded_bytes(),
            piece_count=len(m.pieces),
            total_piece_count=m.total_piece_count,
        )

    def try_next(self) -> FileTaskProgress | None:
        if self._event.is_set():
            self._event.clear()
            now = time.monotonic()
            if now - self._last_report >= 0.1:  # throttle progress frames
                self._last_report = now
                return self._snapshot()
        return None

    async def next_or_done(self, task) -> FileTaskProgress | None:
        waiter = asyncio.ensure_future(self._event.wait())
        try:
            await asyncio.wait({waiter, task}, return_when=asyncio.FIRST_COMPLETED)
        finally:
            waiter.cancel()
        return self.try_next()
