"""Peer task manager: task front-end, dedup and reuse.

Reference: client/daemon/peer/peertask_manager.go — StartFileTask (:328),
StartStreamTask (:357), StartSeedTask (:401), conductor dedup
(getOrCreatePeerTaskConductor :201) and peertask_reuse.go (local-completion
reuse). Stage 2 wires reuse + back-to-source; the P2P conductor
(conductor.py) plugs in via ``scheduler_client``.
"""

from __future__ import annotations

import time
from dataclasses import dataclass, field
from typing import AsyncIterator

from dragonfly2_tpu.daemon.peer.piece_manager import PieceManager
from dragonfly2_tpu.pkg import dflog, idgen
from dragonfly2_tpu.pkg.errors import Code, DfError
from dragonfly2_tpu.pkg.piece import Range
from dragonfly2_tpu.pkg.ratelimit import Limiter
from dragonfly2_tpu.proto.common import UrlMeta
from dragonfly2_tpu.storage import StorageManager, TaskStoreMetadata

log = dflog.get("peer.task_manager")


@dataclass
class FileTaskRequest:
    url: str
    output: str
    meta: UrlMeta = field(default_factory=UrlMeta)
    peer_id: str = ""
    disable_back_source: bool = False
    range: Range | None = None

    def task_id(self) -> str:
        return idgen.task_id_v1(
            self.url,
            digest=self.meta.digest,
            tag=self.meta.tag,
            application=self.meta.application,
            filters=self.meta.filter,
            range_header=self.meta.range,
        )


@dataclass
class FileTaskProgress:
    state: str                  # running | done | failed
    task_id: str = ""
    peer_id: str = ""
    content_length: int = -1
    completed_length: int = 0
    piece_count: int = 0
    total_piece_count: int = -1
    digest: str = ""
    error: dict | None = None
    from_reuse: bool = False
    from_p2p: bool = False

    def to_wire(self) -> dict:
        return {
            "state": self.state,
            "task_id": self.task_id,
            "peer_id": self.peer_id,
            "content_length": self.content_length,
            "completed_length": self.completed_length,
            "piece_count": self.piece_count,
            "total_piece_count": self.total_piece_count,
            "digest": self.digest,
            "error": self.error,
            "from_reuse": self.from_reuse,
            "from_p2p": self.from_p2p,
        }


class TaskManager:
    """Front-end for file/stream/seed tasks. Holds the storage manager, the
    piece manager and (from stage 3) the conductor pool."""

    def __init__(
        self,
        storage: StorageManager,
        piece_manager: PieceManager,
        *,
        host_ip: str = "127.0.0.1",
        scheduler_client=None,
        conductor_factory=None,
        total_rate_limit: int = 0,
    ):
        self.storage = storage
        self.piece_manager = piece_manager
        self.host_ip = host_ip
        self.scheduler_client = scheduler_client
        self.conductor_factory = conductor_factory
        self.limiter = Limiter(total_rate_limit if total_rate_limit > 0 else float("inf"))

    # -- file task (reference peertask_manager.go:328) ---------------------

    async def start_file_task(self, req: FileTaskRequest) -> AsyncIterator[FileTaskProgress]:
        task_id = req.task_id()
        peer_id = req.peer_id or idgen.peer_id_v1(self.host_ip)

        # 1. Reuse: completed local task (reference peertask_reuse.go:50).
        reused = self.storage.find_completed_task(task_id)
        if reused is not None:
            log.info("reusing completed task", task_id=task_id[:16])
            reused.store_to(req.output)
            yield FileTaskProgress(
                state="done",
                task_id=task_id,
                peer_id=peer_id,
                content_length=reused.metadata.content_length,
                completed_length=reused.metadata.content_length,
                piece_count=len(reused.metadata.pieces),
                total_piece_count=reused.metadata.total_piece_count,
                digest=reused.metadata.digest,
                from_reuse=True,
            )
            return

        store = self.storage.register_task(
            TaskStoreMetadata(
                task_id=task_id,
                peer_id=peer_id,
                url=req.url,
                tag=req.meta.tag,
                application=req.meta.application,
                header=dict(req.meta.header),
            )
        )

        # 2. P2P via scheduler when wired (stage 3 conductor), else origin.
        use_p2p = self.scheduler_client is not None and self.conductor_factory is not None
        progress_q = _ProgressAggregator(task_id, peer_id, store)
        store.pin()  # GC must not reclaim the store mid-download
        try:
            if use_p2p:
                conductor = self.conductor_factory(
                    task_id=task_id, peer_id=peer_id, request=req, store=store,
                    on_piece=progress_q.on_piece,
                )
                async for p in self._run_with_progress(conductor.run(), progress_q):
                    yield p
            else:
                if req.disable_back_source:
                    raise DfError(Code.ClientBackSourceError,
                                  "no scheduler and back-to-source disabled")
                coro = self.piece_manager.download_source(
                    store, req.url, req.meta.header,
                    content_range=req.range,
                    on_piece=progress_q.on_piece,
                    limiter=self.limiter,
                )
                async for p in self._run_with_progress(coro, progress_q):
                    yield p
            # 3. Verify + land output (inside the same failure envelope: a
            # digest mismatch must invalidate the store like any other error).
            if req.meta.digest:
                store.validate_digest(req.meta.digest)
                store.metadata.digest = req.meta.digest
            store.mark_done()
            store.store_to(req.output)
        except DfError as e:
            store.mark_invalid()
            yield FileTaskProgress(state="failed", task_id=task_id, peer_id=peer_id,
                                   error=e.to_wire())
            return
        except Exception as e:  # pragma: no cover - defensive
            log.error("file task crashed", exc_info=True)
            store.mark_invalid()
            yield FileTaskProgress(state="failed", task_id=task_id, peer_id=peer_id,
                                   error=DfError(Code.UnknownError, str(e)).to_wire())
            return
        finally:
            store.unpin()

        yield FileTaskProgress(
            state="done",
            task_id=task_id,
            peer_id=peer_id,
            content_length=store.metadata.content_length,
            completed_length=store.downloaded_bytes(),
            piece_count=len(store.metadata.pieces),
            total_piece_count=store.metadata.total_piece_count,
            digest=store.metadata.digest,
            from_p2p=use_p2p,
        )

    async def _run_with_progress(self, coro, progress_q: "_ProgressAggregator"):
        """Run the download while yielding progress snapshots as pieces land."""
        import asyncio

        task = asyncio.ensure_future(coro)
        try:
            while True:
                snap = await progress_q.next_or_done(task)
                if snap is not None:
                    yield snap
                if task.done():
                    task.result()  # re-raise
                    # drain any trailing progress
                    while (s := progress_q.try_next()) is not None:
                        yield s
                    return
        finally:
            if not task.done():
                task.cancel()


class _ProgressAggregator:
    def __init__(self, task_id: str, peer_id: str, store):
        import asyncio

        self.task_id = task_id
        self.peer_id = peer_id
        self.store = store
        self._event = asyncio.Event()
        self._last_report = 0.0

    async def on_piece(self, store, rec) -> None:
        self._event.set()

    def _snapshot(self) -> FileTaskProgress:
        m = self.store.metadata
        return FileTaskProgress(
            state="running",
            task_id=self.task_id,
            peer_id=self.peer_id,
            content_length=m.content_length,
            completed_length=self.store.downloaded_bytes(),
            piece_count=len(m.pieces),
            total_piece_count=m.total_piece_count,
        )

    def try_next(self) -> FileTaskProgress | None:
        if self._event.is_set():
            self._event.clear()
            now = time.monotonic()
            if now - self._last_report >= 0.1:  # throttle progress frames
                self._last_report = now
                return self._snapshot()
        return None

    async def next_or_done(self, task) -> FileTaskProgress | None:
        import asyncio

        waiter = asyncio.ensure_future(self._event.wait())
        try:
            await asyncio.wait({waiter, task}, return_when=asyncio.FIRST_COMPLETED)
        finally:
            waiter.cancel()
        return self.try_next()
