"""Piece manager: origin (back-to-source) piece pipeline.

Reference: client/daemon/peer/piece_manager.go — DownloadSource (:304),
known-length sequential (:481), unknown-length streaming (:539), concurrent
back-to-source by piece group with byte ranges (:796-1000, pieceGroup
:876-922), optional digest computation (WithCalculateDigest :91), file
import for dfcache (ImportFile :662). Parent-peer piece downloads live in
piece_downloader.py; this module owns origin fetches and storage writes.
"""

from __future__ import annotations

import asyncio
import os
import time
from dataclasses import dataclass
from typing import Awaitable, Callable

from dragonfly2_tpu.daemon.peer.piece_downloader import (
    abandonable_native_call,
    native_connect,
)
from dragonfly2_tpu.pkg import dflog
from dragonfly2_tpu.pkg import digest as pkgdigest
from dragonfly2_tpu.pkg import flight as flightlib
from dragonfly2_tpu.pkg import retry as retrylib
from dragonfly2_tpu.pkg.errors import Code, SourceError
from dragonfly2_tpu.pkg.piece import Range, compute_piece_count, compute_piece_size
from dragonfly2_tpu.pkg.ratelimit import Limiter
from dragonfly2_tpu.source import Request as SourceRequest
from dragonfly2_tpu.source import get_client
from dragonfly2_tpu.storage.local_store import LocalTaskStore, PieceRecord, _native

log = dflog.get("peer.piece_manager")

# piece arrival callback: fired after each piece lands in storage, with the
# record and the store (conductor reports to scheduler + notifies subscribers)
PieceCallback = Callable[[LocalTaskStore, PieceRecord], Awaitable[None]]


@dataclass
class PieceManagerOption:
    concurrency: int = 4                  # concurrent range streams to origin
    compute_digest: bool = True           # per-piece md5 during write
    concurrent_min_length: int = 32 << 20 # below this, a single stream wins
    chunk_size: int = 1 << 20
    # Origin fetch retry budget: attempts for TEMPORARY failures only
    # (connect resets, 5xx, short reads). Permanent client errors
    # (403/404/416 — SourceError.temporary=False) fail on the first try:
    # re-asking the origin for a URL it authoritatively rejected can never
    # succeed, it only delays the task's failure verdict.
    origin_attempts: int = 3
    # Origin body chunk-gap watchdog (pkg/retry.watch_idle): bounds the
    # silence between chunks so a stalled origin trips in bounded time
    # instead of at the 300s request deadline. <= 0 disables.
    origin_idle_timeout: float = 60.0


class PieceManager:
    def __init__(self, opt: PieceManagerOption | None = None, limiter: Limiter | None = None):
        self.opt = opt or PieceManagerOption()
        self._limiter = limiter or Limiter()

    # -- origin download entry (reference piece_manager.go:304) ------------

    async def download_source(
        self,
        store: LocalTaskStore,
        url: str,
        header: dict[str, str] | None = None,
        *,
        content_range: Range | None = None,
        on_piece: PieceCallback | None = None,
        limiter: Limiter | None = None,
    ) -> None:
        """Fetch the full content from origin into ``store``. Decides between
        sequential, concurrent-range-group and unknown-length paths."""
        client = get_client(url)
        header = dict(header or {})
        header.pop("Range", None)
        request = SourceRequest(url, header)
        limiter = limiter or self._limiter

        content_length = store.metadata.content_length
        range_known: bool | None = None
        if content_length < 0:
            try:
                content_length, range_known = await client.probe(request)
            except SourceError:
                content_length = -1
        if content_range is not None:
            # Ranged task: treat the range as the content.
            total = content_length if content_length >= 0 else -1
            if total >= 0:
                if content_range.start >= total:
                    raise SourceError(f"range start {content_range.start} beyond length {total}",
                                      Code.BadRequest)
                length = min(content_range.length, total - content_range.start) \
                    if content_range.length >= 0 else total - content_range.start
            else:
                length = content_range.length
            content_length = length

        if content_length is not None and content_length >= 0:
            piece_size = store.metadata.piece_size or compute_piece_size(content_length)
            total_pieces = compute_piece_count(content_length, piece_size)
            store.update_task(content_length=content_length, piece_size=piece_size,
                              total_piece_count=total_pieces)
            support_range = False
            if content_length >= self.opt.concurrent_min_length and self.opt.concurrency > 1:
                if range_known is not None:
                    support_range = range_known  # answered by the same probe
                else:
                    try:
                        support_range = await client.is_support_range(request)
                    except SourceError:
                        support_range = False
            if support_range:
                fetch = lambda: self._download_known_length_concurrent(  # noqa: E731
                    store, client, request, content_range, on_piece, limiter)
            else:
                fetch = lambda: self._download_streaming(  # noqa: E731
                    store, client, request, content_range, on_piece, limiter,
                    known_length=content_length)
        else:
            if store.metadata.piece_size <= 0:
                store.update_task(piece_size=compute_piece_size(-1))
            fetch = lambda: self._download_streaming(  # noqa: E731
                store, client, request, content_range, on_piece, limiter,
                known_length=-1)

        # Origin retry rides the ONE policy module (capped exponential,
        # full jitter) and retries TEMPORARY failures only: a 5xx burst or
        # a dropped stream earns another attempt (landed pieces are
        # skipped on resume), a permanent 403/404/416 fails immediately.
        await retrylib.run(
            fetch, policy=retrylib.SOURCE,
            max_attempts=max(1, self.opt.origin_attempts),
            retryable=lambda e: isinstance(e, SourceError) and e.temporary)

        if not store.is_complete():
            raise SourceError(
                f"source download incomplete: {len(store.metadata.pieces)}/"
                f"{store.metadata.total_piece_count} pieces", Code.BackToSourceAborted)

    # -- native-engine span fetch (no Python byte handling) ----------------

    @staticmethod
    def _span_status_error(client, status: int, req: SourceRequest) -> SourceError:
        mapper = getattr(client, "status_error", None)
        if mapper is not None:
            return mapper(status, req.url)
        return SourceError(f"origin {status}: {req.url}", Code.BackToSourceAborted,
                           temporary=status in (408, 429, 500, 502, 503, 504))

    async def _native_fetch_span(
        self,
        store: LocalTaskStore,
        client,
        req: SourceRequest,
        first: int,
        last: int,
        byte_len: int,
        on_piece: PieceCallback | None,
        limiter: Limiter,
        *,
        ranged: bool,
    ) -> bool:
        """Fetch pieces [first, last) over one native-engine connection:
        the body streams socket→crc32c→pwrite (native/src/dfhttp.cc) and
        Python sees only per-piece records. Returns False when ineligible
        (https, no native lib, client without a plan) so the caller falls
        back to the aiohttp path; raises coded SourceErrors on failures,
        matching the Python path's semantics."""
        nb = _native()
        plan_fn = getattr(client, "native_fetch_plan", None)
        if nb is None or plan_fn is None:
            return False
        plan = plan_fn(req)
        if plan is None:
            return False
        host, port, head = plan
        m = store.metadata
        try:
            h = await native_connect(nb, host, port, 60000)
        except nb.NativeHttpError:
            return False  # let the aiohttp path produce its own coded error
        dup_fd = os.dup(store.data_fd())
        abandoned = False

        def cleanup() -> None:
            nb.http_close(h)
            os.close(dup_fd)

        async def ncall(fn, *args):
            nonlocal abandoned
            try:
                return await abandonable_native_call(fn, *args,
                                                     on_abandon=cleanup)
            except asyncio.CancelledError:
                abandoned = True  # the worker thread now owns cleanup()
                raise

        try:
            try:
                status, clen, _keep = await ncall(nb.http_start, h, head)
            except nb.NativeHttpError:
                # Start-phase failure (chunked origin, odd framing, stalled
                # connect): no body consumed, nothing recorded — let the
                # aiohttp path take over and produce its own coded errors.
                return False
            if 300 <= status < 400:
                # aiohttp follows redirects (CDN/presigned handoffs); the
                # native engine doesn't — hand the request back to it.
                return False
            if ranged and status == 200:
                raise SourceError("origin ignored range request",
                                  Code.SourceRangeUnsupported, temporary=True)
            if status != (206 if ranged else 200):
                raise self._span_status_error(client, status, req)
            if clen < 0:
                # Identity body without Content-Length (read-until-close):
                # only the streaming Python path can delimit it.
                return False
            if clen != byte_len:
                raise SourceError(
                    f"origin returned {clen} bytes, expected {byte_len}",
                    Code.BackToSourceAborted, temporary=True)
            for num in range(first, last):
                take = min(m.piece_size, m.content_length - num * m.piece_size)
                await limiter.wait(take)
                t0 = time.monotonic()
                if store.has_piece(num):
                    # Resume overlap: the bytes still arrive on this stream;
                    # drain without touching the already-verified piece.
                    await ncall(nb.http_read_to_file, h, -1, 0, take)
                    continue
                crc = await ncall(nb.http_read_to_file, h, dup_fd,
                                  num * m.piece_size, take)
                # Off-loop: record_piece's batched metadata save serializes
                # the whole piece map — a loop stall if run inline.
                cost_ms = int((time.monotonic() - t0) * 1000)
                rec = await asyncio.to_thread(
                    store.record_piece, num, take, crc, cost_ms)
                # Float ms for the recorder: sub-ms loopback pieces must
                # not collapse to a zero-length origin interval.
                flightlib.for_task(m.task_id).record(
                    flightlib.EV_SOURCE_LANDED, num,
                    (time.monotonic() - t0) * 1000.0)
                if on_piece is not None:
                    await on_piece(store, rec)
            return True
        except nb.NativeHttpError as e:
            raise SourceError(f"origin {host}:{port} native fetch: {e}",
                              Code.BackToSourceAborted, temporary=True)
        finally:
            if not abandoned:
                cleanup()

    # -- sequential / unknown-length (reference :481,:539) -----------------

    async def _download_streaming(
        self,
        store: LocalTaskStore,
        client,
        request: SourceRequest,
        content_range: Range | None,
        on_piece: PieceCallback | None,
        limiter: Limiter,
        known_length: int,
    ) -> None:
        req = request
        if content_range is not None:
            req = request.with_range(content_range.to_http())
        if (known_length >= 0 and store.metadata.total_piece_count >= 0
                and await self._native_fetch_span(
                    store, client, req, 0, store.metadata.total_piece_count,
                    known_length, on_piece, limiter,
                    ranged=content_range is not None)):
            return
        resp = await client.download(req)
        piece_size = store.metadata.piece_size
        num = 0
        total = 0
        # Zero-copy carve: piece boundaries are memoryview windows over the
        # wire chunks exactly as they arrived — no assembly bytearray, no
        # bytes() copy, no O(piece) del-memmove. The store lands each
        # window list with the per-piece digest FUSED into the write
        # (write_piece_chunks: seeded crc while pwriting — one memory walk
        # for hash+write; digest_reader.go single-pass parity).
        views: list[memoryview] = []
        filled = 0
        start = time.monotonic()
        # Depth-1 landing pipeline: piece N's write+digest runs in a worker
        # thread (GIL released in the native crc+pwrite and the sha feed)
        # WHILE the loop receives piece N+1's chunks — wall becomes
        # max(receive, hash+write) instead of their sum on a busy core.
        # Exactly one landing is in flight, awaited before the next
        # launches, so commits (and the prefix-hasher's in-memory frontier
        # feed) stay in piece order.
        pending: "asyncio.Future | None" = None
        body = retrylib.watch_idle(resp.body, self.opt.origin_idle_timeout,
                                   what=f"origin {request.url[:96]}")
        try:
            try:
                async for chunk in body:
                    total += len(chunk)
                    cv = memoryview(chunk)
                    while len(cv):
                        take = min(piece_size - filled, len(cv))
                        views.append(cv[:take])
                        cv = cv[take:]
                        filled += take
                        if filled == piece_size:
                            if pending is not None:
                                await pending
                            pending = asyncio.ensure_future(
                                self._land_piece_chunks(
                                    store, num, views, piece_size,
                                    on_piece, limiter, start))
                            num += 1
                            views, filled = [], 0
                            start = time.monotonic()
                if pending is not None:
                    await pending
                    pending = None
            except BaseException:
                if pending is not None:
                    pending.cancel()
                    await asyncio.gather(pending, return_exceptions=True)
                raise
        except retrylib.ProgressTimeout as e:
            # Stalled origin (slow-loris): temporary — the retry policy
            # may try again; landed pieces are skipped on resume.
            raise SourceError(str(e), Code.BackToSourceAborted,
                              temporary=True)
        finally:
            await resp.close()
        # Length check BEFORE the trailing partial piece lands: a dropped
        # connection must never persist a truncated piece in metadata.
        if known_length >= 0 and total != known_length:
            raise SourceError(f"origin returned {total} bytes, expected {known_length}",
                              Code.BackToSourceAborted, temporary=True)
        if views:
            await self._land_piece_chunks(
                store, num, views, filled, on_piece, limiter, start)
            num += 1
        if known_length < 0:
            # Learned the length at EOF (reference downloadUnknownLengthSource
            # finishes by updating task metadata).
            store.update_task(content_length=total, total_piece_count=num)

    # -- concurrent piece groups (reference :796-1000) ---------------------

    async def _download_known_length_concurrent(
        self,
        store: LocalTaskStore,
        client,
        request: SourceRequest,
        content_range: Range | None,
        on_piece: PieceCallback | None,
        limiter: Limiter,
    ) -> None:
        m = store.metadata
        total_pieces = m.total_piece_count
        # Resume: never re-fetch the contiguous landed prefix (reference
        # continuePieceNum, piece_manager.go:804-815 — groups start at the
        # first missing piece; mid-range holes still stream-and-drain
        # inside their group, matching the reference).
        continue_piece = 0
        while continue_piece < total_pieces and store.has_piece(continue_piece):
            continue_piece += 1
        to_download = total_pieces - continue_piece
        if to_download <= 0:
            return
        concurrency = min(self.opt.concurrency, to_download)
        # Contiguous piece groups (reference pieceGroup :876-922): group g
        # covers pieces [g*per + min(g, rem) ... ), sizes differ by ≤1.
        per, rem = divmod(to_download, concurrency)
        groups: list[tuple[int, int]] = []
        start_piece = continue_piece
        for g in range(concurrency):
            count = per + (1 if g < rem else 0)
            groups.append((start_piece, start_piece + count))
            start_piece += count

        base_offset = content_range.start if content_range is not None else 0

        async def fetch_group(first: int, last: int) -> None:
            byte_start = base_offset + first * m.piece_size
            byte_len = min(last * m.piece_size, m.content_length) - first * m.piece_size
            req = request.with_range(Range(byte_start, byte_len).to_http())
            if await self._native_fetch_span(store, client, req, first, last,
                                             byte_len, on_piece, limiter,
                                             ranged=True):
                return
            resp = await client.download(req)
            if resp.status != 206:
                await resp.close()
                raise SourceError("origin ignored range request",
                                  Code.SourceRangeUnsupported, temporary=True)
            num = first
            got = 0
            # Same zero-copy carve as the sequential path; the group's
            # LAST piece accumulates to EOF (its size is the range
            # remainder) and lands only after the length check below.
            views: list[memoryview] = []
            filled = 0
            t0 = time.monotonic()
            # Depth-1 landing pipeline per group (see _download_streaming).
            pending: "asyncio.Future | None" = None
            body = retrylib.watch_idle(
                resp.body, self.opt.origin_idle_timeout,
                what=f"origin group [{first},{last}) {request.url[:96]}")
            try:
                try:
                    async for chunk in body:
                        got += len(chunk)
                        cv = memoryview(chunk)
                        while len(cv):
                            if num >= last - 1:
                                views.append(cv)
                                filled += len(cv)
                                break
                            take = min(m.piece_size - filled, len(cv))
                            views.append(cv[:take])
                            cv = cv[take:]
                            filled += take
                            if filled == m.piece_size:
                                if pending is not None:
                                    await pending
                                pending = asyncio.ensure_future(
                                    self._land_piece_chunks(
                                        store, num, views, m.piece_size,
                                        on_piece, limiter, t0))
                                num += 1
                                views, filled = [], 0
                                t0 = time.monotonic()
                    if pending is not None:
                        await pending
                        pending = None
                except BaseException:
                    if pending is not None:
                        pending.cancel()
                        await asyncio.gather(pending, return_exceptions=True)
                    raise
            except retrylib.ProgressTimeout as e:
                raise SourceError(str(e), Code.BackToSourceAborted,
                                  temporary=True)
            finally:
                await resp.close()
            # Length check first — a short stream must not persist its
            # trailing buffer as a (truncated) piece.
            if got != byte_len:
                raise SourceError(f"group [{first},{last}) got {got} bytes, want {byte_len}",
                                  Code.BackToSourceAborted, temporary=True)
            if views:
                await self._land_piece_chunks(
                    store, num, views, filled, on_piece, limiter, t0)
                num += 1

        results = await asyncio.gather(
            *(fetch_group(f, l) for f, l in groups), return_exceptions=True
        )
        errors = [r for r in results if isinstance(r, BaseException)]
        if errors:
            raise errors[0]

    # -- shared piece writer -----------------------------------------------

    async def _land_piece_chunks(
        self,
        store: LocalTaskStore,
        num: int,
        views: list,
        size: int,
        on_piece: PieceCallback | None,
        limiter: Limiter,
        started_at: float,
    ) -> None:
        """Land a carved piece: one write_piece_chunks call (digest fused
        into the write) — off-loop, because it still blocks on disk."""
        await limiter.wait(size)
        cost_ms = int((time.monotonic() - started_at) * 1000)
        if store.has_piece(num):
            return   # resume overlap: bytes already verified on disk
        rec = await asyncio.to_thread(
            store.write_piece_chunks, num, views, cost_ms=cost_ms)
        # Float ms (receive + write): sub-ms loopback pieces must not
        # collapse to a zero-length origin interval in the analyzer.
        flightlib.for_task(store.metadata.task_id).record(
            flightlib.EV_SOURCE_LANDED, num,
            (time.monotonic() - started_at) * 1000.0)
        if on_piece is not None:
            await on_piece(store, rec)

    async def _write_piece(
        self,
        store: LocalTaskStore,
        num: int,
        data: bytes,
        on_piece: PieceCallback | None,
        limiter: Limiter,
        started_at: float,
    ) -> None:
        await limiter.wait(len(data))
        cost_ms = int((time.monotonic() - started_at) * 1000)
        if store.has_piece(num):
            return
        # Thread offload: the fused crc+pwrite releases the GIL; writing
        # inline would block the loop (and upload serving) per 4 MiB piece.
        if self.opt.compute_digest:
            rec = await asyncio.to_thread(store.write_piece, num, data,
                                          cost_ms=cost_ms)
        else:
            rec = await asyncio.to_thread(store.write_piece, num, data,
                                          expected_digest="", cost_ms=cost_ms)
        if on_piece is not None:
            await on_piece(store, rec)

    # -- file import for dfcache (reference :662 ImportFile) ---------------

    async def import_file(self, store: LocalTaskStore, path: str,
                          on_piece: PieceCallback | None = None) -> None:
        import os

        from dragonfly2_tpu.storage.local_store import (
            acquire_read_buffer,
            release_read_buffer,
        )

        size = os.path.getsize(path)
        piece_size = store.metadata.piece_size or compute_piece_size(size)
        total = compute_piece_count(size, piece_size)
        store.update_task(content_length=size, piece_size=piece_size, total_piece_count=total)
        # One pooled buffer for the whole import (pieces land sequentially,
        # the write digests+lands from the view before the next readinto).
        buf = acquire_read_buffer(piece_size)
        try:
            with open(path, "rb") as f:
                for num in range(total):
                    n = f.readinto(buf)
                    t0 = time.monotonic()
                    await self._write_piece(store, num, buf[:n], on_piece,
                                            self._limiter, t0)
        finally:
            release_read_buffer(buf)

    # -- whole-content digest ----------------------------------------------

    @staticmethod
    def validate_content(store: LocalTaskStore, expected_digest: str = "") -> str:
        return store.validate_digest(expected_digest)
