"""Peer-task machinery: conductors, piece pipeline, reuse
(reference: client/daemon/peer)."""
