"""Piece downloader: HTTP GETs against a parent's upload server.

Reference: client/daemon/peer/piece_downloader.go — DownloadPiece (:165),
buildDownloadPieceHTTPRequest (:204): GET
http://{parent}/download/{taskPrefix}/{taskID}?peerId=...&pieceNum=N.
"""

from __future__ import annotations

import asyncio
import time

import aiohttp

from dragonfly2_tpu.pkg import dflog
from dragonfly2_tpu.pkg.errors import Code, DfError

log = dflog.get("peer.piece_downloader")


class PieceDownloader:
    def __init__(self, timeout: float = 30.0):
        self._timeout = timeout
        self._session: aiohttp.ClientSession | None = None
        self._session_loop = None

    async def _sess(self) -> aiohttp.ClientSession:
        loop = asyncio.get_running_loop()
        if self._session is None or self._session.closed or self._session_loop is not loop:
            self._session = aiohttp.ClientSession(
                timeout=aiohttp.ClientTimeout(total=self._timeout),
                connector=aiohttp.TCPConnector(limit_per_host=16),
            )
            self._session_loop = loop
        return self._session

    async def download_piece(self, parent_ip: str, parent_upload_port: int,
                             task_id: str, piece_num: int, *, src_peer_id: str = "",
                             expected_size: int = -1) -> tuple[bytes, int]:
        """Fetch one piece; returns (data, cost_ms)."""
        url = (f"http://{parent_ip}:{parent_upload_port}"
               f"/download/{task_id[:3]}/{task_id}")
        start = time.monotonic()
        sess = await self._sess()
        try:
            async with sess.get(url, params={"peerId": src_peer_id,
                                             "pieceNum": str(piece_num)}) as resp:
                if resp.status == 404:
                    raise DfError(Code.ClientPieceNotFound,
                                  f"parent {parent_ip}:{parent_upload_port} lacks piece {piece_num}")
                if resp.status == 429:
                    raise DfError(Code.ClientRequestLimitFail,
                                  f"parent {parent_ip}:{parent_upload_port} throttled")
                # 206: the upload server serves pieces as sendfile'd byte
                # ranges (Partial Content) — equally complete payloads.
                if resp.status not in (200, 206):
                    raise DfError(Code.ClientPieceRequestFail,
                                  f"parent returned {resp.status} for piece {piece_num}")
                data = await resp.read()
        except aiohttp.ClientError as e:
            raise DfError(Code.ClientPieceRequestFail,
                          f"piece {piece_num} from {parent_ip}:{parent_upload_port}: {e}")
        if expected_size >= 0 and len(data) != expected_size:
            raise DfError(Code.ClientPieceDownloadFail,
                          f"piece {piece_num} size {len(data)} != expected {expected_size}")
        cost_ms = int((time.monotonic() - start) * 1000)
        return data, cost_ms

    async def close(self) -> None:
        if self._session is not None and not self._session.closed:
            await self._session.close()


def is_parent_gone(e: DfError) -> bool:
    """Errors that mean the parent itself is unusable (vs a transient piece
    failure) — shared classification for conductor and PEX pull paths."""
    return e.code in (Code.ClientConnectionError, Code.ClientPieceRequestFail)


async def pull_one_piece(downloader: PieceDownloader, store, dispatcher,
                         assignment, *, task_id: str, peer_id: str,
                         limiter) -> "object":
    """The shared piece-pull step: backfill store geometry from the
    dispatcher, rate-limit, fetch from the assigned parent, verify+write.
    Returns the PieceRecord; raises DfError on failure WITHOUT reporting to
    the dispatcher (callers own success/failure accounting since their
    retry/reschedule policies differ)."""
    if store.metadata.piece_size <= 0 and dispatcher.piece_size > 0:
        store.update_task(
            piece_size=dispatcher.piece_size,
            content_length=dispatcher.content_length
            if dispatcher.content_length >= 0 else None,
            total_piece_count=dispatcher.total_piece_count
            if dispatcher.total_piece_count >= 0 else None,
        )
    await limiter.wait(max(assignment.expected_size, 1)
                       if assignment.expected_size > 0 else 1)
    data, cost_ms = await downloader.download_piece(
        assignment.parent.ip, assignment.parent.upload_port,
        task_id, assignment.piece_num,
        src_peer_id=peer_id, expected_size=assignment.expected_size)
    # Thread offload: the fused crc+pwrite is a GIL-releasing native call;
    # inline it would block the event loop (and this daemon's own upload
    # serving) for the disk write of every 4 MiB piece.
    return await asyncio.to_thread(
        store.write_piece, assignment.piece_num, data,
        expected_digest=assignment.digest, cost_ms=cost_ms)
