"""Piece downloader: HTTP GETs against a parent's upload server.

Reference: client/daemon/peer/piece_downloader.go — DownloadPiece (:165),
buildDownloadPieceHTTPRequest (:204): GET
http://{parent}/download/{taskPrefix}/{taskID}?peerId=...&pieceNum=N.

Fast path: when the native engine (native/src/dfhttp.cc) is available and
the parent-advertised digest is crc32c, piece bodies flow socket→crc32c→
pwrite inside one GIL-free native call — no Python byte handling, no
event-loop copies. The aiohttp path remains for everything else and as the
fallback (mirrors how the reference keeps its data plane fully native).
"""

from __future__ import annotations

import asyncio
import concurrent.futures
import functools
import os
import time

import aiohttp

from dragonfly2_tpu.pkg import dflog
from dragonfly2_tpu.pkg import digest as pkgdigest
from dragonfly2_tpu.pkg import flight as flightlib
from dragonfly2_tpu.pkg import retry as retrylib
from dragonfly2_tpu.pkg import tracing
from dragonfly2_tpu.pkg.errors import Code, DfError
from dragonfly2_tpu import qos as qoslib
from dragonfly2_tpu.storage.local_store import _native

log = dflog.get("peer.piece_downloader")

_RECV_CHUNK = 256 << 10

# Chaos fabric hook (pkg/chaos.enable() arms it; None = inert). While any
# piece.* rule is loaded the native fast path is bypassed so injected
# faults flow through the hookable aiohttp path.
_chaos = None


def _err(code: Code, msg: str, reason: str) -> DfError:
    """Coded per-piece error carrying its typed failure reason — the
    quarantine/demotion vocabulary (pkg/quarantine.REASON_WEIGHTS)."""
    return DfError(code, msg, {"reason": reason})


def failure_reason(e: DfError) -> str:
    """Classify a piece failure into the typed reason-code vocabulary:
    explicit metadata first (raise sites on this path tag themselves),
    then the storage layer's digest-mismatch message, then the code."""
    r = e.metadata.get("reason", "")
    if r:
        return r
    if "digest mismatch" in e.message:
        return "corrupt"
    return {
        Code.ClientConnectionError: "refused",
        Code.ClientPieceRequestFail: "transport",
        Code.ClientPieceDownloadFail: "truncated",
        Code.ClientRequestLimitFail: "throttle",
        Code.ClientPieceNotFound: "not_found",
        Code.RequestTimeout: "stall",
    }.get(e.code, "transport")


async def assemble_piece(chunks, expected_size: int,
                         expected_digest: str = "",
                         ) -> "tuple[list, int, str]":
    """Drain an async chunk iterator into the list of chunks exactly as
    the wire delivered them — no assembly buffer, no concatenation copy;
    the store lands them with one pwritev (write_piece_chunks). Returns
    ``(chunks, size, digest_str)``.

    ``digest_str`` is the piece digest computed WHILE the bytes arrived
    (reference Dragonfly2 streams through a digest reader —
    pkg/digest/digest_reader.go — instead of re-hashing a landed copy),
    for algorithms the store cannot fuse into the write (md5/sha*, or no
    native lib). For native crc32c — the fleet default — it is "" and the
    store checksums each chunk WHILE pwriting it (seeded fused walk), so
    hash+write cost one memory pass total. Either way verification
    happens at the store's single commit point and never re-reads landed
    bytes; size mismatches raise here."""
    algorithm = ""
    if expected_digest:
        try:
            algorithm = pkgdigest.parse(expected_digest).algorithm
        except pkgdigest.InvalidDigestError:
            raise DfError(Code.ClientPieceDownloadFail,
                          f"malformed digest {expected_digest!r}")
    hasher = None
    if algorithm and not (algorithm == pkgdigest.ALGORITHM_CRC32C
                          and _native() is not None):
        hasher = pkgdigest.new_hasher(algorithm)
    out: list = []
    got = 0
    async for chunk in chunks:
        if expected_size >= 0 and got + len(chunk) > expected_size:
            raise _err(Code.ClientPieceDownloadFail,
                       f"body exceeds expected size {expected_size}",
                       "truncated")
        out.append(chunk)
        got += len(chunk)
        if hasher is not None:
            hasher.update(chunk)
    if expected_size >= 0 and got != expected_size:
        raise _err(Code.ClientPieceDownloadFail,
                   f"body size {got} != expected {expected_size}",
                   "truncated")
    digest_str = f"{algorithm}:{hasher.hexdigest()}" if hasher else ""
    return out, got, digest_str


async def _first_byte_tap(chunks, ft, piece_num: int):
    """Flight-recorder tap: mark the first body chunk's arrival so the
    critical-path analyzer can split time-to-first-byte (a silent but
    connected parent = stall) from transfer time."""
    first = True
    async for chunk in chunks:
        if first:
            first = False
            ft.record(flightlib.EV_FIRST_BYTE, piece_num)
        yield chunk

_NATIVE_EXECUTOR: concurrent.futures.ThreadPoolExecutor | None = None


def _native_executor() -> concurrent.futures.ThreadPoolExecutor:
    """Dedicated pool for blocking native-engine calls. MUST NOT be the
    loop's default executor: a native fetch blocks its thread on recv until
    the peer's upload server responds, and that server (aiohttp
    FileResponse) needs a default-executor slot to open/stat the file —
    sharing one small pool deadlocks them (piece fetches hold every slot,
    the server can't serve, fetches time out). Threads here spend their
    life in GIL-free recv/pwrite, so a generous cap costs ~nothing."""
    global _NATIVE_EXECUTOR
    if _NATIVE_EXECUTOR is None:
        _NATIVE_EXECUTOR = concurrent.futures.ThreadPoolExecutor(
            max_workers=max(32, (os.cpu_count() or 1) * 4),
            thread_name_prefix="df-native-io")
    return _NATIVE_EXECUTOR


def run_native(fn, *args) -> asyncio.Future:
    """Schedule a blocking native call on the dedicated executor."""
    loop = asyncio.get_running_loop()
    return loop.run_in_executor(_native_executor(),
                                functools.partial(fn, *args))


async def abandonable_native_call(fn, *args, on_abandon=None):
    """Run a blocking native call in a worker thread; if this coroutine is
    cancelled mid-call, the thread cannot be interrupted (SO_RCVTIMEO bounds
    it), so `on_abandon` is deferred to its completion — the caller hands
    over cleanup of any resources (connection handle, dup'd fd) the thread
    is still using."""
    fut = asyncio.ensure_future(run_native(fn, *args))
    try:
        return await asyncio.shield(fut)
    except asyncio.CancelledError:
        if on_abandon is not None:
            def _done(f: asyncio.Future) -> None:
                if not f.cancelled():
                    f.exception()  # consume: abandoned errors are expected
                on_abandon()

            fut.add_done_callback(_done)
        raise


async def native_connect(nb, host: str, port: int, timeout_ms: int) -> int:
    """Cancel-safe fresh connect: if the caller is cancelled while the
    executor thread is still connecting, the handle the thread creates
    would otherwise be orphaned in the native table — a done callback
    closes it."""
    fut = asyncio.ensure_future(
        run_native(nb.http_connect, host, port, timeout_ms))
    try:
        return await asyncio.shield(fut)
    except asyncio.CancelledError:
        def _done(f: asyncio.Future) -> None:
            if not f.cancelled() and f.exception() is None:
                nb.http_close(f.result())
            elif not f.cancelled():
                f.exception()  # consume
        fut.add_done_callback(_done)
        raise


class NativeConnPool:
    """Keep-alive pool over native HTTP connections, keyed by (host, port).
    Event-loop-confined: list ops are synchronous; only the blocking connect
    runs in a worker thread (after the free list came up empty). Parked
    handles expire after IDLE_TTL_S so connections to parents that left the
    swarm don't leak fds until shutdown — expiry is swept on every release."""

    MAX_FREE_PER_HOST = 8
    IDLE_TTL_S = 60.0

    def __init__(self, timeout_ms: int = 30000):
        self._timeout_ms = timeout_ms
        self._free: dict[tuple[str, int], list[tuple[int, float]]] = {}

    async def acquire(self, nb, host: str, port: int) -> tuple[int, bool]:
        """Returns (handle, from_pool). from_pool=True means the connection
        is a reused keep-alive — callers should retry a transport failure
        once on a fresh connection before blaming the parent (the server
        may have idle-closed it between the liveness probe and the send)."""
        free = self._free.get((host, port))
        while free:
            h, _parked = free.pop()
            if nb.http_reusable(h):
                return h, True
            nb.http_close(h)
        return await native_connect(nb, host, port, self._timeout_ms), False

    def release(self, nb, host: str, port: int, h: int, reusable: bool) -> None:
        self._sweep_idle(nb)
        if reusable and nb.http_reusable(h):
            free = self._free.setdefault((host, port), [])
            if len(free) < self.MAX_FREE_PER_HOST:
                free.append((h, time.monotonic()))
                return
        nb.http_close(h)

    def _sweep_idle(self, nb) -> None:
        cutoff = time.monotonic() - self.IDLE_TTL_S
        for key in list(self._free):
            kept = []
            for h, parked in self._free[key]:
                if parked < cutoff:
                    nb.http_close(h)
                else:
                    kept.append((h, parked))
            if kept:
                self._free[key] = kept
            else:
                del self._free[key]

    def close_all(self, nb) -> None:
        for free in self._free.values():
            for h, _parked in free:
                nb.http_close(h)
        self._free.clear()


def _unsafe_request_ids(task_id: str, src_peer_id: str) -> bool:
    """True when either id cannot be spliced verbatim into a raw request
    head: a CR/LF or control char would smuggle extra headers, non-latin-1
    won't encode, and URL metacharacters would change the path/query parse.
    Externally-supplied ids (seed trigger specs) make this reachable — the
    SINGLE guard for every native-path request builder (the aiohttp
    fallback quotes them safely instead)."""
    return any(ord(c) < 0x20 or c == "\x7f" or ord(c) > 0xff or c in " ?&#"
               for c in f"{task_id}{src_peer_id}")


def _traceparent_line() -> str:
    """Raw-head traceparent header for the native request builders (hex
    ASCII only — safe to splice). Empty when not tracing."""
    ctx = tracing.current()
    return f"{tracing.TRACEPARENT}: {ctx.to_traceparent()}\r\n" if ctx else ""


def _upload_status_error(status: int, parent: str, what: str) -> DfError | None:
    """Map a parent upload-server status to the coded per-piece error the
    aiohttp path produces, or None for payload statuses (200/206). Shared
    by the single-piece and span native paths so a new status case cannot
    diverge between them."""
    if status in (404, 416):
        return _err(Code.ClientPieceNotFound,
                    f"parent {parent} lacks {what} ({status})", "not_found")
    if status == 429:
        return _err(Code.ClientRequestLimitFail,
                    f"parent {parent} throttled", "throttle")
    if status not in (200, 206):
        return _err(Code.ClientPieceRequestFail,
                    f"parent {parent} returned {status} for {what}",
                    "http5xx" if status >= 500 else "transport")
    return None


class PieceDownloader:
    def __init__(self, timeout: float = 30.0, idle_timeout: float = 10.0):
        self._timeout = timeout
        # Per-chunk progress watchdog (pkg/retry.watch_idle): the overall
        # timeout bounds the transfer, this bounds the gap between chunks
        # so a slow-loris parent trips in seconds, not at the deadline.
        self._idle_timeout = idle_timeout
        self._session: aiohttp.ClientSession | None = None
        self._session_loop = None
        self._pool = NativeConnPool(int(timeout * 1000))

    async def _sess(self) -> aiohttp.ClientSession:
        loop = asyncio.get_running_loop()
        if self._session is None or self._session.closed or self._session_loop is not loop:
            self._session = aiohttp.ClientSession(
                timeout=aiohttp.ClientTimeout(total=self._timeout),
                connector=aiohttp.TCPConnector(limit_per_host=16),
            )
            self._session_loop = loop
        return self._session

    async def download_piece(self, parent_ip: str, parent_upload_port: int,
                             task_id: str, piece_num: int, *, src_peer_id: str = "",
                             expected_size: int = -1,
                             expected_digest: str = "",
                             tenant: str = "") -> tuple[list, int, int, str]:
        """Fetch one piece; returns (chunks, size, cost_ms, digest_str) —
        the body as wire chunks plus the streaming digest (see
        assemble_piece). Land with store.write_piece_chunks, which
        verifies at the commit point with no second pass and no re-read."""
        url = (f"http://{parent_ip}:{parent_upload_port}"
               f"/download/{task_id[:3]}/{task_id}")
        parent = f"{parent_ip}:{parent_upload_port}"
        ft = flightlib.for_task(task_id)
        ft.record(flightlib.EV_REQUEST, piece_num, 0.0, parent)
        chaos_key = f"{parent}|{task_id}|{piece_num}"
        if _chaos is not None:
            fault = _chaos.on_request("piece.request", chaos_key)
            if fault is not None:
                if fault.kind == "stall":
                    await asyncio.sleep(fault.stall_s)
                elif fault.kind == "http5xx":
                    raise _err(Code.ClientPieceRequestFail,
                               f"parent {parent} returned {fault.status} "
                               f"for piece {piece_num} (chaos)", "http5xx")
                else:
                    raise _err(Code.ClientPieceRequestFail,
                               f"piece {piece_num} from {parent}: "
                               f"chaos {fault.kind}", "refused")
        start = time.monotonic()
        sess = await self._sess()
        params = {"peerId": src_peer_id, "pieceNum": str(piece_num)}
        if tenant:
            # QoS attribution: the serving daemon accounts and
            # rate-splits by this tag (upload.py → qos.TenantBuckets).
            params["tenant"] = qoslib.normalize_tenant(tenant)
        try:
            # The piece HTTP hop carries the caller's trace context so the
            # serving daemon's span joins the SAME trace (upload.py
            # extracts) — without it every pod download is N disconnected
            # traces, one per daemon.
            async with sess.get(url, params=params,
                                headers=tracing.inject()) as resp:
                status_err = _upload_status_error(
                    resp.status, parent, f"piece {piece_num}")
                if status_err is not None:
                    raise status_err
                body = resp.content.iter_chunked(_RECV_CHUNK)
                if _chaos is not None:
                    body = _chaos.wrap_body("piece.body", chaos_key, body)
                chunks, size, digest_str = await assemble_piece(
                    _first_byte_tap(
                        retrylib.watch_idle(
                            body, self._idle_timeout,
                            what=f"piece {piece_num} from {parent}"),
                        ft, piece_num),
                    expected_size, expected_digest)
        except retrylib.ProgressTimeout as e:
            # The stall watchdog tripped: the parent is connected but not
            # producing. Treat like a dead parent (reschedule elsewhere).
            raise _err(Code.ClientPieceRequestFail,
                       f"piece {piece_num} from {parent}: {e}", "stall")
        except asyncio.TimeoutError:
            # aiohttp total-timeout surfaces as a bare TimeoutError, NOT a
            # ClientError — uncaught it would escape the coded-DfError
            # contract and fail the whole task instead of one piece.
            raise _err(Code.ClientPieceRequestFail,
                       f"piece {piece_num} from {parent}: "
                       f"timed out after {self._timeout}s", "stall")
        except (aiohttp.ClientError, ConnectionResetError) as e:
            raise _err(Code.ClientPieceRequestFail,
                       f"piece {piece_num} from {parent}: {e}", "transport")
        cost_ms = int((time.monotonic() - start) * 1000)
        return chunks, size, cost_ms, digest_str

    async def download_piece_to_store(self, parent_ip: str,
                                      parent_upload_port: int, task_id: str,
                                      piece_num: int, store, *,
                                      src_peer_id: str = "",
                                      expected_size: int,
                                      expected_digest: str = "",
                                      tenant: str = "") -> "object | None":
        """Native fast path: land the piece straight into the store's data
        file (socket→crc32c→pwrite, GIL-free) and commit its record.
        Returns the PieceRecord, or None when this piece is ineligible (no
        native engine, unknown size, non-crc32c digest) and the caller must
        use the aiohttp + write_piece path. Registration only happens after
        the crc check, so a bad body leaves no visible trace."""
        nb = _native()
        piece_size = store.metadata.piece_size
        if _chaos is not None and _chaos.targets("piece"):
            return None   # chaos aims at pieces: use the hookable path
        if (nb is None or expected_size < 0 or piece_size <= 0
                or expected_size > piece_size or store.has_piece(piece_num)):
            return None
        want_crc = -1
        if expected_digest:
            try:
                d = pkgdigest.parse(expected_digest)
            except pkgdigest.InvalidDigestError:
                # Malformed parent-advertised digest can never match any
                # body: the same per-piece failure the in-memory path's
                # hex-string comparison produces, without fetching first.
                # (parse itself validates the hex — int() below cannot
                # fail on a parsed digest.)
                raise DfError(Code.ClientPieceDownloadFail,
                              f"piece {piece_num}: malformed digest {expected_digest!r}")
            if d.algorithm != pkgdigest.ALGORITHM_CRC32C:
                return None
            want_crc = int(d.encoded, 16)

        if _unsafe_request_ids(task_id, src_peer_id):
            return None  # the aiohttp path quotes them safely
        # normalize_tenant clamps to a splice-safe identifier charset —
        # the tenant tag never widens the raw-head injection surface.
        tenant_q = (f"&tenant={qoslib.normalize_tenant(tenant)}"
                    if tenant else "")
        head = (
            f"GET /download/{task_id[:3]}/{task_id}"
            f"?peerId={src_peer_id}&pieceNum={piece_num}{tenant_q} HTTP/1.1\r\n"
            f"Host: {parent_ip}:{parent_upload_port}\r\n"
            f"{_traceparent_line()}"
            "Accept-Encoding: identity\r\nConnection: keep-alive\r\n\r\n"
        ).encode("latin-1")
        flightlib.for_task(task_id).record(
            flightlib.EV_REQUEST, piece_num, 0.0,
            f"{parent_ip}:{parent_upload_port}")
        start = time.monotonic()
        while True:
            try:
                h, from_pool = await self._pool.acquire(
                    nb, parent_ip, parent_upload_port)
            except nb.NativeHttpError as e:
                raise _err(Code.ClientPieceRequestFail,
                           f"piece {piece_num} from {parent_ip}:{parent_upload_port}: {e}",
                           "refused")
            dup_fd = os.dup(store.data_fd())

            def abandon(h=h, dup_fd=dup_fd) -> None:
                nb.http_close(h)
                os.close(dup_fd)

            try:
                status, n, crc, keep = await abandonable_native_call(
                    nb.http_fetch_to_file, h, head, dup_fd,
                    piece_num * piece_size, expected_size, on_abandon=abandon)
            except asyncio.CancelledError:
                raise  # abandon() deferred to the worker thread's completion
            except nb.NativeHttpError as e:
                abandon()
                if from_pool:
                    # Stale keep-alive (server idle-closed between the
                    # liveness probe and the send): the GET is idempotent
                    # and nothing was recorded — retry on a fresh/next
                    # connection instead of blaming a healthy parent. The
                    # pool drains closed handles, so this terminates.
                    continue
                if e.code == nb.HTTP_E_LENMISMATCH:
                    # Wrong-size body is a per-piece data failure (matches
                    # the aiohttp path), not grounds to evict the parent.
                    raise _err(Code.ClientPieceDownloadFail,
                               f"piece {piece_num} from {parent_ip}:{parent_upload_port}: {e}",
                               "truncated")
                raise _err(Code.ClientPieceRequestFail,
                           f"piece {piece_num} from {parent_ip}:{parent_upload_port}: {e}",
                           "transport")
            os.close(dup_fd)
            self._pool.release(nb, parent_ip, parent_upload_port, h, keep)
            break
        status_err = _upload_status_error(
            status, f"{parent_ip}:{parent_upload_port}", f"piece {piece_num}")
        if status_err is not None:
            raise status_err
        if want_crc >= 0 and crc != want_crc:
            raise _err(Code.ClientPieceDownloadFail,
                       f"piece {piece_num} digest mismatch: want {want_crc:08x}, got {crc:08x}",
                       "corrupt")
        cost_ms = int((time.monotonic() - start) * 1000)
        # Off-loop: the batched metadata save inside record_piece json-dumps
        # the whole accumulated piece map — a repeated loop stall on
        # many-piece tasks if run inline.
        return await asyncio.to_thread(store.record_piece, piece_num, n, crc,
                                       cost_ms, want_crc >= 0)

    async def download_span_to_store(self, parent_ip: str,
                                     parent_upload_port: int, task_id: str,
                                     run: list, store, *,
                                     src_peer_id: str = "",
                                     limiter=None,
                                     on_result=None,
                                     tenant: str = "") -> "bool":
        """Coalesced native fast path: fetch a CONTIGUOUS run of pieces
        from one parent as a single ranged GET, the body streaming
        socket→crc32c→pwrite per piece on one connection — one request
        round-trip and one executor hop per PIECE READ instead of one
        whole exchange per piece (the per-core fabric multiplier VERDICT
        r04 names; reference hot loop being beaten:
        client/daemon/peer/peertask_conductor.go:1043).

        Returns False when ineligible (no native engine, short run, unknown
        geometry, non-crc32c digest, unsafe ids) — the caller falls back to
        per-piece pulls. Otherwise awaits ``on_result(a, rec, err)`` AS
        EACH PIECE LANDS (rec on success, coded DfError on failure) and
        returns True. Streaming the callbacks — not batching them at span
        end — is what keeps ttfp and downstream piece discovery (broker →
        SyncPieceTasks children) piece-granular while the wire rides one
        request. A transport failure mid-span fails only the unread
        pieces; landed pieces stay recorded."""
        nb = _native()
        piece_size = store.metadata.piece_size
        if _chaos is not None and _chaos.targets("piece"):
            return False   # chaos aims at pieces: per-piece hookable path
        if nb is None or len(run) < 2 or piece_size <= 0:
            return False
        want_crcs: list[int] = []
        for a in run:
            if (a.expected_size < 0 or a.expected_size > piece_size
                    or store.has_piece(a.piece_num)):
                return False
            if a.digest:
                try:
                    d = pkgdigest.parse(a.digest)
                except pkgdigest.InvalidDigestError:
                    return False  # malformed: per-piece path raises its coded error
                if d.algorithm != pkgdigest.ALGORITHM_CRC32C:
                    return False
                want_crcs.append(int(d.encoded, 16))
            else:
                want_crcs.append(-1)
        for prev, nxt in zip(run, run[1:]):
            if nxt.piece_num != prev.piece_num + 1:
                return False
        if _unsafe_request_ids(task_id, src_peer_id):
            return False  # the aiohttp path quotes them safely

        start = run[0].piece_num * piece_size
        total = sum(a.expected_size for a in run)
        tenant_q = (f"&tenant={qoslib.normalize_tenant(tenant)}"
                    if tenant else "")
        head = (
            f"GET /download/{task_id[:3]}/{task_id}"
            f"?peerId={src_peer_id}{tenant_q} HTTP/1.1\r\n"
            f"Host: {parent_ip}:{parent_upload_port}\r\n"
            f"Range: bytes={start}-{start + total - 1}\r\n"
            f"{_traceparent_line()}"
            "Accept-Encoding: identity\r\nConnection: keep-alive\r\n\r\n"
        ).encode("latin-1")
        ft = flightlib.for_task(task_id)

        async def fail_all(err: DfError) -> bool:
            for a in run:
                await on_result(a, None, err)
            return True

        while True:
            try:
                h, from_pool = await self._pool.acquire(
                    nb, parent_ip, parent_upload_port)
            except nb.NativeHttpError as e:
                return await fail_all(_err(
                    Code.ClientPieceRequestFail,
                    f"span {run[0].piece_num}-{run[-1].piece_num} from "
                    f"{parent_ip}:{parent_upload_port}: {e}", "refused"))
            dup_fd = os.dup(store.data_fd())
            abandoned = False

            def cleanup(h=h, dup_fd=dup_fd) -> None:
                nb.http_close(h)
                os.close(dup_fd)

            async def ncall(fn, *args):
                nonlocal abandoned
                try:
                    return await abandonable_native_call(
                        fn, *args, on_abandon=cleanup)
                except asyncio.CancelledError:
                    abandoned = True  # worker thread now owns cleanup()
                    raise

            try:
                try:
                    status, clen, _keep = await ncall(nb.http_start, h, head)
                except nb.NativeHttpError as e:
                    cleanup()
                    if from_pool:
                        continue  # stale keep-alive: retry on a fresh conn
                    return await fail_all(_err(
                        Code.ClientPieceRequestFail,
                        f"span {run[0].piece_num}-{run[-1].piece_num} from "
                        f"{parent_ip}:{parent_upload_port}: {e}", "transport"))
                break
            except asyncio.CancelledError:
                raise  # cleanup deferred to the worker thread
            except BaseException:
                cleanup()
                raise

        try:
            status_err = _upload_status_error(
                status, f"{parent_ip}:{parent_upload_port}",
                f"span {run[0].piece_num}-{run[-1].piece_num}")
            if status_err is not None:
                return await fail_all(status_err)
            if clen != total:
                # Geometry disagreement: data failure, stream state unknown.
                abandoned = True
                cleanup()
                return await fail_all(_err(
                    Code.ClientPieceDownloadFail,
                    f"span Content-Length {clen} != expected {total}",
                    "truncated"))

            transport_err: DfError | None = None
            for i, a in enumerate(run):
                if transport_err is not None:
                    await on_result(a, None, transport_err)
                    continue
                if limiter is not None:
                    await limiter.wait(a.expected_size)
                ft.record(flightlib.EV_REQUEST, a.piece_num, 0.0,
                          f"{parent_ip}:{parent_upload_port}")
                t0 = time.monotonic()
                try:
                    crc = await ncall(nb.http_read_to_file,
                                      h, dup_fd, a.piece_num * piece_size,
                                      a.expected_size)
                except nb.NativeHttpError as e:
                    transport_err = _err(
                        Code.ClientPieceRequestFail,
                        f"piece {a.piece_num} mid-span from "
                        f"{parent_ip}:{parent_upload_port}: {e}",
                        "transport")
                    await on_result(a, None, transport_err)
                    continue
                if want_crcs[i] >= 0 and crc != want_crcs[i]:
                    # Wrong bytes are on disk but unrecorded: invisible to
                    # serving/reuse until a good write lands over them.
                    await on_result(a, None, _err(
                        Code.ClientPieceDownloadFail,
                        f"piece {a.piece_num} digest mismatch: "
                        f"want {want_crcs[i]:08x}, got {crc:08x}",
                        "corrupt"))
                    continue
                cost_ms = int((time.monotonic() - t0) * 1000)
                rec = await asyncio.to_thread(
                    store.record_piece, a.piece_num, a.expected_size, crc,
                    cost_ms, want_crcs[i] >= 0)
                await on_result(a, rec, None)
            return True
        finally:
            if not abandoned:
                os.close(dup_fd)
                # Reusable only when the whole body was consumed.
                self._pool.release(nb, parent_ip, parent_upload_port, h,
                                   nb.http_reusable(h))

    async def close(self) -> None:
        if self._session is not None and not self._session.closed:
            await self._session.close()
        nb = _native()
        if nb is not None:
            self._pool.close_all(nb)


def is_parent_gone(e: DfError) -> bool:
    """Errors that mean the parent itself is unusable (vs a transient piece
    failure) — shared classification for conductor and PEX pull paths."""
    return e.code in (Code.ClientConnectionError, Code.ClientPieceRequestFail)


async def pull_one_piece(downloader: PieceDownloader, store, dispatcher,
                         assignment, *, task_id: str, peer_id: str,
                         limiter, tenant: str = "") -> "object":
    """The shared piece-pull step: backfill store geometry from the
    dispatcher, rate-limit, fetch from the assigned parent, verify+write.
    Returns the PieceRecord; raises DfError on failure WITHOUT reporting to
    the dispatcher (callers own success/failure accounting since their
    retry/reschedule policies differ)."""
    if store.metadata.piece_size <= 0 and dispatcher.piece_size > 0:
        store.update_task(
            piece_size=dispatcher.piece_size,
            content_length=dispatcher.content_length
            if dispatcher.content_length >= 0 else None,
            total_piece_count=dispatcher.total_piece_count
            if dispatcher.total_piece_count >= 0 else None,
        )
    await limiter.wait(max(assignment.expected_size, 1)
                       if assignment.expected_size > 0 else 1)
    # Native fast path: body lands socket→crc32c→pwrite without entering
    # Python; returns None when ineligible (falls through to aiohttp).
    rec = await downloader.download_piece_to_store(
        assignment.parent.ip, assignment.parent.upload_port,
        task_id, assignment.piece_num, store,
        src_peer_id=peer_id, expected_size=assignment.expected_size,
        expected_digest=assignment.digest, tenant=tenant)
    if rec is not None:
        return rec
    chunks, _size, cost_ms, received_digest = await downloader.download_piece(
        assignment.parent.ip, assignment.parent.upload_port,
        task_id, assignment.piece_num,
        src_peer_id=peer_id, expected_size=assignment.expected_size,
        expected_digest=assignment.digest, tenant=tenant)
    # Thread offload: the write blocks on disk; inline it would stall the
    # event loop (and this daemon's own upload serving) per 4 MiB piece.
    # The chunks land via one pwritev (crc fused into the write, or
    # verified against the digest streamed during receive) — single pass,
    # no assembly copy, no store re-read.
    ft = flightlib.for_task(task_id)
    ft.record(flightlib.EV_STORE_START, assignment.piece_num)
    rec = await asyncio.to_thread(
        store.write_piece_chunks, assignment.piece_num, chunks,
        received_digest, expected_digest=assignment.digest, cost_ms=cost_ms)
    ft.record(flightlib.EV_STORED, assignment.piece_num)
    return rec
