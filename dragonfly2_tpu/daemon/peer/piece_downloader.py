"""Piece downloader: HTTP GETs against a parent's upload server.

Reference: client/daemon/peer/piece_downloader.go — DownloadPiece (:165),
buildDownloadPieceHTTPRequest (:204): GET
http://{parent}/download/{taskPrefix}/{taskID}?peerId=...&pieceNum=N.
"""

from __future__ import annotations

import asyncio
import time

import aiohttp

from dragonfly2_tpu.pkg import dflog
from dragonfly2_tpu.pkg.errors import Code, DfError

log = dflog.get("peer.piece_downloader")


class PieceDownloader:
    def __init__(self, timeout: float = 30.0):
        self._timeout = timeout
        self._session: aiohttp.ClientSession | None = None
        self._session_loop = None

    async def _sess(self) -> aiohttp.ClientSession:
        loop = asyncio.get_running_loop()
        if self._session is None or self._session.closed or self._session_loop is not loop:
            self._session = aiohttp.ClientSession(
                timeout=aiohttp.ClientTimeout(total=self._timeout),
                connector=aiohttp.TCPConnector(limit_per_host=16),
            )
            self._session_loop = loop
        return self._session

    async def download_piece(self, parent_ip: str, parent_upload_port: int,
                             task_id: str, piece_num: int, *, src_peer_id: str = "",
                             expected_size: int = -1) -> tuple[bytes, int]:
        """Fetch one piece; returns (data, cost_ms)."""
        url = (f"http://{parent_ip}:{parent_upload_port}"
               f"/download/{task_id[:3]}/{task_id}")
        start = time.monotonic()
        sess = await self._sess()
        try:
            async with sess.get(url, params={"peerId": src_peer_id,
                                             "pieceNum": str(piece_num)}) as resp:
                if resp.status == 404:
                    raise DfError(Code.ClientPieceNotFound,
                                  f"parent {parent_ip}:{parent_upload_port} lacks piece {piece_num}")
                if resp.status == 429:
                    raise DfError(Code.ClientRequestLimitFail,
                                  f"parent {parent_ip}:{parent_upload_port} throttled")
                if resp.status != 200:
                    raise DfError(Code.ClientPieceRequestFail,
                                  f"parent returned {resp.status} for piece {piece_num}")
                data = await resp.read()
        except aiohttp.ClientError as e:
            raise DfError(Code.ClientPieceRequestFail,
                          f"piece {piece_num} from {parent_ip}:{parent_upload_port}: {e}")
        if expected_size >= 0 and len(data) != expected_size:
            raise DfError(Code.ClientPieceDownloadFail,
                          f"piece {piece_num} size {len(data)} != expected {expected_size}")
        cost_ms = int((time.monotonic() - start) * 1000)
        return data, cost_ms

    async def close(self) -> None:
        if self._session is not None and not self._session.closed:
            await self._session.close()
