"""Piece broker: per-task pub/sub for piece arrivals.

Reference: client/daemon/rpcserver/subscriber.go — piece-arrival push into
SyncPieceTasks server streams and stream-task waiters. Subscribers get the
current snapshot first, then incremental piece numbers.
"""

from __future__ import annotations

import asyncio
from dataclasses import dataclass, field


@dataclass
class PieceEvent:
    piece_nums: list[int]
    total_piece_count: int = -1
    content_length: int = -1
    piece_size: int = 0
    done: bool = False
    failed: bool = False
    # piece_num → "algo:encoded" — children verify against the parent's
    # advertised digest (reference commonv1 PieceInfo.piece_md5).
    digests: dict[int, str] = field(default_factory=dict)


@dataclass
class _TaskChannel:
    queues: set[asyncio.Queue] = field(default_factory=set)
    done: bool = False
    failed: bool = False


class PieceBroker:
    def __init__(self):
        self._tasks: dict[str, _TaskChannel] = {}

    def _chan(self, task_id: str) -> _TaskChannel:
        ch = self._tasks.get(task_id)
        if ch is None:
            ch = _TaskChannel()
            self._tasks[task_id] = ch
        return ch

    def publish(self, task_id: str, event: PieceEvent) -> None:
        # No subscribers → nothing to deliver; creating a channel here would
        # leak one per task ever downloaded.
        ch = self._tasks.get(task_id)
        if ch is None:
            return
        if event.done:
            ch.done = True
        if event.failed:
            ch.failed = True
        for q in list(ch.queues):
            q.put_nowait(event)

    def subscribe(self, task_id: str) -> asyncio.Queue:
        q: asyncio.Queue = asyncio.Queue()
        self._chan(task_id).queues.add(q)
        return q

    def unsubscribe(self, task_id: str, q: asyncio.Queue) -> None:
        ch = self._tasks.get(task_id)
        if ch is not None:
            ch.queues.discard(q)
            if not ch.queues:
                self._tasks.pop(task_id, None)

    def is_done(self, task_id: str) -> bool:
        ch = self._tasks.get(task_id)
        return ch is not None and ch.done
