"""Device sink manager: the daemon-side terminal store for ``--device=tpu``.

The reference daemon's terminal store is always the filesystem
(client/daemon/storage/storage_manager.go:54-131 — TaskStorageDriver with
one local-disk implementation). The TPU build adds a second, selectable
terminal: TPU HBM. When a download request carries ``device="tpu"``, every
verified piece is landed into a preallocated device buffer as it arrives
(ops/hbm_sink.HBMSink), completion re-verifies the landed bytes ON DEVICE
against host-side checksums, and the result is consumable as a JAX array
(``as_tensor``) or a mesh-sharded array (``shard_to_mesh``) without ever
re-reading host storage.

Threading: all sink mutations run on ONE dedicated worker thread — the
piece read-back, host→device staging and the jit dispatches would
otherwise stall the daemon's event loop (upload serving, RPC) for the
duration of each copy. The async surface awaits that thread, so the
download path still backpressures on landing.

Lifecycle: sinks are created lazily at the first landed piece (task
metadata — length and piece size — is unknown at request time), verified
at completion, and held up to a TTL for the consuming process to claim
(``take``) — under cap pressure a verified resident past its claim
grace may be evicted early for a new landing (the disk store stays
authoritative). Failed or aborted tasks discard their sink immediately;
unclaimed sinks expire so HBM is not leaked. The disk store remains
authoritative for upload/reuse — the sink is an *additional* terminal,
which is what lets other peers still fetch pieces from this host.
"""

from __future__ import annotations

import asyncio
import time
from concurrent.futures import ThreadPoolExecutor

from dragonfly2_tpu.pkg import dflog, metrics

log = dflog.get("peer.device_sink")

SINK_LANDED_BYTES = metrics.counter(
    "device_sink_landed_bytes_total", "Bytes landed into device sinks")
SINK_VERIFY_COUNT = metrics.counter(
    "device_sink_verify_total", "Device sink verifications", ("result",))


class DeviceSinkError(Exception):
    pass


class TaskDeviceSink:
    """One task's HBM landing: wraps ops.hbm_sink.HBMSink with the piece
    bookkeeping the daemon needs (which pieces landed, their host digests,
    staleness)."""

    def __init__(self, task_id: str, content_length: int, piece_size: int, *,
                 device=None, batch_pieces: int = 8):
        from dragonfly2_tpu.ops.hbm_sink import HBMSink

        # HBM offsets are word-addressed: a non-word-aligned piece size
        # (only possible for single-piece tasks, where it equals the
        # content length) rounds up — zero padding is checksum-neutral.
        total_pieces = max(
            1, (content_length + piece_size - 1) // piece_size)
        if piece_size % 4 and total_pieces > 1:
            raise DeviceSinkError(
                f"piece size {piece_size} not 4-byte aligned")
        aligned = piece_size + ((-piece_size) % 4)
        self.task_id = task_id
        self.sink = HBMSink(content_length, aligned, device=device,
                            batch_pieces=batch_pieces)
        self.created_at = time.time()
        self.verified = False
        self.verified_at = 0.0
        # Host-side piece digests at land time: lets a later finalize
        # detect that the store's content changed under a resident sink.
        self.piece_digests: dict[int, str] = {}

    def land(self, piece_num: int, data: bytes, digest: str = "") -> None:
        self.sink.land_piece(piece_num, data)
        self.piece_digests[piece_num] = digest
        SINK_LANDED_BYTES.inc(len(data))

    @property
    def landed(self) -> set[int]:
        return self.sink.landed

    def verify(self) -> None:
        try:
            self.sink.verify()
        except ValueError as e:
            SINK_VERIFY_COUNT.labels("corrupt").inc()
            raise DeviceSinkError(str(e)) from e
        SINK_VERIFY_COUNT.labels("ok").inc()
        self.verified = True
        self.verified_at = time.time()

    # Consumption — delegates to the HBMSink.

    def as_bytes_array(self):
        return self.sink.as_bytes_array()

    def as_tensor(self, dtype, shape):
        return self.sink.as_tensor(dtype, shape)

    def shard_to_mesh(self, mesh, axis_name: str = "d"):
        return self.sink.shard_to_mesh(mesh, axis_name)

    def ici_broadcast(self, mesh, axis_name: str = "d", n_chunks: int = 4):
        """Striped-broadcast consumption: replicate the landed content to
        every device of the mesh via the chunked ring all-gather (ICI
        completes the copy; the NIC is done once the stripe landed).
        Requires a verified sink — a striped task must never expose
        unverified bytes, on device exactly as over upload."""
        if not self.verified:
            raise DeviceSinkError(
                f"ici_broadcast on unverified sink {self.task_id[:16]}")
        return self.sink.ring_replicate(mesh, axis_name, n_chunks=n_chunks)


class DeviceSinkManager:
    """Owns the per-task sinks a daemon is landing. Selected per request
    (FileTaskRequest.device == "tpu"); gated by TPUSinkOption.enabled."""

    def admit(self):
        """Admission bound for CLIENT-API device pulls: an async context
        holding one HBM-sink slot (one below ``max_tasks``, so an
        unrelated RPC-path device task is never starved). Shared across
        every download_to_device/download_sharded on this daemon —
        per-call bounds compose into cap overruns when calls run
        concurrently. RPC-path requests deliberately do not admit: their
        contract is graceful disk-only degradation at the cap, while the
        client API's contract is a verified device landing or an error."""
        if self._admission is None:
            self._admission = asyncio.Semaphore(max(1, self.max_tasks - 1))
        return self._admission

    def __init__(self, *, mesh_shape: list[int] | None = None,
                 batch_pieces: int = 8, max_tasks: int = 4,
                 ttl: float = 600.0, device=None):
        self._admission = None
        self.claim_grace_s = 10.0   # see _create's eviction rule
        # Task ids a client pull has announced it WILL claim (set before
        # the landing starts, cleared after take) — never evicted.
        # Refcounted: concurrent claimers of one deduped task each hold
        # a reference; the first to finish must not strip the others'.
        self._protected: dict[str, int] = {}
        self.mesh_shape = list(mesh_shape or [])
        self.batch_pieces = batch_pieces
        self.max_tasks = max_tasks
        self.ttl = ttl
        self._device = device
        self._sinks: dict[str, TaskDeviceSink] = {}
        # Tasks whose sink hit a device error mid-download: disk-only for
        # the rest of this attempt (cleared on discard → retry is fresh).
        self._degraded: set[str] = set()
        # Single worker: serializes sink mutation (HBMSink is not
        # thread-safe) and keeps device copies off the event loop.
        self._exec = ThreadPoolExecutor(
            max_workers=1, thread_name_prefix="df-device-sink")

    def close(self) -> None:
        self._exec.shutdown(wait=False, cancel_futures=True)

    async def _run(self, fn, *args):
        return await asyncio.get_running_loop().run_in_executor(
            self._exec, fn, *args)

    # -- landing ----------------------------------------------------------

    async def on_piece(self, task_id: str, store, rec) -> None:
        """Land one verified piece as it arrives (conductor/back-source
        on_piece hook). Creation is lazy: the first piece to arrive after
        the task's length and piece size are known allocates the buffer."""
        await self._run(self._land_sync, task_id, store, rec)

    def _land_sync(self, task_id: str, store, rec) -> None:
        if task_id in self._degraded:
            return
        sink = self._sinks.get(task_id)
        if sink is None:
            m = store.metadata
            if m.content_length < 0 or m.piece_size <= 0:
                return  # metadata not known yet; backfill catches it later
            sink = self._create(task_id, m.content_length, m.piece_size)
            if sink is None:
                return
        if rec.num in sink.landed:
            return
        if rec.num >= sink.sink.total_pieces:
            log.warning("piece out of sink range, skipped",
                        task=task_id[:16], piece=rec.num)
            return
        try:
            sink.land(rec.num, store.read_piece(rec.num), rec.digest)
        except Exception as e:
            # Device trouble mid-stream (HBM OOM in the staging device_put,
            # runtime errors): degrade THIS task to disk-only — the
            # download itself must not fail, and later pieces must not
            # retry a doomed sink.
            log.warning("device landing failed; degrading to disk-only",
                        task=task_id[:16], error=str(e)[:200])
            self._sinks.pop(task_id, None)
            self._degraded.add(task_id)

    def _create(self, task_id: str, content_length: int,
                piece_size: int) -> TaskDeviceSink | None:
        self._expire()
        if len(self._sinks) >= self.max_tasks:
            # Residents are cached conveniences — the disk store stays
            # authoritative — so a verified, unclaimed sink yields its
            # HBM to a NEW landing rather than failing it (oldest first).
            # Mid-landing sinks are never evicted, and a freshly verified
            # sink gets a claim grace: its requester is typically between
            # verify and take() (both await points), and evicting there
            # would strand a successful download in a lose-the-sink loop.
            now = time.time()
            verified = sorted(
                (s for s in self._sinks.values()
                 if s.verified and s.task_id not in self._protected),
                key=lambda s: s.created_at)
            # Grace is a PREFERENCE, not a guarantee: evict out-of-grace
            # residents first, but when every (unprotected) resident is
            # freshly verified (e.g. an RPC preheat just warmed max_tasks
            # sinks) still evict the oldest rather than hard-failing the
            # new landing. Sinks a client pull has announced it will
            # claim (protect/unprotect) are never candidates — evicting
            # one strands a completed, verified download in a
            # lose-the-sink retry loop.
            evictable = ([s for s in verified
                          if now - s.verified_at > self.claim_grace_s]
                         or verified)
            if evictable:
                victim = evictable[0]
                log.info("evicting resident device sink for new landing",
                         evicted=victim.task_id[:16], task=task_id[:16])
                del self._sinks[victim.task_id]
            else:
                log.warning("device sink cap reached; landing to disk only",
                            task=task_id[:16], cap=self.max_tasks)
                return None
        try:
            sink = TaskDeviceSink(task_id, content_length, piece_size,
                                  device=self._device,
                                  batch_pieces=self.batch_pieces)
        except Exception as e:
            # Includes device OOM (XlaRuntimeError): degrade to disk-only
            # rather than failing the whole download.
            log.warning("device sink unavailable for task",
                        task=task_id[:16], error=str(e)[:200])
            return None
        self._sinks[task_id] = sink
        log.info("device sink created", task=task_id[:16],
                 bytes=content_length)
        return sink

    # -- completion -------------------------------------------------------

    async def finalize(self, task_id: str, store) -> TaskDeviceSink | None:
        """Complete the landing: backfill pieces the streaming hook missed
        (reuse path, tiny/small shortcuts, pre-metadata arrivals), then
        verify every landed piece on device. Returns None when no sink
        could be allocated (cap reached, misaligned pieces) — disk-only
        degradation; raises DeviceSinkError on device-copy CORRUPTION."""
        return await self._run(self._finalize_sync, task_id, store)

    def _finalize_sync(self, task_id: str, store) -> TaskDeviceSink | None:
        if task_id in self._degraded:
            self._degraded.discard(task_id)  # next attempt starts fresh
            return None
        try:
            return self._finalize_inner(task_id, store)
        except DeviceSinkError:
            raise  # device-copy corruption: surfaced to the caller
        except Exception as e:
            # Environment failures (OOM during backfill staging, assembly
            # dispatch errors, store read races) degrade to disk-only —
            # the digest-verified disk result must not be discarded over a
            # device-side hiccup.
            log.warning("device finalize failed; disk-only result",
                        task=task_id[:16], error=str(e)[:200])
            self._sinks.pop(task_id, None)
            return None

    def _finalize_inner(self, task_id: str, store) -> TaskDeviceSink | None:
        m = store.metadata
        sink = self._sinks.get(task_id)
        if sink is not None and self._stale(sink, store):
            # The store's content changed under a resident sink (same task
            # id, new bytes — e.g. origin changed between invalidate and
            # retry): a mixed buffer must never verify. Rebuild.
            log.warning("device sink stale vs store; rebuilding",
                        task=task_id[:16])
            del self._sinks[task_id]
            sink = None
        if sink is None:
            sink = self._create(task_id, m.content_length, m.piece_size)
            if sink is None:
                return None
        for rec in store.get_pieces():
            if rec.num not in sink.landed:
                sink.land(rec.num, store.read_piece(rec.num), rec.digest)
        sink.verify()
        log.info("device sink verified", task=task_id[:16],
                 pieces=len(sink.landed))
        return sink

    @staticmethod
    def _stale(sink: TaskDeviceSink, store) -> bool:
        pieces = store.metadata.pieces
        for num, digest in sink.piece_digests.items():
            rec = pieces.get(num)
            if rec is None or (digest and rec.digest and rec.digest != digest):
                return True
        return False

    # -- consumption / lifecycle ------------------------------------------

    def protect(self, task_id: str) -> None:
        """Announce an imminent claim: the sink for ``task_id`` (existing
        or about to land) is exempt from cap-pressure eviction until
        ``unprotect``. Callers must pair with unprotect in a finally."""
        self._protected[task_id] = self._protected.get(task_id, 0) + 1

    def unprotect(self, task_id: str) -> None:
        n = self._protected.get(task_id, 0) - 1
        if n > 0:
            self._protected[task_id] = n
        else:
            self._protected.pop(task_id, None)

    def get(self, task_id: str) -> TaskDeviceSink | None:
        return self._sinks.get(task_id)

    def take(self, task_id: str) -> TaskDeviceSink | None:
        """Claim the sink (caller owns the buffer; manager forgets it)."""
        return self._sinks.pop(task_id, None)

    def discard(self, task_id: str) -> None:
        self._sinks.pop(task_id, None)
        self._degraded.discard(task_id)

    def gc(self) -> None:
        """Periodic TTL sweep (daemon GC hook) — unclaimed sinks must not
        hold content-sized HBM for the daemon's lifetime."""
        self._expire()

    def _expire(self) -> None:
        now = time.time()
        for tid in [t for t, s in self._sinks.items()
                    if now - s.created_at > self.ttl]:
            log.info("device sink expired", task=tid[:16])
            del self._sinks[tid]

    def default_mesh(self):
        """Mesh over LOCAL devices per TPUSinkOption.mesh_shape (or all
        local devices on one axis when unset) — the sink's shard_to_mesh
        spreads over this host's chips; under jax.distributed the global
        list would include non-addressable devices."""
        import numpy as np
        import jax
        from jax.sharding import Mesh

        devices = jax.local_devices()
        if self.mesh_shape:
            n = int(np.prod(self.mesh_shape))
            names = tuple(f"d{i}" for i in range(len(self.mesh_shape)))
            return Mesh(np.asarray(devices[:n]).reshape(self.mesh_shape),
                        names)
        return Mesh(np.asarray(devices), ("d",))
