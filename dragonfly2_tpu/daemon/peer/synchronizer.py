"""Piece-task synchronizer: per-parent drpc streams announcing pieces.

Reference: client/daemon/peer/peertask_piecetask_synchronizer.go — one
``SyncPieceTasks`` stream per parent (:81-143 syncPeers), received piece
infos dispatched into the dispatcher (:341-386), invalid peers reported so
the scheduler can blocklist them.

Wire (drpc "Peer.SyncPieceTasks"):
  open_body: {task_id, src_peer_id (requester), dst_peer_id (parent)}
  parent → child: {pieces: [nums], total_piece_count, content_length,
                   piece_size, done}
  child → parent: {interested: true}   (keep-alive / request-more)
"""

from __future__ import annotations

import asyncio

from dragonfly2_tpu.daemon.peer.piece_dispatcher import PieceDispatcher
from dragonfly2_tpu.pkg import dflog
from dragonfly2_tpu.pkg.errors import Code, DfError
from dragonfly2_tpu.pkg.types import NetAddr
from dragonfly2_tpu.rpc import Client

log = dflog.get("peer.synchronizer")


class PieceTaskSynchronizer:
    """Manages one sync stream per parent for a single conductor."""

    # Idle-stream keep-alive: a parent that announced everything it has
    # goes quiet while the child drains its assignment queue — that is a
    # HEALTHY stream, not a dead one. Instead of one fatal 60 s recv
    # timeout, recv in keep-alive-sized slices and send the documented
    # {interested: true} on each idle slice. Class attrs so tests can
    # shrink the cadence.
    KEEPALIVE_INTERVAL = 15.0

    def __init__(self, task_id: str, peer_id: str, dispatcher: PieceDispatcher,
                 on_parent_dead=None, own_slice: str = ""):
        self.task_id = task_id
        self.peer_id = peer_id
        self.dispatcher = dispatcher
        self.on_parent_dead = on_parent_dead
        # This host's ICI domain: parents advertising the same tpu_slice
        # are marked same_slice in the dispatcher (stripe wanted-set +
        # locality byte accounting).
        self.own_slice = own_slice
        self._tasks: dict[str, asyncio.Task] = {}
        self._clients: dict[str, Client] = {}

    def sync_parents(self, parents: list[dict]) -> None:
        """Start/refresh sync streams for the scheduled parent set
        (reference syncPeers :81)."""
        for parent in parents:
            peer_id = parent["id"]
            host = parent.get("host") or {}
            ip, port = host.get("ip", ""), host.get("port", 0)
            upload_port = host.get("upload_port", 0)
            if not ip or not port or not upload_port:
                log.warning("parent missing address", parent=peer_id[:24])
                continue
            parent_slice = host.get("tpu_slice", "") or ""
            self.dispatcher.upsert_parent(
                peer_id, ip, upload_port,
                same_slice=bool(self.own_slice)
                and parent_slice == self.own_slice,
                tpu_slice=parent_slice)
            # Seed known pieces from the schedule response, and the
            # relayed digests into the SHARED map only (no parent
            # attribution — relayed digests have no provenance and must
            # not be laundered into a parent's certified map): early
            # assignments then verify at landing, and certification still
            # requires the parent's own announced values to match.
            finished = parent.get("finished_pieces") or []
            if finished:
                self.dispatcher.on_parent_pieces(peer_id, finished)
                self.dispatcher.seed_shared_digests(
                    parent.get("piece_digests"))
            if peer_id not in self._tasks or self._tasks[peer_id].done():
                self._tasks[peer_id] = asyncio.ensure_future(
                    self._sync_one(peer_id, ip, port))

    async def _sync_one(self, parent_peer_id: str, ip: str, port: int) -> None:
        cli = self._clients.get(parent_peer_id)
        if cli is None:
            cli = Client(NetAddr.tcp(ip, port))
            self._clients[parent_peer_id] = cli
        try:
            stream = await cli.open_stream(
                "Peer.SyncPieceTasks",
                {"task_id": self.task_id, "src_peer_id": self.peer_id,
                 "dst_peer_id": parent_peer_id},
            )
            done = False
            while True:
                try:
                    msg = await stream.recv(timeout=self.KEEPALIVE_INTERVAL)
                except DfError as e:
                    if e.code != Code.RequestTimeout:
                        raise
                    # Idle slice, not a dead stream: the parent may simply
                    # have announced everything it holds. Keep the stream
                    # (and the parent) alive while the dispatcher still
                    # considers it usable; a parent the dispatcher blocked
                    # (failures, drop) has nothing left to say.
                    info = self.dispatcher.parents.get(parent_peer_id)
                    if info is None or info.blocked:
                        break
                    await stream.send({"interested": True})
                    continue
                if msg is None:
                    break
                self.dispatcher.on_parent_pieces(
                    parent_peer_id,
                    msg.get("pieces") or [],
                    msg.get("total_piece_count", -1),
                    msg.get("content_length", -1),
                    msg.get("piece_size", 0),
                    digests=msg.get("digests") or {},
                )
                if msg.get("done"):
                    # The parent passed its completion gate (seed: full
                    # digest validated) — its digest map can certify the
                    # child's re-hash-skip decision (provenance-checked).
                    self.dispatcher.note_parent_done(parent_peer_id)
                    done = True
                    break
            if not done:
                # Clean close without done: the parent went away mid-task; it
                # must not linger as an 'active' parent with a stale subset.
                log.info("sync stream closed early", parent=parent_peer_id[:24])
                self.dispatcher.drop_parent(parent_peer_id)
                if self.on_parent_dead is not None:
                    self.on_parent_dead(parent_peer_id)
        except asyncio.CancelledError:
            raise
        except Exception as e:
            log.warning("sync stream lost", parent=parent_peer_id[:24], error=str(e))
            self.dispatcher.drop_parent(parent_peer_id)
            if self.on_parent_dead is not None:
                self.on_parent_dead(parent_peer_id)

    async def close(self) -> None:
        for t in self._tasks.values():
            t.cancel()
        for t in self._tasks.values():
            try:
                await t
            except (asyncio.CancelledError, Exception):
                pass
        for cli in self._clients.values():
            await cli.close()
        self._tasks.clear()
        self._clients.clear()
