"""Daemon bootstrap: wire every sub-service and serve.

Reference: client/daemon/daemon.go — New (:108) builds storage, peer task
manager, rpc servers, upload server, proxy, object storage, gc, announcer;
Serve (:400-710) starts them; Stop (:711) tears down. Stage 2 wires the
download path; later stages attach upload/proxy/objectstorage/announcer.
"""

from __future__ import annotations

import asyncio

from dragonfly2_tpu.daemon.config import DaemonConfig
from dragonfly2_tpu.daemon.peer.piece_manager import PieceManager, PieceManagerOption
from dragonfly2_tpu.daemon.peer.task_manager import TaskManager
from dragonfly2_tpu.daemon.rpcserver import DaemonRpcServer
from dragonfly2_tpu.pkg import dflog
from dragonfly2_tpu.pkg.cache import GC, GCTask
from dragonfly2_tpu.pkg.ratelimit import Limiter
from dragonfly2_tpu.pkg.types import NetAddr
from dragonfly2_tpu.storage import StorageManager, StorageOption

log = dflog.get("daemon")


class Daemon:
    def __init__(self, config: DaemonConfig):
        self.config = config
        path = config.dfpath.ensure()
        dflog.configure(log_dir=path.log_dir)

        self.storage = StorageManager(
            StorageOption(
                data_dir=path.data_dir,
                task_ttl=config.storage.task_ttl,
                disk_gc_threshold=config.storage.disk_gc_threshold,
                keep_storage=config.storage.keep_storage,
                gc_interval=config.gc_interval,
            )
        )
        self.storage.reload()

        rate = config.download.rate_limit
        self.piece_manager = PieceManager(
            PieceManagerOption(
                concurrency=config.download.piece_concurrency,
                compute_digest=config.download.calculate_digest,
                concurrent_min_length=config.download.concurrent_min_length,
            ),
            limiter=Limiter(rate if rate > 0 else float("inf")),
        )
        self.task_manager = TaskManager(
            self.storage,
            self.piece_manager,
            host_ip=config.host.ip,
            total_rate_limit=rate,
        )
        self.rpc = DaemonRpcServer(self.task_manager)
        self.gc = GC(log)
        self.gc.add(GCTask("storage", config.gc_interval, 30.0, self._gc_storage))
        self._stopped = asyncio.Event()

    async def _gc_storage(self) -> None:
        self.storage.gc()

    async def serve(self) -> None:
        await self.rpc.serve_download(NetAddr.unix(self.config.download.unix_sock))
        if self.config.download.peer_port >= 0:
            await self.rpc.serve_peer(
                NetAddr.tcp(self.config.host.ip, self.config.download.peer_port)
            )
        self.gc.serve()
        log.info(
            "daemon up",
            sock=self.config.download.unix_sock,
            data_dir=self.storage.opt.data_dir,
        )
        if self.config.alive_time > 0:
            try:
                await asyncio.wait_for(self._stopped.wait(), self.config.alive_time)
            except asyncio.TimeoutError:
                log.info("alive time reached, exiting")
        else:
            await self._stopped.wait()

    async def stop(self) -> None:
        self.gc.stop()
        await self.rpc.close()
        self.storage.close()
        self._stopped.set()

    def peer_port(self) -> int:
        return self.rpc.peer_server.port()
