"""Daemon bootstrap: wire every sub-service and serve.

Reference: client/daemon/daemon.go — New (:108) builds storage, peer task
manager, rpc servers, upload server, proxy, object storage, gc, announcer;
Serve (:400-710) starts them; Stop (:711) tears down.
"""

from __future__ import annotations

import asyncio
import os

from dragonfly2_tpu.daemon.announcer import Announcer
from dragonfly2_tpu.daemon.config import DaemonConfig
from dragonfly2_tpu.daemon.peer.conductor import PeerTaskConductor
from dragonfly2_tpu.daemon.peer.piece_manager import PieceManager, PieceManagerOption
from dragonfly2_tpu.daemon.peer.task_manager import TaskManager
from dragonfly2_tpu.daemon.rpcserver import DaemonRpcServer
from dragonfly2_tpu.daemon.schedulerclient import SchedulerClient
from dragonfly2_tpu.daemon.upload import UploadManager
from dragonfly2_tpu.pkg import dflog
from dragonfly2_tpu.pkg.cache import GC, GCTask
from dragonfly2_tpu.pkg.ratelimit import Limiter
from dragonfly2_tpu.pkg.types import NetAddr
from dragonfly2_tpu.storage import StorageManager, StorageOption

log = dflog.get("daemon")


class Daemon:
    def __init__(self, config: DaemonConfig):
        self.config = config
        path = config.dfpath.ensure()
        dflog.configure(log_dir=path.log_dir)

        # TPU topology autodetection feeds the scheduler's ICI/DCN-aware
        # evaluator (env-based; never initializes JAX unless opted in).
        from dragonfly2_tpu.parallel.topology import apply_to_host_config

        apply_to_host_config(config.host)

        self.storage = StorageManager(
            StorageOption(
                data_dir=path.data_dir,
                task_ttl=config.storage.task_ttl,
                disk_gc_threshold=config.storage.disk_gc_threshold,
                keep_storage=config.storage.keep_storage,
                gc_interval=config.gc_interval,
                fd_idle_close=config.storage.fd_idle_close,
            )
        )
        self.storage.reload()

        rate = config.download.rate_limit
        self.piece_manager = PieceManager(
            PieceManagerOption(
                concurrency=config.download.piece_concurrency,
                compute_digest=config.download.calculate_digest,
                concurrent_min_length=config.download.concurrent_min_length,
            ),
            limiter=Limiter(rate if rate > 0 else float("inf")),
        )

        self.scheduler_client: SchedulerClient | None = None
        if config.scheduler.addrs:
            self.scheduler_client = SchedulerClient(config.scheduler.addrs)

        # Tenant QoS plane (dragonfly2_tpu/qos): one DWRR dispatch gate
        # shared by every conductor's piece workers + per-tenant upload
        # buckets under the daemon-wide cap. Gated off by default; with
        # it on, piece serving stays on the aiohttp path (attribution
        # and per-tenant limiting live there).
        self.qos_gate = None
        qos_buckets = None
        if config.qos.enabled:
            from dragonfly2_tpu import qos as qoslib

            capacity = config.qos.dispatch_capacity or (
                2 * max(1, config.download.parent_concurrency))
            self.qos_gate = qoslib.WFQGate(capacity)
            qos_buckets = qoslib.TenantBuckets(
                float(config.upload.rate_limit),
                min_share_fraction=config.qos.upload_min_share_fraction)
        self.upload = UploadManager(self.storage,
                                    rate_limit=config.upload.rate_limit,
                                    qos_buckets=qos_buckets)
        device_sinks = None
        if config.tpu_sink.enabled:
            from dragonfly2_tpu.daemon.peer.device_sink import DeviceSinkManager

            device_sinks = DeviceSinkManager(
                mesh_shape=config.tpu_sink.mesh_shape,
                batch_pieces=config.tpu_sink.batch_pieces,
                max_tasks=config.tpu_sink.max_tasks)
        self.task_manager = TaskManager(
            self.storage,
            self.piece_manager,
            host_ip=config.host.ip,
            scheduler_client=self.scheduler_client,
            conductor_factory=self._make_conductor if self.scheduler_client else None,
            total_rate_limit=rate,
            host_wire=self._host_wire,
            traffic_shaper=config.download.traffic_shaper,
            prefetch=config.download.prefetch,
            device_sinks=device_sinks,
        )
        self.rpc = DaemonRpcServer(self.task_manager)
        self.proxy = None
        if config.proxy.enabled:
            from dragonfly2_tpu.daemon.proxy import Proxy
            from dragonfly2_tpu.daemon.transport import P2PTransport, rules_from_config

            rules = rules_from_config(config.proxy.rules)
            ca = None
            if config.proxy.hijack_https or config.proxy.sni_hijack:
                from dragonfly2_tpu.pkg.certify import CertAuthority

                ca = CertAuthority.load_or_generate(
                    config.proxy.ca_cert, config.proxy.ca_key,
                    persist_dir=os.path.join(config.work_home or ".", "ca"))
            self.proxy = Proxy(
                P2PTransport(self.task_manager, rules=rules),
                registry_mirror=config.proxy.registry_mirror,
                max_concurrency=config.proxy.max_concurrency,
                white_list_ports=config.proxy.white_list_ports,
                cert_authority=ca,
                hijack_hosts=config.proxy.hijack_hosts)
        self.object_storage = None
        if config.object_storage.enabled:
            from dragonfly2_tpu.daemon.objectstorage import ObjectStorageService
            from dragonfly2_tpu.daemon.transport import P2PTransport
            from dragonfly2_tpu.pkg.objectstorage import new_client

            backend = new_client(config.object_storage.backend,
                                 **config.object_storage.backend_options)
            self.object_storage = ObjectStorageService(
                backend, P2PTransport(self.task_manager),
                get_seed_peers=self._known_seed_peers,
                trigger_seed=self._trigger_seed_peer)
        self.announcer: Announcer | None = None
        self.dynconfig = None  # manager-source scheduler resolution
        self.pex = None        # gossip peer exchange (started in start())
        self.metrics = None    # Prometheus + /debug endpoint
        self.prof_obs = None   # runtime observatory (pkg/prof)
        self._prof_probe = None
        self._runtime_slo = None
        self._started = False
        self._peer_port = 0
        self.gc = GC(log)
        self.gc.add(GCTask("storage", config.gc_interval, 30.0, self._gc_storage))
        self._stopped = asyncio.Event()

    def _host_wire(self) -> dict:
        """Canonical host identity, {} before the announcer exists."""
        if self.announcer is None:
            return {}
        return self.announcer.host_wire()

    # -- object-storage replication hooks ----------------------------------

    def _known_seed_peers(self) -> list[dict]:
        """Seed peers from dynconfig (manager mode); empty otherwise —
        replication then degrades to backend-only writes."""
        if self.dynconfig is not None and hasattr(self.dynconfig, "cached_seed_peers"):
            return self.dynconfig.cached_seed_peers()
        return []

    async def _trigger_seed_peer(self, seed: dict, spec: dict) -> bool:
        """Fire Peer.TriggerDownloadTask at a seed daemon (same RPC the
        scheduler uses — seed_client.py)."""
        from dragonfly2_tpu.rpc import Client

        addr = NetAddr.tcp(seed.get("ip", ""), int(seed.get("port", 0)))
        cli = Client(addr)
        try:
            resp = await cli.call("Peer.TriggerDownloadTask", spec, timeout=10.0)
            return bool(resp and resp.get("ok"))
        except Exception:
            return False
        finally:
            await cli.close()

    # -- conductor factory (P2P path) --------------------------------------

    def _make_conductor(self, *, task_id: str, peer_id: str, request, store,
                        on_piece, is_seed: bool = False,
                        limiter=None) -> PeerTaskConductor:
        disable_back_source = getattr(request, "disable_back_source", False)
        if self.announcer is None:
            raise RuntimeError("conductor requires a started daemon (announcer missing)")
        # Single source of truth for the host record: the announcer's wire
        # form (minus telemetry) — scheduler must see ONE identity per host.
        host_info = self.announcer.host_wire()
        host_info.pop("telemetry", None)
        meta = {
            "tag": request.meta.tag,
            "application": request.meta.application,
            "digest": request.meta.digest,
            "filters": request.meta.filter.split("&") if request.meta.filter else [],
            "header": dict(request.meta.header),
            "priority": request.meta.priority,
            "tenant": request.meta.tenant,
            "range": request.meta.range,
            "pod_broadcast": getattr(request, "pod_broadcast", False),
        }
        return PeerTaskConductor(
            task_id=task_id,
            peer_id=peer_id,
            url=request.url,
            store=store,
            scheduler_client=self.scheduler_client,
            piece_manager=self.piece_manager,
            host_info=host_info,
            meta=meta,
            flight=self.task_manager.flight.task(task_id),
            quarantine=self.task_manager.quarantine,
            is_seed=is_seed or self.config.seed_peer,
            piece_parallelism=self.config.download.parent_concurrency,
            report_batch=self.config.download.report_batch,
            limiter=limiter if limiter is not None else self.task_manager.limiter,
            on_piece=on_piece,
            wfq=self.qos_gate,
            disable_back_source=disable_back_source,
            local_range_source=(
                lambda s, cb, _req=request:
                self.task_manager.import_range_from_local_parent(s, _req, cb)),
        )

    async def _resolve_schedulers_from_manager(self) -> None:
        """Manager-source dynconfig: resolve (and keep fresh) the scheduler
        set; static config addrs stay as fallback (reference
        client/config/dynconfig_manager.go). The refresh loop always runs, so
        a daemon started before any scheduler registers picks one up on the
        next refresh instead of staying sourceless forever."""
        from dragonfly2_tpu.daemon.dynconfig import DaemonDynconfig

        h = self.config.host
        self.dynconfig = DaemonDynconfig(
            local_addrs=self.config.scheduler.addrs,
            manager_addr=self.config.manager_addr,
            host_info={"hostname": h.hostname, "ip": h.ip, "idc": h.idc,
                       "location": h.location, "pod": h.tpu_slice},
            cache_dir=self.config.dfpath.cache_dir)
        addrs = await self.dynconfig.scheduler_addrs()
        if addrs:
            self._apply_scheduler_addrs(addrs)
        else:
            log.warning("manager returned no schedulers yet; will keep polling")

        def _on_change(data: dict) -> None:
            fresh = [f"{s['ip']}:{s['port']}" for s in data.get("schedulers", [])
                     if s.get("state") == "active"]
            if fresh:
                self._apply_scheduler_addrs(fresh)

        self.dynconfig.register(_on_change)
        self.dynconfig.serve()

    def _apply_scheduler_addrs(self, addrs: list[str]) -> None:
        if self.scheduler_client is None:
            self.scheduler_client = SchedulerClient(addrs)
            self.task_manager.scheduler_client = self.scheduler_client
            self.task_manager.conductor_factory = self._make_conductor
            # Late discovery (daemon already serving): bring the announcer up
            # now so the scheduler learns this host.
            if self._started and self.announcer is None:
                self.announcer = Announcer(
                    self.config, self.scheduler_client,
                    peer_port=self._peer_port, upload_port=self.upload.port,
                    recorder=self.task_manager.flight)
                asyncio.create_task(self.announcer.start())
        else:
            self.scheduler_client.update_addrs(addrs)

    async def _gc_storage(self) -> None:
        self.storage.gc()
        if self.task_manager.device_sinks is not None:
            # TTL sweep of unclaimed device sinks: content-sized HBM must
            # not stay resident for the daemon's lifetime.
            self.task_manager.device_sinks.gc()

    # -- lifecycle ---------------------------------------------------------

    async def start(self) -> None:
        """Bring every service up (non-blocking)."""
        # Retain FIRST: services go live mid-start, and a sibling
        # daemon's stop() must not close the shared origin sessions under
        # a request that raced in. A failed start releases in the
        # except — both hygiene properties hold.
        from dragonfly2_tpu.source.client import default_registry

        self._source_registry = default_registry().retain()
        try:
            await self._start_inner()
        except BaseException:
            registry, self._source_registry = self._source_registry, None
            if registry is not None:
                await registry.release()
            raise

    async def _start_inner(self) -> None:
        # Chaos fabric: armed ONLY when DF_CHAOS is set (benches/e2e fault
        # drills). The guard keeps pkg/chaos entirely unimported — and the
        # data plane hook-free — in normal operation.
        if os.environ.get("DF_CHAOS"):
            from dragonfly2_tpu.pkg import chaos

            chaos.maybe_enable_from_env()
        # Warm the native data-plane probe off-loop: a cold first import
        # compiles the C++ library (seconds of g++), which must not freeze
        # the event loop at the first piece write on the hot path.
        from dragonfly2_tpu.storage import local_store

        await asyncio.get_running_loop().run_in_executor(None, local_store._native)
        if self.config.manager_addr:
            await self._resolve_schedulers_from_manager()
        self.task_manager.shaper.serve()
        # Flight recorder: post-mortem bundles land next to the logs so a
        # failed task's autopsy survives the process (pkg/flight).
        recorder = self.task_manager.flight
        if not recorder.dump_dir:
            recorder.dump_dir = self.config.dfpath.log_dir
        recorder.keep_bundles = self.config.flight_keep_bundles
        if self.config.clock_offset_s:
            recorder.wall_offset = self.config.clock_offset_s
        if self.config.prof.enabled:
            # Runtime observatory: always-on sampler + loop-lag probe +
            # GC observatory (pkg/prof; paired cost published as
            # config12_prof). Slow ticks/pauses stamp typed events into
            # every running flight; the probe feeds a daemon-side
            # loop_lag SLO engine at /debug/slo.
            from dataclasses import replace as _dc_replace

            from dragonfly2_tpu.pkg import prof as proflib
            from dragonfly2_tpu.pkg import slo as slolib

            self.prof_obs = proflib.install(self.config.prof,
                                            recorder=recorder)
            self._prof_probe = self.prof_obs.arm_loop("daemon")
            recorder.runtime = self.prof_obs
            self._runtime_slo = slolib.SLOEngine(
                specs=tuple(
                    _dc_replace(s, threshold=self.config.prof.lag_slow_s)
                    for s in slolib.RUNTIME_SLOS),
                probes=self.prof_obs.slo_probes())
        if self.config.metrics_port >= 0:
            from dragonfly2_tpu.pkg.metrics_server import MetricsServer

            # Loopback by default: /debug exposes live stacks; operators
            # who want network scraping front it deliberately.
            self.metrics = MetricsServer(flight=recorder,
                                         prof=self.prof_obs,
                                         slo=self._runtime_slo)
            await self.metrics.serve("127.0.0.1", self.config.metrics_port)
        await self.rpc.serve_download(NetAddr.unix(self.config.unix_sock))
        if self.config.download.peer_port >= 0:  # -1 disables the peer service
            await self.rpc.serve_peer(
                NetAddr.tcp(self.config.host.ip, self.config.download.peer_port))
        await self.upload.serve(self.config.host.ip, self.config.upload.port)
        if self.proxy is not None:
            await self.proxy.serve(self.config.host.ip, self.config.proxy.port)
            if self.config.proxy.sni_enabled:
                await self.proxy.serve_sni(
                    self.config.host.ip, self.config.proxy.sni_port,
                    hijack=self.config.proxy.sni_hijack)
        if self.object_storage is not None:
            await self.object_storage.serve(self.config.host.ip,
                                            self.config.object_storage.port)
        if self.config.pex.enabled:
            from dragonfly2_tpu.daemon.pex import PeerExchange

            self.pex = PeerExchange(
                ip=self.config.host.ip,
                peer_port=self.rpc.peer_server.port() if self.rpc.peer_server._servers else 0,
                upload_port=self.upload.port,
                secret=self.config.pex.secret)
            await self.pex.start(self.config.pex.port, self.config.pex.seeds)
            self.task_manager.pex = self.pex
            # Gossip everything already complete on disk (restart recovery).
            for store in self.storage.tasks():
                if store.metadata.done and not store.metadata.invalid:
                    self.pex.add_task(store.metadata.task_id)
        peer_port = self.rpc.peer_server.port() if self.rpc.peer_server._servers else 0
        self._peer_port = peer_port
        self._started = True
        if self.scheduler_client is not None:
            self.announcer = Announcer(
                self.config, self.scheduler_client,
                peer_port=peer_port,
                upload_port=self.upload.port,
                recorder=self.task_manager.flight,
            )
            await self.announcer.start()
        self.gc.serve()
        log.info(
            "daemon up",
            sock=self.config.unix_sock,
            peer_port=peer_port,
            upload_port=self.upload.port,
            seed=self.config.seed_peer,
        )

    async def serve(self) -> None:
        await self.start()
        if self.config.alive_time > 0:
            try:
                await asyncio.wait_for(self._stopped.wait(), self.config.alive_time)
            except asyncio.TimeoutError:
                log.info("alive time reached, exiting")
        else:
            await self._stopped.wait()

    async def stop(self) -> None:
        self.gc.stop()
        self.task_manager.shaper.stop()
        if self.pex is not None:
            await self.pex.stop()
        if self.metrics is not None:
            await self.metrics.close()
        if self.prof_obs is not None:
            from dragonfly2_tpu.pkg import prof as proflib

            if self._prof_probe is not None:
                self._prof_probe.disarm()
                self.prof_obs.probes.pop(self._prof_probe.name, None)
            self.task_manager.flight.runtime = None
            proflib.release(self.prof_obs)
            self.prof_obs = None
        if self.dynconfig is not None:
            await self.dynconfig.stop()
        if self.announcer is not None:
            await self.announcer.stop()
        if self.scheduler_client is not None:
            await self.scheduler_client.close()
        if self.proxy is not None:
            await self.proxy.close()
        if self.object_storage is not None:
            await self.object_storage.close()
        await self.upload.close()
        await self.rpc.close()
        if self.task_manager.device_sinks is not None:
            self.task_manager.device_sinks.close()
        registry = getattr(self, "_source_registry", None)
        if registry is not None:
            self._source_registry = None
            await registry.release(close_when_idle=True)
        self.storage.close()
        self._stopped.set()

    def peer_port(self) -> int:
        return self.rpc.peer_server.port()
