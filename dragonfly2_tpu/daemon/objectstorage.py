"""Object-storage gateway: S3-like HTTP service on the daemon.

Reference: client/daemon/objectstorage/objectstorage.go — routes (:148-203),
GET object via P2P stream task (:253), PUT imports to the backend and
replicates to seed peers (putObject :369, importObjectToSeedPeers :629,
modes AsyncWriteBack/WriteBack), bucket CRUD, metadata listing.

GETs ride the P2P fabric: the backend's object_url (gs://, https://, or
file://) becomes the stream-task origin, so every daemon's gateway
produces the same task ID for the same object and pulls from peers before
touching the backend. Replication asks seed peers to prefetch that URL via
the same Peer.TriggerDownloadTask RPC the scheduler uses.

Routes:
  GET    /healthy
  GET    /buckets                              list buckets
  PUT    /buckets/{bucket}                     create bucket
  DELETE /buckets/{bucket}                     delete bucket
  GET    /buckets/{bucket}/metadatas?prefix=   list object metadata
  HEAD   /buckets/{bucket}/objects/{key:.*}    object metadata
  GET    /buckets/{bucket}/objects/{key:.*}    get via P2P (Range ok)
  PUT    /buckets/{bucket}/objects/{key:.*}    put + replicate (mode=...)
  DELETE /buckets/{bucket}/objects/{key:.*}    delete
"""

from __future__ import annotations

import asyncio

from aiohttp import web

from dragonfly2_tpu.daemon.transport import P2PTransport
from dragonfly2_tpu.daemon.upload import _PieceFileResponse
from dragonfly2_tpu.pkg import dflog, idgen, metrics, tracing
from dragonfly2_tpu.pkg import digest as pkgdigest
from dragonfly2_tpu.pkg.errors import DfError
from dragonfly2_tpu.pkg.objectstorage import ObjectStorage, ObjectStorageError

log = dflog.get("daemon.objectstorage")

OBJ_REQUESTS = metrics.counter("objectstorage_requests_total",
                               "Object gateway requests", ("method", "result"))
OBJ_BYTES = metrics.counter("objectstorage_bytes_total",
                            "Object gateway bytes", ("direction",))

# Write-back modes (reference objectstorage.go putObject :369).
MODE_WRITE_BACK = "write_back"            # replicate to seeds synchronously
MODE_ASYNC_WRITE_BACK = "async_write_back"  # fire-and-forget replication


class ObjectStorageService:
    def __init__(self, backend: ObjectStorage, transport, *,
                 get_seed_peers=None, trigger_seed=None):
        """``transport`` is the daemon's P2PTransport (fetch());
        ``get_seed_peers()`` returns [{ip, peer_port}] from dynconfig;
        ``trigger_seed(peer, spec)`` fires Peer.TriggerDownloadTask."""
        self.backend = backend
        self.transport = transport
        self.get_seed_peers = get_seed_peers or (lambda: [])
        self.trigger_seed = trigger_seed
        self._runner: web.AppRunner | None = None
        self._port = 0
        # Strong refs to fire-and-forget replication tasks: the loop keeps
        # only weak refs, so an unreferenced task can be GC'd mid-flight.
        self._background: set[asyncio.Task] = set()

    async def serve(self, host: str, port: int = 0) -> int:
        app = web.Application(client_max_size=4 << 30)
        r = app.router
        r.add_get("/healthy", self._healthy)
        r.add_get("/buckets", self._list_buckets)
        r.add_put("/buckets/{bucket}", self._create_bucket)
        r.add_delete("/buckets/{bucket}", self._delete_bucket)
        r.add_get("/buckets/{bucket}/metadatas", self._list_metadatas)
        r.add_head("/buckets/{bucket}/objects/{key:.*}", self._head_object)
        r.add_get("/buckets/{bucket}/objects/{key:.*}", self._get_object,
                  allow_head=False)
        r.add_put("/buckets/{bucket}/objects/{key:.*}", self._put_object)
        r.add_delete("/buckets/{bucket}/objects/{key:.*}", self._delete_object)
        r.add_post("/buckets/{bucket}/prefetch/{key:.*}", self._prefetch_object)
        self._runner = web.AppRunner(app, access_log=None)
        await self._runner.setup()
        site = web.TCPSite(self._runner, host, port)
        await site.start()
        self._port = site._server.sockets[0].getsockname()[1]
        log.info("object storage gateway up", port=self._port,
                 backend=self.backend.name)
        return self._port

    @property
    def port(self) -> int:
        return self._port

    async def close(self) -> None:
        if self._runner is not None:
            await self._runner.cleanup()
        await self.backend.close()

    # -- buckets -----------------------------------------------------------

    async def _healthy(self, request: web.Request) -> web.Response:
        return web.json_response({"ok": True, "backend": self.backend.name})

    async def _list_buckets(self, request: web.Request) -> web.Response:
        try:
            buckets = await self.backend.list_buckets()
        except ObjectStorageError as e:
            raise web.HTTPBadGateway(text=str(e))
        return web.json_response([{"name": b.name, "created_at": b.created_at}
                                  for b in buckets])

    async def _create_bucket(self, request: web.Request) -> web.Response:
        try:
            await self.backend.create_bucket(request.match_info["bucket"])
        except ObjectStorageError as e:
            raise web.HTTPBadRequest(text=str(e))
        return web.json_response({"ok": True}, status=201)

    async def _delete_bucket(self, request: web.Request) -> web.Response:
        try:
            await self.backend.delete_bucket(request.match_info["bucket"])
        except ObjectStorageError as e:
            raise web.HTTPNotFound(text=str(e))
        return web.json_response({"ok": True})

    async def _list_metadatas(self, request: web.Request) -> web.Response:
        try:
            metas = await self.backend.list_object_metadatas(
                request.match_info["bucket"],
                prefix=request.query.get("prefix", ""),
                marker=request.query.get("marker", ""),
                limit=int(request.query.get("limit", 1000)))
        except ObjectStorageError as e:
            raise web.HTTPNotFound(text=str(e))
        return web.json_response({"metadatas": [{
            "key": m.key, "content_length": m.content_length,
            "content_type": m.content_type, "etag": m.etag,
            "digest": m.digest} for m in metas]})

    # -- objects -----------------------------------------------------------

    async def _head_object(self, request: web.Request) -> web.Response:
        bucket, key = request.match_info["bucket"], request.match_info["key"]
        try:
            meta = await self.backend.get_object_metadata(bucket, key)
        except ObjectStorageError:
            raise web.HTTPNotFound()
        headers = {"Content-Length": str(max(meta.content_length, 0)),
                   "X-Dragonfly-Digest": meta.digest,
                   "ETag": meta.etag or ""}
        if meta.content_type:
            headers["Content-Type"] = meta.content_type
        return web.Response(status=200, headers=headers)

    async def _prefetch_object(self, request: web.Request) -> web.Response:
        """Pull an object into this daemon's stores without streaming it
        back: piece store always; `?device=tpu` additionally lands verified
        pieces in the HBM sink (the north star's dfstore --device=tpu —
        a pod-wide webdataset/checkpoint warm-up never touches the client).
        Whole-object prefetches share task identity with gateway GETs
        (url + tag=bucket), so later GETs are warm hits. A `?range=a-b`
        prefetch warms the RANGED task id instead: it dedups with
        dfget/preheat/device pulls of the same canonical span (gateway
        GETs always resolve the whole-object task, so they are warmed by
        whole-object prefetches, not ranged ones)."""
        bucket, key = request.match_info["bucket"], request.match_info["key"]
        device = request.query.get("device", "")
        if device not in ("", "tpu"):
            raise web.HTTPBadRequest(text=f"unknown device {device!r}")
        from dragonfly2_tpu.daemon.peer.task_manager import FileTaskRequest
        from dragonfly2_tpu.pkg.piece import Range
        from dragonfly2_tpu.proto.common import UrlMeta

        # Sharded warm-up: `?range=a-b` prefetches just that span as its
        # own ranged task (dedups with dfget/preheat/device pulls of the
        # same canonical span; warm whole-object stores serve it locally).
        rng = ""
        if request.query.get("range"):
            try:
                rng = Range.normalize_header(request.query["range"])
            except ValueError as e:
                raise web.HTTPBadRequest(
                    text=f"bad range {request.query['range']!r}: {e}")
        url = self.backend.object_url(bucket, key)
        req = FileTaskRequest(url=url, output="",
                              meta=UrlMeta(tag=bucket, range=rng),
                              device=device)
        if rng:
            req.range = Range.parse_http(rng)

        async def run_prefetch():
            final = None
            async for p in self.transport.task_manager.start_file_task(req):
                final = p
            return final

        # Detached from the request lifetime: a client timeout/disconnect
        # must NOT cancel the download (cancellation invalidates the
        # partially-warmed store — the opposite of what prefetch is for).
        # The shield keeps the task running in _background to completion.
        fut = asyncio.ensure_future(run_prefetch())
        self._background.add(fut)
        fut.add_done_callback(self._background.discard)
        try:
            final = await asyncio.shield(fut)
        except asyncio.CancelledError:
            fut.add_done_callback(
                lambda f: f.exception() if not f.cancelled() else None)
            raise
        except DfError as e:
            OBJ_REQUESTS.labels("PREFETCH", "error").inc()
            raise web.HTTPBadGateway(text=f"prefetch failed: {e}")
        if final is None or final.state != "done":
            OBJ_REQUESTS.labels("PREFETCH", "error").inc()
            err = (final.error or {}) if final is not None else {}
            raise web.HTTPBadGateway(
                text=f"prefetch failed: {err.get('message', 'no result')}")
        OBJ_REQUESTS.labels("PREFETCH", "ok").inc()
        return web.json_response({
            "state": final.state,
            "task_id": final.task_id,
            "content_length": final.content_length,
            "from_reuse": final.from_reuse,
            "from_p2p": final.from_p2p,
            "device_verified": final.device_verified,
        })

    @staticmethod
    def _try_sendfile(attrs: dict, rng, total: int):
        """Fast exit: serve via sendfile (zero Python byte handling)
        instead of the piece iterator whenever the shared
        P2PTransport.sendfile_window predicate (also used by the proxy)
        allows it — a completed store for any window, or an in-progress
        store whose requested range has fully landed. Returns
        (response, byte_count) or (None, 0). The response owns a store pin
        until the send finishes (upload-server discipline)."""
        window = P2PTransport.sendfile_window(attrs, rng, total)
        if window is None:
            return None, 0
        store, offset, count = window
        store.pin()

        def release() -> None:
            # Runs when the send finishes (or aborts): counters record at
            # response completion, matching the streaming path's timing.
            # (Aborted sends still count the window size — FileResponse
            # doesn't expose partial-send byte counts.)
            store.unpin()
            OBJ_BYTES.labels("out").inc(count)
            OBJ_REQUESTS.labels("GET", "ok").inc()

        range_header = None
        if rng is not None:
            range_header = f"bytes={offset}-{offset + count - 1}"
        return (_PieceFileResponse(store.data_path, range_header, release,
                                   content_total=total),
                count)

    async def _get_object_ranged_task(self, request: web.Request,
                                      bucket: str, key: str,
                                      rng_header: str) -> web.StreamResponse:
        """`?ranged_task=1` + Range: serve the span as its own RANGED file
        task instead of a window over the whole-object stream task. Task
        identity includes the canonical range, so (a) a cold read fetches
        ONLY the span's bytes from origin/peers, (b) every host reading
        the same span dedupes on one task, and (c) a warm whole-object
        store satisfies it locally (import_range_from_local_parent). This
        is the dataset plane's sample-read path (dataset/shard_reader.py);
        whole-shard consumers keep the plain GET."""
        from dragonfly2_tpu.daemon.peer.task_manager import FileTaskRequest
        from dragonfly2_tpu.pkg.piece import Range
        from dragonfly2_tpu.proto.common import UrlMeta

        try:
            rng = Range.normalize_header(rng_header)
        except ValueError as e:
            raise web.HTTPBadRequest(text=f"bad range {rng_header!r}: {e}")
        url = self.backend.object_url(bucket, key)
        req = FileTaskRequest(url=url, output="",
                              meta=UrlMeta(tag=bucket, range=rng))
        req.range = Range.parse_http(rng)
        final = None
        try:
            async for p in self.transport.task_manager.start_file_task(req):
                if p.state == "failed":
                    raise DfError.from_wire(p.error or {})
                final = p
        except DfError as e:
            OBJ_REQUESTS.labels("GET", "error").inc()
            raise web.HTTPBadGateway(text=f"ranged task failed: {e}")
        if final is None or final.state != "done":
            OBJ_REQUESTS.labels("GET", "error").inc()
            raise web.HTTPBadGateway(text="ranged task ended without result")
        store = self.transport.task_manager.storage.find_completed_task(
            final.task_id)
        if store is None:
            OBJ_REQUESTS.labels("GET", "error").inc()
            raise web.HTTPBadGateway(text="ranged task store missing")
        # The ranged store's data file IS the span: sendfile it whole.
        count = store.metadata.content_length
        store.pin()

        def release() -> None:
            store.unpin()
            OBJ_BYTES.labels("out").inc(count)
            OBJ_REQUESTS.labels("GET", "ok").inc()

        resp = _PieceFileResponse(store.data_path, None, release)
        resp.headers["X-Dragonfly-Task-Id"] = final.task_id
        resp.headers["X-Dragonfly-From-Reuse"] = \
            "1" if final.from_reuse else "0"
        return resp

    async def _get_object(self, request: web.Request) -> web.StreamResponse:
        """GET via the P2P fabric (reference :253 getObject → stream task)."""
        bucket, key = request.match_info["bucket"], request.match_info["key"]
        # Adopt the caller's trace context (dataset-plane readers and
        # other gateways inject it): the gateway hop joins the task's
        # trace instead of starting a disconnected one.
        tp = request.headers.get(tracing.TRACEPARENT, "")
        with tracing.extract({tracing.TRACEPARENT: tp} if tp else None,
                             "gateway.get_object", bucket=bucket):
            return await self._get_object_inner(request, bucket, key)

    async def _get_object_inner(self, request: web.Request, bucket: str,
                                key: str) -> web.StreamResponse:
        url = self.backend.object_url(bucket, key)
        headers = {"X-Dragonfly-Tag": bucket}
        rng_header = request.headers.get("Range", "")
        if rng_header and request.query.get("ranged_task"):
            return await self._get_object_ranged_task(request, bucket, key,
                                                      rng_header)
        if rng_header:
            headers["Range"] = rng_header
        try:
            attrs, body_iter = await self.transport.fetch(url, headers)
        except (DfError, ValueError) as e:
            OBJ_REQUESTS.labels("GET", "error").inc()
            raise web.HTTPBadGateway(text=f"p2p fetch failed: {e}")
        rng = attrs.get("range")
        total = attrs.get("content_length", -1)
        sendfile_resp, sendfile_count = self._try_sendfile(attrs, rng, total)
        if sendfile_resp is not None:
            await body_iter.aclose()  # unstarted generator: no pin taken yet
            return sendfile_resp
        if rng is not None and total < 0:
            # Ranged GET against an unknown-length origin (chunked source):
            # the range resolved, so the slice is satisfiable — stream it
            # with an unknown-total Content-Range rather than a bogus 416.
            resp = web.StreamResponse(status=206, headers={
                "Content-Range":
                    f"bytes {rng.start}-{rng.start + rng.length - 1}/*"})
        elif rng is not None:
            resp_len = min(rng.length, max(total - rng.start, 0))
            if resp_len <= 0:
                await body_iter.aclose()
                raise web.HTTPRequestRangeNotSatisfiable(
                    headers={"Content-Range": f"bytes */{total}"})
            resp = web.StreamResponse(status=206, headers={
                "Content-Range":
                    f"bytes {rng.start}-{rng.start + resp_len - 1}/{total}",
                "Content-Length": str(resp_len)})
        elif total >= 0:
            resp = web.StreamResponse(status=200,
                                      headers={"Content-Length": str(total)})
        else:
            resp = web.StreamResponse(status=200)  # chunked
        await resp.prepare(request)
        sent = 0
        try:
            async for chunk in body_iter:
                await resp.write(chunk)
                sent += len(chunk)
        finally:
            OBJ_BYTES.labels("out").inc(sent)
        OBJ_REQUESTS.labels("GET", "ok").inc()
        await resp.write_eof()
        return resp

    async def _put_object(self, request: web.Request) -> web.Response:
        """PUT: land in the backend, then replicate to seed peers
        (reference putObject :369 + importObjectToSeedPeers :629). The body
        streams through a spooled temp file (64 MiB in RAM, disk beyond) so
        multi-GB checkpoint shards never occupy daemon memory whole."""
        import tempfile

        bucket, key = request.match_info["bucket"], request.match_info["key"]
        mode = request.query.get("mode", MODE_ASYNC_WRITE_BACK)
        hasher = pkgdigest.new_hasher(pkgdigest.ALGORITHM_SHA256)
        size = 0
        with tempfile.SpooledTemporaryFile(max_size=64 << 20) as spool:
            async for chunk in request.content.iter_chunked(1 << 20):
                hasher.update(chunk)
                spool.write(chunk)
                size += len(chunk)
            spool.seek(0)
            digest = f"{pkgdigest.ALGORITHM_SHA256}:{hasher.hexdigest()}"
            try:
                await self.backend.put_object(
                    bucket, key, spool, digest=digest,
                    content_type=request.content_type or "")
            except ObjectStorageError as e:
                OBJ_REQUESTS.labels("PUT", "error").inc()
                raise web.HTTPBadGateway(text=str(e))
        OBJ_BYTES.labels("in").inc(size)
        OBJ_REQUESTS.labels("PUT", "ok").inc()
        replication = self._replicate_to_seeds(bucket, key, digest)
        if mode == MODE_WRITE_BACK:
            await replication
        else:
            t = asyncio.ensure_future(replication)
            self._background.add(t)
            t.add_done_callback(self._background.discard)
        return web.json_response({"ok": True, "digest": digest}, status=200)

    async def _replicate_to_seeds(self, bucket: str, key: str, digest: str) -> None:
        """Ask every known seed peer to prefetch the object's origin URL —
        the P2P analog of the reference's per-seed import (:629)."""
        if self.trigger_seed is None:
            return
        seeds = list(self.get_seed_peers() or [])
        if not seeds:
            return
        url = self.backend.object_url(bucket, key)
        # Task identity must match what a gateway GET produces
        # (P2PTransport.fetch: UrlMeta(tag=bucket), no digest) or the
        # replicated copies can never serve a GET. The digest still rides
        # the spec for whole-content verification on the seed.
        task_id = idgen.task_id_v1(url, tag=bucket)
        spec = {"task_id": task_id, "url": url, "tag": bucket, "digest": digest}
        results = await asyncio.gather(
            *(self.trigger_seed(s, spec) for s in seeds),
            return_exceptions=True)
        ok = sum(1 for r in results if r is True)
        log.info("object replicated to seeds", bucket=bucket, key=key,
                 ok=ok, total=len(seeds))

    async def _delete_object(self, request: web.Request) -> web.Response:
        bucket, key = request.match_info["bucket"], request.match_info["key"]
        try:
            await self.backend.delete_object(bucket, key)
        except ObjectStorageError as e:
            raise web.HTTPNotFound(text=str(e))
        OBJ_REQUESTS.labels("DELETE", "ok").inc()
        return web.json_response({"ok": True})
