"""Daemon announcer: registers this host with schedulers, keeps alive.

Reference: client/daemon/announcer/announcer.go — builds AnnounceHostRequest
with full host telemetry via gopsutil (:158-300, psutil here), periodic
announce (:103-156), LeaveHost on stop.
"""

from __future__ import annotations

import asyncio
import time

from dragonfly2_tpu.daemon.config import DaemonConfig
from dragonfly2_tpu.pkg import dflog, idgen

log = dflog.get("daemon.announcer")

try:
    import psutil

    HAVE_PSUTIL = True
except ImportError:  # pragma: no cover
    HAVE_PSUTIL = False


class Announcer:
    def __init__(self, config: DaemonConfig, scheduler_client, *,
                 peer_port: int, upload_port: int, interval: float = 30.0,
                 recorder=None):
        self.config = config
        self.scheduler_client = scheduler_client
        self.peer_port = peer_port
        self.upload_port = upload_port
        self.interval = interval
        self.host_id = idgen.host_id(config.host.hostname, peer_port)
        self._task: asyncio.Task | None = None
        # Clock-alignment sampling (pkg/podlens): t0/t1 around each
        # announce on a monotonic-anchored wall clock (an NTP step mid-
        # run cannot skew a sample) plus the daemon-wide chaos/test skew
        # knob; the sample completes when the response's ``sched_wall``
        # echo arrives and SHIPS ON THE NEXT ANNOUNCE (start() announces
        # twice so a fresh daemon aligns immediately).
        self._wall0 = time.time() + config.clock_offset_s
        self._pc0 = time.perf_counter()
        self._pending_clock: dict | None = None
        # Flight recorder to stash the scheduler's scorecard row for this
        # host into (post-mortem bundles embed it).
        self.recorder = recorder

    def _wall_now(self) -> float:
        return self._wall0 + (time.perf_counter() - self._pc0)

    def host_wire(self) -> dict:
        h = self.config.host
        return {
            "id": self.host_id,
            "hostname": h.hostname,
            "ip": h.ip,
            "port": self.peer_port,
            "upload_port": self.upload_port,
            "type": int(self.config.host_type_enum),
            "idc": h.idc,
            "location": h.location,
            "tpu_slice": h.tpu_slice,
            "tpu_worker_index": h.tpu_worker_index,
            "telemetry": self._telemetry(),
        }

    @staticmethod
    def _telemetry() -> dict:
        if not HAVE_PSUTIL:
            return {}
        try:
            mem = psutil.virtual_memory()
            disk = psutil.disk_usage("/")
            return {
                "cpu_percent": psutil.cpu_percent(interval=None),
                "mem_percent": mem.percent,
                "disk_free": disk.free,
            }
        except Exception:
            return {}

    async def start(self) -> None:
        await self.announce_once()
        # Second immediate announce ships the first's round-trip clock
        # sample — a fresh daemon is alignable before its first task
        # finishes, not one announce interval later.
        if self._pending_clock is not None:
            await self.announce_once()
        self._task = asyncio.ensure_future(self._loop())

    async def announce_once(self) -> None:
        body = self.host_wire()
        if self._pending_clock is not None:
            body["clock"] = self._pending_clock
        t0 = self._wall_now()
        try:
            resp = await self.scheduler_client.announce_host(body)
        except Exception as e:
            log.warning("host announce failed", error=str(e))
            return
        t1 = self._wall_now()
        resp = resp if isinstance(resp, dict) else {}
        echo = resp.get("sched_wall")
        if isinstance(echo, (int, float)) and echo > 0:
            self._pending_clock = {"t0": t0, "t1": t1, "echo": float(echo)}
        scorecard = resp.get("scorecard")
        if self.recorder is not None and isinstance(scorecard, dict):
            # The subject host's fleet-wide standing, embedded into any
            # post-mortem bundle dumped from here on.
            self.recorder.scorecard_snapshot = {
                "at_wall": round(t1, 3), **scorecard}

    async def _loop(self) -> None:
        while True:
            await asyncio.sleep(self.interval)
            await self.announce_once()

    async def stop(self) -> None:
        if self._task is not None:
            self._task.cancel()
        try:
            await self.scheduler_client.leave_host(self.host_id)
        except Exception:
            pass
