"""Daemon announcer: registers this host with schedulers, keeps alive.

Reference: client/daemon/announcer/announcer.go — builds AnnounceHostRequest
with full host telemetry via gopsutil (:158-300, psutil here), periodic
announce (:103-156), LeaveHost on stop.
"""

from __future__ import annotations

import asyncio

from dragonfly2_tpu.daemon.config import DaemonConfig
from dragonfly2_tpu.pkg import dflog, idgen

log = dflog.get("daemon.announcer")

try:
    import psutil

    HAVE_PSUTIL = True
except ImportError:  # pragma: no cover
    HAVE_PSUTIL = False


class Announcer:
    def __init__(self, config: DaemonConfig, scheduler_client, *,
                 peer_port: int, upload_port: int, interval: float = 30.0):
        self.config = config
        self.scheduler_client = scheduler_client
        self.peer_port = peer_port
        self.upload_port = upload_port
        self.interval = interval
        self.host_id = idgen.host_id(config.host.hostname, peer_port)
        self._task: asyncio.Task | None = None

    def host_wire(self) -> dict:
        h = self.config.host
        return {
            "id": self.host_id,
            "hostname": h.hostname,
            "ip": h.ip,
            "port": self.peer_port,
            "upload_port": self.upload_port,
            "type": int(self.config.host_type_enum),
            "idc": h.idc,
            "location": h.location,
            "tpu_slice": h.tpu_slice,
            "tpu_worker_index": h.tpu_worker_index,
            "telemetry": self._telemetry(),
        }

    @staticmethod
    def _telemetry() -> dict:
        if not HAVE_PSUTIL:
            return {}
        try:
            mem = psutil.virtual_memory()
            disk = psutil.disk_usage("/")
            return {
                "cpu_percent": psutil.cpu_percent(interval=None),
                "mem_percent": mem.percent,
                "disk_free": disk.free,
            }
        except Exception:
            return {}

    async def start(self) -> None:
        await self.announce_once()
        self._task = asyncio.ensure_future(self._loop())

    async def announce_once(self) -> None:
        try:
            await self.scheduler_client.announce_host(self.host_wire())
        except Exception as e:
            log.warning("host announce failed", error=str(e))

    async def _loop(self) -> None:
        while True:
            await asyncio.sleep(self.interval)
            await self.announce_once()

    async def stop(self) -> None:
        if self._task is not None:
            self._task.cancel()
        try:
            await self.scheduler_client.leave_host(self.host_id)
        except Exception:
            pass
