"""HTTP(S) forward proxy + registry mirror over the P2P fabric.

Reference: client/daemon/proxy/proxy.go — ServeHTTP (:301), CONNECT tunnel
with TLS hijack (:471 handleHTTPS: terminate TLS with a CA-forged leaf
cert so HTTPS registry pulls ride P2P), SNI proxy (proxy_sni.go),
mirrorRegistry (:585), shouldUseDragonfly rules (:662-699), basic auth
(:294), max-concurrency gate (:195) and white-listed ports.

Implementation is a raw asyncio server (not aiohttp) because CONNECT
tunnelling needs the bare socket. GETs that match the rules are served from
stream peer tasks via the transport; everything else passes through.

HTTPS interception: with a ``CertAuthority`` configured, CONNECT tunnels to
matching hosts are answered 200 and the client side is upgraded to TLS
using a leaf certificate forged for the target host; the decrypted requests
then run through the same rule engine, so container-image blob pulls hit
the P2P fabric instead of tunnelling blindly to origin. Hosts outside
``hijack_hosts`` keep the blind relay. A separate SNI listener
(``serve_sni``) accepts direct TLS connections (no CONNECT), routing by
ClientHello SNI — terminate-and-serve when hijacking, peek-and-splice
passthrough otherwise.
"""

from __future__ import annotations

import asyncio
import base64
import re
import ssl as ssl_mod
from urllib.parse import urljoin, urlsplit

import aiohttp

from dragonfly2_tpu.daemon.transport import P2PTransport
from dragonfly2_tpu.pkg import dflog, metrics
from dragonfly2_tpu.pkg.errors import DfError

log = dflog.get("daemon.proxy")

PROXY_REQUESTS = metrics.counter("proxy_requests_total", "Proxy requests", ("via",))
PROXY_BYTES = metrics.counter("proxy_bytes_total", "Proxy bytes served", ("via",))

_HOP_HEADERS = {"connection", "proxy-connection", "keep-alive", "te", "trailer",
                "transfer-encoding", "upgrade", "proxy-authorization"}


def parse_sni(record: bytes) -> str | None:
    """Extract the server_name from a raw TLS ClientHello record
    (RFC 8446 §4.1.2 + RFC 6066 §3). Returns None on anything malformed —
    the caller treats that as 'no SNI'."""
    try:
        if record[0] != 0x16 or record[5] != 0x01:  # handshake / ClientHello
            return None
        i = 9                      # record(5) + handshake type/len(4)
        i += 2 + 32                # client version + random
        i += 1 + record[i]         # session id
        cs_len = int.from_bytes(record[i:i + 2], "big")
        i += 2 + cs_len            # cipher suites
        i += 1 + record[i]         # compression methods
        ext_end = i + 2 + int.from_bytes(record[i:i + 2], "big")
        i += 2
        while i + 4 <= ext_end:
            ext_type = int.from_bytes(record[i:i + 2], "big")
            ext_len = int.from_bytes(record[i + 2:i + 4], "big")
            i += 4
            if ext_type == 0:      # server_name
                # list len(2) + type(1) + name len(2) + name
                name_len = int.from_bytes(record[i + 3:i + 5], "big")
                return record[i + 5:i + 5 + name_len].decode("idna")
            i += ext_len
    except (IndexError, UnicodeError):
        pass
    return None


def _hget(headers: dict[str, str], name: str, default: str = "") -> str:
    """Case-insensitive header lookup (HTTP/2 hops lowercase names)."""
    lname = name.lower()
    for k, v in headers.items():
        if k.lower() == lname:
            return v
    return default


class Proxy:
    def __init__(self, transport: P2PTransport, *, registry_mirror: str = "",
                 basic_auth: tuple[str, str] | None = None,
                 max_concurrency: int = 0,
                 white_list_ports: list[int] | None = None,
                 cert_authority=None,
                 hijack_hosts: list[str] | None = None):
        self.transport = transport
        self.registry_mirror = registry_mirror.rstrip("/")
        self.basic_auth = basic_auth
        self.max_concurrency = max_concurrency
        self.white_list_ports = white_list_ports or []
        # TLS interception: a pkg.certify.CertAuthority. None = blind
        # relay for every CONNECT (round-1 behavior).
        self.ca = cert_authority
        self.hijack_hosts = [re.compile(p) for p in hijack_hosts or []]
        self._inflight = 0
        self._server: asyncio.AbstractServer | None = None
        self._sni_server: asyncio.AbstractServer | None = None
        self._session: aiohttp.ClientSession | None = None
        self._port = 0
        self._sni_port = 0

    def _http(self) -> aiohttp.ClientSession:
        """One shared upstream session: connection reuse across proxied
        requests instead of a handshake per request. Honors the same
        DRAGONFLY_SSL_CA_FILE / DRAGONFLY_SSL_INSECURE trust knobs as the
        back-to-source HTTP client, so re-originated requests inside a
        hijacked tunnel reach private-CA upstreams too."""
        if self._session is None or self._session.closed:
            from dragonfly2_tpu.source.clients.http import HTTPSourceClient

            ssl_ctx = HTTPSourceClient._ssl_config()
            connector = (aiohttp.TCPConnector(ssl=ssl_ctx)
                         if ssl_ctx is not None else None)
            self._session = aiohttp.ClientSession(
                auto_decompress=False, connector=connector)
        return self._session

    async def serve(self, host: str = "127.0.0.1", port: int = 0) -> int:
        self._server = await asyncio.start_server(self._handle_conn, host, port)
        self._port = self._server.sockets[0].getsockname()[1]
        log.info("proxy up", port=self._port,
                 mirror=self.registry_mirror or None)
        return self._port

    @property
    def port(self) -> int:
        return self._port

    async def serve_sni(self, host: str = "127.0.0.1", port: int = 0,
                        *, hijack: bool = False) -> int:
        """SNI listener (reference proxy_sni.go): accepts raw TLS
        connections (no CONNECT) and routes by ClientHello server name.
        hijack=True terminates TLS with a forged cert and serves through
        the rule engine; otherwise the ClientHello is peeked and spliced
        to <sni-host>:443 untouched."""
        if hijack and self.ca is None:
            raise ValueError("SNI hijack requires a cert_authority")

        async def handle(reader, writer):
            await self._handle_sni_conn(reader, writer, hijack)

        self._sni_server = await asyncio.start_server(handle, host, port)
        self._sni_port = self._sni_server.sockets[0].getsockname()[1]
        log.info("sni proxy up", port=self._sni_port, hijack=hijack)
        return self._sni_port

    async def close(self) -> None:
        if self._session is not None and not self._session.closed:
            await self._session.close()
        for server in (self._server, self._sni_server):
            if server is not None:
                server.close()
                await server.wait_closed()
        self._server = self._sni_server = None

    # -- connection handling ----------------------------------------------

    async def _handle_conn(self, reader: asyncio.StreamReader,
                           writer: asyncio.StreamWriter) -> None:
        try:
            await self._request_loop(reader, writer, scheme="http",
                                     tunnel_host="")
        except (ConnectionError, asyncio.IncompleteReadError):
            pass
        except Exception:
            log.error("proxy connection error", exc_info=True)
        finally:
            try:
                writer.close()
                await writer.wait_closed()
            except Exception:
                pass

    async def _request_loop(self, reader: asyncio.StreamReader,
                            writer: asyncio.StreamWriter, *,
                            scheme: str, tunnel_host: str) -> None:
        """Keep-alive request loop. scheme/tunnel_host carry the hijacked-
        tunnel context: inside a TLS-intercepted CONNECT the client speaks
        origin-form requests that resolve against https://tunnel_host."""
        while True:
            request = await self._read_request(reader)
            if request is None:
                break
            method, target, version, headers = request
            if (self.basic_auth and scheme == "http"
                    and not self._check_auth(headers)):
                # Proxy auth rides the outer hop only: inside a hijacked
                # tunnel the client believes it talks to the origin.
                await self._respond(writer, 407, b"proxy auth required",
                                    extra="Proxy-Authenticate: Basic realm=\"dragonfly\"\r\n")
                break
            if self.max_concurrency and self._inflight >= self.max_concurrency:
                # Unread request bodies would desync the keep-alive
                # stream; shed load by closing the connection.
                await self._respond(writer, 503, b"proxy at max concurrency",
                                    extra="Connection: close\r\n")
                break
            self._inflight += 1
            try:
                if method == "CONNECT" and scheme == "http":
                    await self._handle_connect(target, reader, writer)
                    return  # tunnel consumed the connection
                keep_alive = await self._handle_http(
                    method, target, headers, reader, writer,
                    scheme=scheme, tunnel_host=tunnel_host)
                if not keep_alive:
                    break
                if _hget(headers, "Connection").lower() == "close":
                    break  # client asked for single-shot; don't hold EOF
            finally:
                self._inflight -= 1

    @staticmethod
    async def _read_request(reader: asyncio.StreamReader):
        try:
            line = await reader.readline()
        except (ConnectionError, asyncio.LimitOverrunError):
            return None
        if not line or line in (b"\r\n", b"\n"):
            return None
        try:
            method, target, version = line.decode("latin1").strip().split(" ", 2)
        except ValueError:
            return None
        headers: dict[str, str] = {}
        while True:
            hline = await reader.readline()
            if not hline or hline in (b"\r\n", b"\n"):
                break
            k, _, v = hline.decode("latin1").partition(":")
            headers[k.strip()] = v.strip()
        return method.upper(), target, version, headers

    def _check_auth(self, headers: dict[str, str]) -> bool:
        cred = _hget(headers, "Proxy-Authorization")
        if not cred.startswith("Basic "):
            return False
        try:
            user, _, pw = base64.b64decode(cred[6:]).decode().partition(":")
        except Exception:
            return False
        return (user, pw) == self.basic_auth

    # -- CONNECT tunnel (reference handleHTTPS :471) -----------------------

    def _should_hijack(self, host: str) -> bool:
        if self.ca is None:
            return False
        if not self.hijack_hosts:
            return True
        return any(p.search(host) for p in self.hijack_hosts)

    async def _handle_connect(self, target: str, reader: asyncio.StreamReader,
                              writer: asyncio.StreamWriter) -> None:
        host, _, port_s = target.partition(":")
        port = int(port_s or 443)
        if self.white_list_ports and port not in self.white_list_ports:
            await self._respond(writer, 403, b"port not allowed")
            return
        if self._should_hijack(host):
            await self._handle_connect_hijack(host, reader, writer)
            return
        try:
            up_reader, up_writer = await asyncio.open_connection(host, port)
        except OSError as e:
            await self._respond(writer, 502, f"connect failed: {e}".encode())
            return
        writer.write(b"HTTP/1.1 200 Connection established\r\n\r\n")
        await writer.drain()
        PROXY_REQUESTS.labels("tunnel").inc()

        async def relay(src: asyncio.StreamReader, dst: asyncio.StreamWriter):
            try:
                while True:
                    data = await src.read(64 << 10)
                    if not data:
                        break
                    dst.write(data)
                    await dst.drain()
            except (ConnectionError, asyncio.CancelledError):
                pass
            finally:
                try:
                    dst.close()
                except Exception:
                    pass

        await asyncio.gather(relay(reader, up_writer), relay(up_reader, writer))

    async def _handle_connect_hijack(self, host: str,
                                     reader: asyncio.StreamReader,
                                     writer: asyncio.StreamWriter) -> None:
        """TLS interception (reference proxy.go:471 handleHTTPS): answer
        the CONNECT, upgrade the client leg to TLS with a cert forged for
        ``host``, then serve the decrypted requests through the normal
        rule engine — registry blob GETs ride P2P."""
        writer.write(b"HTTP/1.1 200 Connection established\r\n\r\n")
        await writer.drain()
        try:
            await writer.start_tls(self.ca.server_context(host))
        except (ssl_mod.SSLError, ConnectionError, OSError) as e:
            # Client refused our cert (CA not installed) or handshake
            # failure: nothing to salvage, the tunnel is gone.
            log.warning("tls hijack handshake failed", host=host,
                        error=str(e))
            return
        PROXY_REQUESTS.labels("hijack").inc()
        await self._request_loop(reader, writer, scheme="https",
                                 tunnel_host=host)

    # -- SNI proxy (reference proxy_sni.go) --------------------------------

    async def _handle_sni_conn(self, reader: asyncio.StreamReader,
                               writer: asyncio.StreamWriter,
                               hijack: bool) -> None:
        try:
            if hijack:
                # Terminate TLS directly; the right forged cert is picked
                # during the handshake via the SNI callback.
                holder: dict[str, str] = {}
                await writer.start_tls(self._sni_hijack_context(holder))
                host = holder.get("host", "")
                if not host:
                    return
                PROXY_REQUESTS.labels("hijack").inc()
                await self._request_loop(reader, writer, scheme="https",
                                         tunnel_host=host)
                return
            # Passthrough: peek the ClientHello for the server name, then
            # splice the bytes to <sni>:443 untouched.
            hello = await self._read_tls_record(reader)
            host = parse_sni(hello) if hello else None
            if not host:
                return
            try:
                up_reader, up_writer = await asyncio.open_connection(host, 443)
            except OSError as e:
                log.warning("sni upstream connect failed", host=host,
                            error=str(e))
                return
            up_writer.write(hello)
            await up_writer.drain()
            PROXY_REQUESTS.labels("sni").inc()

            async def relay(src, dst):
                try:
                    while True:
                        data = await src.read(64 << 10)
                        if not data:
                            break
                        dst.write(data)
                        await dst.drain()
                except (ConnectionError, asyncio.CancelledError):
                    pass
                finally:
                    try:
                        dst.close()
                    except Exception:
                        pass

            await asyncio.gather(relay(reader, up_writer),
                                 relay(up_reader, writer))
        except (ConnectionError, asyncio.IncompleteReadError,
                ssl_mod.SSLError):
            pass
        except Exception:
            log.error("sni connection error", exc_info=True)
        finally:
            try:
                writer.close()
                await writer.wait_closed()
            except Exception:
                pass

    def _sni_hijack_context(self, holder: dict[str, str]):
        """Server context whose cert is chosen during the handshake from
        the ClientHello SNI (we don't know the target host beforehand)."""
        # Fresh (uncached) cert-bearing context per connection: the
        # sni_callback writes into this connection's holder, so it must
        # not be shared. SNI-less clients get the localhost cert (they'll
        # fail hostname checks anyway).
        base = self.ca.fresh_server_context("localhost")

        def on_sni(sock, server_name, _ctx):
            if server_name:
                holder["host"] = server_name
                sock.context = self.ca.server_context(server_name)
            return None

        base.sni_callback = on_sni
        return base

    @staticmethod
    async def _read_tls_record(reader: asyncio.StreamReader) -> bytes | None:
        """Read exactly one TLS record (the ClientHello) off the wire."""
        try:
            header = await reader.readexactly(5)
        except asyncio.IncompleteReadError:
            return None
        if header[0] != 0x16:  # not a TLS handshake record
            return None
        length = int.from_bytes(header[3:5], "big")
        if length > 1 << 16:
            return None
        try:
            body = await reader.readexactly(length)
        except asyncio.IncompleteReadError:
            return None
        return header + body

    # -- plain HTTP --------------------------------------------------------

    def _resolve_url(self, target: str, headers: dict[str, str],
                     scheme: str = "http", tunnel_host: str = "") -> str:
        if target.startswith("http://") or target.startswith("https://"):
            return target                      # classic forward proxy
        if tunnel_host:
            # Inside a hijacked CONNECT/SNI tunnel: origin-form requests
            # resolve against the tunnelled host (the Host header should
            # match, but the CONNECT target is what the client asked for).
            host = _hget(headers, "Host", tunnel_host)
            return f"{scheme}://{host}{target}"
        if self.registry_mirror:
            # Mirror mode (reference mirrorRegistry :585): we ARE the
            # registry host; rebase the origin-form path onto the remote.
            return urljoin(self.registry_mirror + "/", target.lstrip("/"))
        host = _hget(headers, "Host")
        return f"{scheme}://{host}{target}"

    async def _handle_http(self, method: str, target: str,
                           headers: dict[str, str],
                           reader: asyncio.StreamReader,
                           writer: asyncio.StreamWriter, *,
                           scheme: str = "http",
                           tunnel_host: str = "") -> bool:
        url = self._resolve_url(target, headers, scheme, tunnel_host)
        fwd_headers = {k: v for k, v in headers.items()
                       if k.lower() not in _HOP_HEADERS and k.lower() != "host"}
        body = b""
        length = int(_hget(headers, "Content-Length", "0") or 0)
        if length:
            body = await reader.readexactly(length)

        if self.transport.should_use_p2p(method, url, fwd_headers):
            fetched = None
            try:
                # Pre-stream failures (bad range, task setup) fall back to
                # direct; once headers are written there is no falling back —
                # a mid-stream error severs the connection instead.
                fetched = await self.transport.fetch(url, fwd_headers)
                attrs, body_iter = fetched
                if attrs.get("range") is not None and attrs["content_length"] < 0:
                    # Ranged request against an unknown-length origin: a
                    # correct 206 needs the total — punt to direct.
                    await body_iter.aclose()
                    fetched = None
            except (DfError, ValueError) as e:
                log.warning("p2p fetch failed, falling back to direct",
                            url=url, error=str(e))
            if fetched is not None:
                return await self._serve_p2p(fetched, writer)
        return await self._serve_direct(method, url, fwd_headers, body, writer)

    async def _serve_p2p(self, fetched, writer: asyncio.StreamWriter) -> bool:
        attrs, body_iter = fetched
        rng = attrs.get("range")      # open-ended ranges arrive resolved
        total = attrs.get("content_length", -1)
        if rng is not None:
            status = 206
            resp_len = min(rng.length, max(total - rng.start, 0))
            if resp_len <= 0:
                # Range at/past EOF: RFC 9110 §15.5.17 — 416 with the
                # unsatisfied-range form, never a degenerate Content-Range.
                await body_iter.aclose()
                await Proxy._respond(
                    writer, 416, b"range not satisfiable",
                    extra=f"Content-Range: bytes */{total}\r\n")
                PROXY_REQUESTS.labels("p2p").inc()
                return True
            extra = (f"Content-Range: bytes {rng.start}-"
                     f"{rng.start + resp_len - 1}/{total}\r\n")
        else:
            status = 200
            resp_len = total
            extra = ""
        # Warm fast path: a completed local store serves via
        # loop.sendfile straight off the page cache (fallback=True keeps
        # TLS-hijacked tunnels working through the chunked-copy fallback).
        window = P2PTransport.sendfile_window(attrs, rng, total)
        if window is not None:
            store, offset, count = window
            # Pin BEFORE any await: the aclose suspension would otherwise
            # open a window for storage GC to reclaim the unpinned store.
            store.pin()
            await body_iter.aclose()  # unstarted generator: holds no pin
            try:
                writer.write(
                    (f"HTTP/1.1 {status} OK\r\n{extra}"
                     f"Content-Length: {count}\r\n\r\n").encode())
                await writer.drain()
                with open(store.data_path, "rb") as f:
                    await asyncio.get_running_loop().sendfile(
                        writer.transport, f, offset, count, fallback=True)
            finally:
                store.unpin()
            PROXY_REQUESTS.labels("p2p").inc()
            PROXY_BYTES.labels("p2p").inc(count)
            return True
        sent = await self._write_body(writer, status, resp_len, extra, body_iter)
        PROXY_REQUESTS.labels("p2p").inc()
        PROXY_BYTES.labels("p2p").inc(sent)
        return True

    async def _serve_direct(self, method: str, url: str, headers: dict[str, str],
                            body: bytes, writer: asyncio.StreamWriter) -> bool:
        """Pass-through (reference proxy directHandler / mirror reverse
        proxy for non-GET and rule-excluded traffic)."""
        try:
            async with self._http().request(method, url, headers=headers,
                                            data=body or None,
                                            allow_redirects=False) as resp:
                hdrs = "".join(
                    f"{k}: {v}\r\n" for k, v in resp.headers.items()
                    if k.lower() not in _HOP_HEADERS
                    and k.lower() != "content-length")
                length = resp.content_length
                bodiless = (method == "HEAD" or resp.status in (204, 304)
                            or 100 <= resp.status < 200)
                if bodiless:
                    # Relay the upstream Content-Length verbatim (HEAD
                    # semantics) but send no body bytes.
                    head = f"HTTP/1.1 {resp.status} X\r\n{hdrs}"
                    if length is not None:
                        head += f"Content-Length: {length}\r\n"
                    writer.write(head.encode() + b"\r\n")
                    await writer.drain()
                    PROXY_REQUESTS.labels("direct").inc()
                    return True

                async def chunks():
                    async for chunk in resp.content.iter_chunked(256 << 10):
                        yield chunk

                sent = await self._write_body(
                    writer, resp.status,
                    length if length is not None else -1, hdrs, chunks())
                PROXY_REQUESTS.labels("direct").inc()
                PROXY_BYTES.labels("direct").inc(sent)
                return True
        except aiohttp.ClientError as e:
            await self._respond(writer, 502, f"upstream error: {e}".encode())
            return False

    # -- response writing --------------------------------------------------

    @staticmethod
    async def _respond(writer: asyncio.StreamWriter, status: int, body: bytes,
                       extra: str = "") -> None:
        writer.write((f"HTTP/1.1 {status} X\r\n{extra}"
                      f"Content-Length: {len(body)}\r\n\r\n").encode() + body)
        await writer.drain()

    @staticmethod
    async def _write_body(writer: asyncio.StreamWriter, status: int,
                          content_length: int, extra_headers: str,
                          body_iter) -> int:
        """Known length -> raw body; unknown -> chunked transfer."""
        chunked = content_length < 0
        head = f"HTTP/1.1 {status} OK\r\n{extra_headers}"
        if chunked:
            head += "Transfer-Encoding: chunked\r\n\r\n"
        else:
            head += f"Content-Length: {content_length}\r\n\r\n"
        writer.write(head.encode())
        sent = 0
        async for chunk in body_iter:
            if not chunk:
                continue
            if chunked:
                writer.write(f"{len(chunk):x}\r\n".encode() + chunk + b"\r\n")
            else:
                writer.write(chunk)
            sent += len(chunk)
            await writer.drain()
        if chunked:
            writer.write(b"0\r\n\r\n")
        await writer.drain()
        return sent
