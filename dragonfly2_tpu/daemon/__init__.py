"""The data-plane peer daemon (reference: client/daemon)."""
