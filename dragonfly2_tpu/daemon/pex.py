"""PEX: gossip peer exchange among daemons — peers find each other without
the scheduler.

Reference: client/daemon/pex/ — hashicorp/memberlist gossip cluster
(peer_exchange.go:114 NewPeerExchange), member manager, per-peer task
possession broadcast, reconcile loops. Here the memberlist role is a
SWIM-lite UDP gossip: periodic pings to random members piggyback the full
membership view and each node's task-possession list (versioned, so stale
gossip never regresses fresher state). Task payloads still ride the normal
HTTP upload path; PEX only answers "who has task X".

Wire (msgpack over UDP):
  {"t": "ping"|"ack"|"join"|"join_ack",
   "from": {node_id, ip, pex_port, peer_port, upload_port, incarnation},
   "members": [member...],                 # piggybacked view
   "tasks": {node_id: {"v": version, "ids": [task_id...]}}}
"""

from __future__ import annotations

import asyncio
import hashlib
import hmac as hmac_mod
import random
import time
import uuid
from dataclasses import dataclass, field

import msgpack

from dragonfly2_tpu.pkg import dflog

log = dflog.get("daemon.pex")

GOSSIP_INTERVAL = 1.0
SUSPECT_AFTER = 5.0     # no direct/indirect news → suspect
DEAD_AFTER = 15.0       # suspect this long → removed
MAX_DATAGRAM = 60_000


@dataclass
class Member:
    node_id: str
    ip: str
    pex_port: int
    peer_port: int = 0
    upload_port: int = 0
    incarnation: int = 0
    # Monotone per-node counter bumped every gossip round; liveness flows
    # transitively: ANY message carrying a higher heartbeat proves the node
    # was alive recently, so big clusters don't need direct contact pairs
    # (the role memberlist's suspicion protocol plays in the reference).
    heartbeat: int = 0
    last_seen: float = field(default_factory=time.monotonic)

    def to_wire(self) -> dict:
        return {"node_id": self.node_id, "ip": self.ip,
                "pex_port": self.pex_port, "peer_port": self.peer_port,
                "upload_port": self.upload_port,
                "incarnation": self.incarnation,
                "heartbeat": self.heartbeat}

    @classmethod
    def from_wire(cls, d: dict) -> "Member":
        return cls(node_id=d["node_id"], ip=d["ip"], pex_port=d["pex_port"],
                   peer_port=d.get("peer_port", 0),
                   upload_port=d.get("upload_port", 0),
                   incarnation=d.get("incarnation", 0),
                   heartbeat=d.get("heartbeat", 0))


class _Proto(asyncio.DatagramProtocol):
    def __init__(self, pex: "PeerExchange"):
        self.pex = pex

    def datagram_received(self, data: bytes, addr) -> None:
        data = self.pex._authenticate(data)
        if data is None:
            return
        try:
            msg = msgpack.unpackb(data, raw=False)
        except Exception:
            return
        self.pex._on_message(msg, addr)


class PeerExchange:
    """One gossip endpoint per daemon."""

    def __init__(self, *, ip: str, peer_port: int = 0, upload_port: int = 0,
                 node_id: str = "", gossip_interval: float = GOSSIP_INTERVAL,
                 secret: str | bytes = ""):
        self.node_id = node_id or uuid.uuid4().hex[:16]
        self.ip = ip
        self.peer_port = peer_port
        self.upload_port = upload_port
        self.gossip_interval = gossip_interval
        self.secret = (secret.encode() if isinstance(secret, str) else
                       bytes(secret))
        self.incarnation = int(time.time())
        self.heartbeat = 0
        self._seeds: list[tuple[str, int]] = []
        self.members: dict[str, Member] = {}
        # node_id → (version, set(task_ids)); own entry lives here too.
        self._possession: dict[str, tuple[int, set[str]]] = {
            self.node_id: (0, set())}
        self._transport: asyncio.DatagramTransport | None = None
        self._loop_task: asyncio.Task | None = None
        self._port = 0

    # -- lifecycle ---------------------------------------------------------

    async def start(self, port: int = 0, seeds: list[str] | None = None) -> int:
        loop = asyncio.get_running_loop()
        self._transport, _ = await loop.create_datagram_endpoint(
            lambda: _Proto(self), local_addr=(self.ip, port))
        self._port = self._transport.get_extra_info("sockname")[1]
        self._seeds = []
        for seed in seeds or []:
            host, sep, p = seed.rpartition(":")
            if not sep or not host or not p.isdigit():
                log.warning("ignoring malformed pex seed (want host:port)",
                            seed=seed)
                continue
            self._seeds.append((host, int(p)))
        for addr in self._seeds:
            self._send({"t": "join", **self._envelope()}, addr)
        self._loop_task = asyncio.create_task(self._gossip_loop())
        log.info("pex up", node=self.node_id, port=self._port,
                 seeds=len(seeds or []))
        return self._port

    async def stop(self) -> None:
        if self._loop_task is not None:
            self._loop_task.cancel()
            self._loop_task = None
        if self._transport is not None:
            self._transport.close()
            self._transport = None

    @property
    def port(self) -> int:
        return self._port

    # -- possession API (reference peer_pool.go) ---------------------------

    def add_task(self, task_id: str) -> None:
        version, ids = self._possession[self.node_id]
        if task_id not in ids:
            ids.add(task_id)
            self._possession[self.node_id] = (version + 1, ids)

    def remove_task(self, task_id: str) -> None:
        version, ids = self._possession[self.node_id]
        if task_id in ids:
            ids.discard(task_id)
            self._possession[self.node_id] = (version + 1, ids)

    def find_holders(self, task_id: str) -> list[Member]:
        """Live members that gossiped possession of ``task_id``."""
        out = []
        for node_id, (_, ids) in self._possession.items():
            if node_id == self.node_id or task_id not in ids:
                continue
            m = self.members.get(node_id)
            if m is not None:
                out.append(m)
        return out

    def alive_members(self) -> list[Member]:
        return list(self.members.values())

    # -- gossip ------------------------------------------------------------

    # Possession payload budget per datagram: a ~70 B/task-id estimate
    # under the 60 KB datagram cap, leaving room for membership.
    _TASK_BUDGET = 40_000
    _TASK_ID_COST = 70

    def _envelope(self) -> dict:
        me = Member(self.node_id, self.ip, self._port, self.peer_port,
                    self.upload_port, self.incarnation, self.heartbeat)
        # Possession rides in randomized, budget-bounded subsets: every
        # round carries different nodes' entries, so large clusters converge
        # over a few rounds instead of silently dropping the payload.
        tasks: dict[str, dict] = {}
        budget = self._TASK_BUDGET
        entries = list(self._possession.items())
        random.shuffle(entries)
        # Own entry first — it is the one only we can originate.
        entries.sort(key=lambda kv: kv[0] != self.node_id)
        for nid, (v, ids) in entries:
            cost = self._TASK_ID_COST * max(1, len(ids))
            if cost > budget:
                continue
            budget -= cost
            tasks[nid] = {"v": v, "ids": list(ids)}
        return {"from": me.to_wire(),
                "members": [m.to_wire() for m in self.members.values()]
                + [me.to_wire()],
                "tasks": tasks}

    # Gossip authentication: with a shared secret configured, every
    # datagram is MAC'd (sha256 HMAC, 16-byte tag) over a wall-clock
    # timestamp plus the payload, and unauthenticated, forged, or stale
    # packets are dropped on receipt — membership and possession state can
    # then only be injected by secret holders, and a captured datagram
    # cannot be replayed outside the freshness window to resurrect departed
    # peers or deleted task announcements.
    _MAC_LEN = 16
    _TS_LEN = 8
    _FRESHNESS_S = 60.0

    def _seal(self, data: bytes) -> bytes:
        if not self.secret:
            return data
        ts = int(time.time() * 1000).to_bytes(self._TS_LEN, "big")
        mac = hmac_mod.new(self.secret, ts + data, hashlib.sha256).digest()
        return mac[: self._MAC_LEN] + ts + data

    def _authenticate(self, data: bytes) -> bytes | None:
        if not self.secret:
            return data
        if len(data) <= self._MAC_LEN + self._TS_LEN:
            return None
        mac = data[: self._MAC_LEN]
        ts_bytes = data[self._MAC_LEN: self._MAC_LEN + self._TS_LEN]
        payload = data[self._MAC_LEN + self._TS_LEN:]
        want = hmac_mod.new(self.secret, ts_bytes + payload,
                            hashlib.sha256).digest()[: self._MAC_LEN]
        if not hmac_mod.compare_digest(mac, want):
            return None
        ts = int.from_bytes(ts_bytes, "big") / 1000.0
        if abs(time.time() - ts) > self._FRESHNESS_S:
            return None
        return payload

    def _send(self, msg: dict, addr: tuple[str, int]) -> None:
        if self._transport is None:
            return
        data = msgpack.packb(msg, use_bin_type=True)
        if len(data) > MAX_DATAGRAM:
            # Membership alone overflowed (very large cluster): ship a
            # random member subset; convergence is probabilistic per round.
            slim = dict(msg)
            slim["tasks"] = {}
            members = msg.get("members") or []
            random.shuffle(members)
            slim["members"] = members[:200]
            data = msgpack.packb(slim, use_bin_type=True)
        try:
            self._transport.sendto(self._seal(data), addr)
        except OSError:
            pass

    async def _gossip_loop(self) -> None:
        while True:
            await asyncio.sleep(self.gossip_interval)
            self.heartbeat += 1
            self._expire()
            targets = list(self.members.values())
            if not targets:
                # Isolated (lost join datagram, seeds down): keep knocking
                # on the seed doors — memberlist retries joins too.
                for addr in self._seeds:
                    self._send({"t": "join", **self._envelope()}, addr)
                continue
            for m in random.sample(targets, min(3, len(targets))):
                self._send({"t": "ping", **self._envelope()}, (m.ip, m.pex_port))

    def _expire(self) -> None:
        now = time.monotonic()
        dead = [nid for nid, m in self.members.items()
                if now - m.last_seen > DEAD_AFTER]
        for nid in dead:
            self.members.pop(nid, None)
            self._possession.pop(nid, None)
            log.info("pex member dead", node=nid)

    def _merge(self, msg: dict, sender_addr) -> None:
        sender = Member.from_wire(msg["from"])
        if sender.node_id != self.node_id:
            existing = self.members.get(sender.node_id)
            if existing is None or sender.incarnation >= existing.incarnation:
                sender.last_seen = time.monotonic()
                sender.heartbeat = max(sender.heartbeat,
                                       existing.heartbeat if existing else 0)
                self.members[sender.node_id] = sender
        for w in msg.get("members") or []:
            m = Member.from_wire(w)
            if m.node_id == self.node_id:
                continue
            existing = self.members.get(m.node_id)
            if existing is None:
                # Learned indirectly: not yet "seen"; give it a grace window.
                m.last_seen = time.monotonic() - SUSPECT_AFTER
                self.members[m.node_id] = m
            elif (m.incarnation > existing.incarnation
                  or m.heartbeat > existing.heartbeat):
                # Fresher news (restart or newer heartbeat) proves recent
                # life even without direct contact — transitive liveness.
                m.last_seen = time.monotonic()
                self.members[m.node_id] = m
        for nid, entry in (msg.get("tasks") or {}).items():
            if nid == self.node_id:
                continue  # nobody else is authoritative for our tasks
            version = entry.get("v", 0)
            current = self._possession.get(nid)
            if current is None or version > current[0]:
                self._possession[nid] = (version, set(entry.get("ids") or []))

    def _on_message(self, msg: dict, addr) -> None:
        t = msg.get("t")
        if t not in ("ping", "ack", "join", "join_ack") or "from" not in msg:
            return
        self._merge(msg, addr)
        if t == "ping":
            sender = msg["from"]
            self._send({"t": "ack", **self._envelope()},
                       (sender["ip"], sender["pex_port"]))
        elif t == "join":
            sender = msg["from"]
            self._send({"t": "join_ack", **self._envelope()},
                       (sender["ip"], sender["pex_port"]))
