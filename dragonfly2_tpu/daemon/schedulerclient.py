"""Daemon-side scheduler client.

Reference: pkg/rpc/scheduler/client — consistent-hash pick of a scheduler
per task (pkg/balancer/consistent_hashing.go) + the AnnouncePeer stream
wrapper the conductor drives.
"""

from __future__ import annotations

from dragonfly2_tpu.pkg import dflog
from dragonfly2_tpu.pkg.errors import Code, DfError
from dragonfly2_tpu.pkg.types import NetAddr
from dragonfly2_tpu.rpc import Client, ClientStream
from dragonfly2_tpu.rpc.balancer import HashRing

log = dflog.get("daemon.schedulerclient")


class SchedulerClient:
    def __init__(self, addrs: list[str]):
        if not addrs:
            raise DfError(Code.BadRequest, "no scheduler addresses")
        self._ring = HashRing(addrs)
        self._clients: dict[str, Client] = {}

    def _client_for(self, task_id: str) -> Client:
        return self._client_for_addr(self._ring.pick(task_id))

    async def _routed_call(self, task_id: str, method: str, body: dict,
                           timeout: float, idempotent: bool = False):
        """Unary call with the same clockwise ring failover as the
        announce stream: connection-level failures try the next member;
        the OWNING member's error is what surfaces if all fail (it is the
        one operators need to diagnose).

        Failover is OPT-IN per method (``idempotent=True``): a
        state-bearing call (e.g. the persistent-cache family, whose
        Started/Finished pair must land on the member holding the task
        FSM) must NOT fail over — the substitute member would give an
        authoritative-looking "not found" where the caller needs a
        retryable connection error (advisor round 3)."""
        members = (self._ring.pick_n(task_id, len(self._ring.members()))
                   if idempotent else self._ring.pick_n(task_id, 1))
        first: DfError | None = None
        for i, addr in enumerate(members):
            try:
                return await self._client_for_addr(addr).call(
                    method, body, timeout=timeout)
            except DfError as e:
                if first is None:
                    first = e
                if e.code != Code.ClientConnectionError:
                    raise  # a scheduler ANSWERED: its verdict stands
                if i + 1 < len(members):
                    log.warning("scheduler unreachable, trying next ring "
                                "member", addr=addr, method=method,
                                error=e.message)
        raise first if first is not None else DfError(
            Code.SchedError, "no scheduler addresses")

    def update_addrs(self, addrs: list[str]) -> None:
        """Dynconfig observer: rebuild the hash ring when the manager's
        scheduler set changes (reference pkg/resolver/scheduler_resolver.go).
        Clients for removed schedulers are closed, not leaked."""
        if not addrs or set(addrs) == set(self._ring.members()):
            return
        log.info("scheduler set changed", addrs=addrs)
        self._ring = HashRing(addrs)
        stale = [a for a in self._clients if a not in set(addrs)]
        for addr in stale:
            cli = self._clients.pop(addr)
            try:
                import asyncio

                asyncio.get_running_loop().create_task(cli.close())
            except RuntimeError:  # no loop: close() at daemon stop handled it
                pass

    async def open_announce_stream(self, open_body: dict) -> ClientStream:
        """Open the AnnouncePeer stream on the ring member owning this
        task, failing over clockwise to the other members when one is
        unreachable (a dead scheduler must not push its ~1/N of tasks to
        origin while healthy schedulers sit idle; dynconfig eventually
        drops the dead member from the ring)."""
        task_id = open_body["task_id"]
        members = self._ring.pick_n(task_id, len(self._ring.members()))
        first: DfError | None = None
        for i, addr in enumerate(members):
            try:
                cli = self._client_for_addr(addr)
                return await cli.open_stream("Scheduler.AnnouncePeer",
                                             open_body)
            except DfError as e:
                if first is None:
                    first = e
                if i + 1 < len(members):
                    log.warning("scheduler unreachable, trying next ring "
                                "member", addr=addr, error=e.message)
        if first is not None:
            raise first
        raise DfError(Code.SchedError, "no scheduler addresses")

    async def announce_host(self, host_wire: dict) -> "dict | None":
        # Host announcements go to every scheduler (each keeps its own
        # view). Returns the first successful response — it carries the
        # scheduler's ``sched_wall`` clock echo + this host's scorecard
        # row (announcer feeds the clock aligner / post-mortem bundles;
        # with multiple ring members the first member's clock anchors).
        first: "dict | None" = None
        for addr in self._ring.members():
            try:
                resp = await self._client_for_addr(addr).call(
                    "Scheduler.AnnounceHost", host_wire, timeout=10.0)
                if first is None and isinstance(resp, dict):
                    first = resp
            except DfError as e:
                log.warning("announce host failed", addr=addr, error=e.message)
        return first

    async def unary(self, task_id: str, method: str, body: dict,
                    timeout: float = 10.0, idempotent: bool = False):
        """Unary call routed by task id through the consistent-hash ring
        (public surface for call families without a dedicated wrapper,
        e.g. the persistent cache RPCs). Ring failover only when the
        caller declares the method ``idempotent`` — the safe default for
        state-bearing methods is the owning member's error, retryable."""
        return await self._routed_call(task_id, method, body, timeout,
                                       idempotent=idempotent)

    async def announce_task(self, body: dict) -> None:
        """Advertise a locally-complete task (dfcache import) — reference
        AnnounceTask, service_v1.go:331. Idempotent registration: safe to
        land on a failover member."""
        await self._routed_call(body.get("task_id", ""),
                                "Scheduler.AnnounceTask", body, 10.0,
                                idempotent=True)

    async def leave_host(self, host_id: str) -> None:
        for addr in self._ring.members():
            try:
                await self._client_for_addr(addr).call("Scheduler.LeaveHost", {"id": host_id},
                                                       timeout=5.0)
            except DfError:
                pass

    def _client_for_addr(self, addr: str) -> Client:
        cli = self._clients.get(addr)
        if cli is None:
            host, _, port = addr.rpartition(":")
            cli = Client(NetAddr.tcp(host, int(port)))
            self._clients[addr] = cli
        return cli

    async def close(self) -> None:
        for cli in self._clients.values():
            await cli.close()
        self._clients.clear()
