"""Daemon-side scheduler client.

Reference: pkg/rpc/scheduler/client — consistent-hash pick of a scheduler
per task (pkg/balancer/consistent_hashing.go) + the AnnouncePeer stream
wrapper the conductor drives.
"""

from __future__ import annotations

from dragonfly2_tpu.pkg import dflog, metrics
from dragonfly2_tpu.pkg.errors import Code, DfError
from dragonfly2_tpu.pkg.types import NetAddr
from dragonfly2_tpu.rpc import Client, ClientStream
from dragonfly2_tpu.rpc.balancer import HashRing

log = dflog.get("daemon.schedulerclient")

FAILOVER_COUNT = metrics.counter(
    "peer_scheduler_failover_total",
    "Announce-stream opens by ring outcome: owner (the consistent-hash "
    "owner answered), failover (a clockwise substitute answered while "
    "the owner was unreachable), exhausted (no ring member reachable)",
    ("result",))

# --------------------------------------------------------------------- #
# RPC classification — THE table (satellite of ISSUE 9).
#
# Every scheduler RPC this daemon speaks must have a row here; the guard
# test (tests/test_scheduler_ha.py) greps the daemon/client sources for
# quoted Scheduler.<Method> literals and fails on any name missing from
# the table — a silently misclassified RPC is a failover correctness bug
# (ring failover of a state-bearing call turns a retryable connection
# error into an authoritative-looking "not found" from a member that
# never owned the task).
#
#   stream         AnnouncePeer: ring-ordered open with clockwise
#                  failover; recovery re-registers with resume state and
#                  re-reports idempotently, so ANY member can adopt it.
#   idempotent     safe to land on any ring member — registration-shaped
#                  or read-only; ``unary()`` fails over on connection
#                  errors.
#   state_bearing  must land on the member holding the task FSM; NO ring
#                  failover — the owning member's (retryable) connection
#                  error is the correct surface.
#   fanout         sent to every ring member (each keeps its own view).
# --------------------------------------------------------------------- #

STREAM = "stream"
IDEMPOTENT = "idempotent"
STATE_BEARING = "state_bearing"
FANOUT = "fanout"

RPC_TABLE: dict[str, str] = {
    "Scheduler.AnnouncePeer": STREAM,
    "Scheduler.AnnounceHost": FANOUT,
    "Scheduler.LeaveHost": FANOUT,
    "Scheduler.AnnounceTask": IDEMPOTENT,
    "Scheduler.LeavePeer": IDEMPOTENT,
    "Scheduler.StatTask": IDEMPOTENT,
    "Scheduler.StatPeer": IDEMPOTENT,
    "Scheduler.PodTimeline": IDEMPOTENT,
    "Scheduler.UploadPersistentCacheTaskStarted": STATE_BEARING,
    "Scheduler.UploadPersistentCacheTaskFinished": STATE_BEARING,
    "Scheduler.UploadPersistentCacheTaskFailed": STATE_BEARING,
    "Scheduler.StatPersistentCacheTask": STATE_BEARING,
    "Scheduler.ListPersistentCacheTasks": STATE_BEARING,
    "Scheduler.DeletePersistentCacheTask": STATE_BEARING,
}


class SchedulerClient:
    def __init__(self, addrs: list[str]):
        if not addrs:
            raise DfError(Code.BadRequest, "no scheduler addresses")
        self._ring = HashRing(addrs)
        self._clients: dict[str, Client] = {}
        # Ring-rebuild observers: task_id → callback(new_owner_addr),
        # fired when a dynconfig scheduler-set change moves the task's
        # ownership away from the member its announce stream currently
        # sits on (conductor re-homes gracefully — satellite of ISSUE 9).
        self._watchers: dict[str, object] = {}
        self._stream_addrs: dict[str, str] = {}

    def _client_for(self, task_id: str) -> Client:
        return self._client_for_addr(self._ring.pick(task_id))

    async def _routed_call(self, task_id: str, method: str, body: dict,
                           timeout: float, idempotent: bool = False):
        """Unary call with the same clockwise ring failover as the
        announce stream: connection-level failures try the next member;
        the OWNING member's error is what surfaces if all fail (it is the
        one operators need to diagnose).

        Failover is OPT-IN per method (``idempotent=True``, resolved from
        RPC_TABLE by ``unary``): a state-bearing call (e.g. the
        persistent-cache family, whose Started/Finished pair must land on
        the member holding the task FSM) must NOT fail over — the
        substitute member would give an authoritative-looking "not found"
        where the caller needs a retryable connection error (advisor
        round 3)."""
        members = (self._ring.pick_n(task_id, len(self._ring.members()))
                   if idempotent else self._ring.pick_n(task_id, 1))
        first: DfError | None = None
        for i, addr in enumerate(members):
            try:
                return await self._client_for_addr(addr).call(
                    method, body, timeout=timeout)
            except DfError as e:
                if first is None:
                    first = e
                if e.code != Code.ClientConnectionError:
                    raise  # a scheduler ANSWERED: its verdict stands
                if i + 1 < len(members):
                    log.warning("scheduler unreachable, trying next ring "
                                "member", addr=addr, method=method,
                                error=e.message)
        raise first if first is not None else DfError(
            Code.SchedError, "no scheduler addresses")

    def update_addrs(self, addrs: list[str]) -> None:
        """Dynconfig observer: rebuild the hash ring when the manager's
        scheduler set changes (reference pkg/resolver/scheduler_resolver.go).
        Clients for removed schedulers are closed, not leaked; announce
        streams sitting on a still-alive but NO-LONGER-OWNING member get
        their conductor's ring-change callback so they can drain and
        re-home instead of riding a stale shard."""
        if not addrs or set(addrs) == set(self._ring.members()):
            return
        log.info("scheduler set changed", addrs=addrs)
        self._ring = HashRing(addrs)
        stale = [a for a in self._clients if a not in set(addrs)]
        for addr in stale:
            cli = self._clients.pop(addr)
            try:
                import asyncio

                asyncio.get_running_loop().create_task(cli.close())
            except RuntimeError:  # no loop: close() at daemon stop handled it
                pass
        for task_id, cb in list(self._watchers.items()):
            owner = self._ring.pick(task_id)
            current = self._stream_addrs.get(task_id)
            if owner and current and owner != current:
                try:
                    cb(owner)
                except Exception:
                    log.warning("ring-change callback failed",
                                task=task_id[:16], exc_info=True)

    # -- ring-rebuild observation (conductor re-homing) --------------------

    def watch_ring(self, task_id: str, cb) -> None:
        """Register ``cb(new_owner_addr)`` to fire when a ring rebuild
        moves ``task_id``'s ownership off the member its announce stream
        was opened on."""
        self._watchers[task_id] = cb

    def unwatch_ring(self, task_id: str) -> None:
        self._watchers.pop(task_id, None)
        self._stream_addrs.pop(task_id, None)

    def stream_addr(self, task_id: str) -> str:
        """The ring member the task's announce stream last opened on."""
        return self._stream_addrs.get(task_id, "")

    async def open_announce_stream(self, open_body: dict) -> ClientStream:
        """Open the AnnouncePeer stream on the ring member owning this
        task, failing over clockwise to the other members when one is
        unreachable (a dead scheduler must not push its ~1/N of tasks to
        origin while healthy schedulers sit idle; dynconfig eventually
        drops the dead member from the ring)."""
        task_id = open_body["task_id"]
        members = self._ring.pick_n(task_id, len(self._ring.members()))
        first: DfError | None = None
        for i, addr in enumerate(members):
            try:
                cli = self._client_for_addr(addr)
                stream = await cli.open_stream("Scheduler.AnnouncePeer",
                                               open_body)
            except DfError as e:
                if first is None:
                    first = e
                if i + 1 < len(members):
                    log.warning("scheduler unreachable, trying next ring "
                                "member", addr=addr, error=e.message)
                continue
            self._stream_addrs[task_id] = addr
            FAILOVER_COUNT.labels("owner" if i == 0 else "failover").inc()
            return stream
        FAILOVER_COUNT.labels("exhausted").inc()
        if first is not None:
            raise first
        raise DfError(Code.SchedError, "no scheduler addresses")

    async def announce_host(self, host_wire: dict) -> "dict | None":
        # Host announcements go to every scheduler (each keeps its own
        # view). Returns the first successful response — it carries the
        # scheduler's ``sched_wall`` clock echo + this host's scorecard
        # row (announcer feeds the clock aligner / post-mortem bundles;
        # with multiple ring members the first member's clock anchors).
        first: "dict | None" = None
        for addr in self._ring.members():
            try:
                resp = await self._client_for_addr(addr).call(
                    "Scheduler.AnnounceHost", host_wire, timeout=10.0)
                if first is None and isinstance(resp, dict):
                    first = resp
            except DfError as e:
                log.warning("announce host failed", addr=addr, error=e.message)
        return first

    async def unary(self, task_id: str, method: str, body: dict,
                    timeout: float = 10.0,
                    idempotent: "bool | None" = None):
        """Unary call routed by task id through the consistent-hash ring
        (public surface for call families without a dedicated wrapper,
        e.g. the persistent cache RPCs). Ring failover is resolved from
        RPC_TABLE — only ``idempotent``-classified methods fail over; the
        safe posture for state-bearing methods is the owning member's
        error, retryable. An explicit ``idempotent=`` overrides (plugin
        methods the table cannot know)."""
        if idempotent is None:
            idempotent = RPC_TABLE.get(method) == IDEMPOTENT
        return await self._routed_call(task_id, method, body, timeout,
                                       idempotent=idempotent)

    async def announce_task(self, body: dict) -> None:
        """Advertise a locally-complete task (dfcache import) — reference
        AnnounceTask, service_v1.go:331. Idempotent registration: safe to
        land on a failover member."""
        await self._routed_call(body.get("task_id", ""),
                                "Scheduler.AnnounceTask", body, 10.0,
                                idempotent=True)

    async def leave_host(self, host_id: str) -> None:
        for addr in self._ring.members():
            try:
                await self._client_for_addr(addr).call("Scheduler.LeaveHost", {"id": host_id},
                                                       timeout=5.0)
            except DfError:
                pass

    def _client_for_addr(self, addr: str) -> Client:
        cli = self._clients.get(addr)
        if cli is None:
            host, _, port = addr.rpartition(":")
            cli = Client(NetAddr.tcp(host, int(port)))
            self._clients[addr] = cli
        return cli

    async def close(self) -> None:
        for cli in self._clients.values():
            await cli.close()
        self._clients.clear()
