"""Pod-sharded streaming loader over P2P tar shards.

The input-pipeline contract (tf.data-shaped, Murray et al.): every epoch
is a deterministic function of ``(seed, epoch, num_hosts)`` —

  * **exactly-once**: the union of the per-host iterators covers every
    sample of every shard exactly once per epoch;
  * **reproducible**: the same (seed, epoch, host_id) yields the same
    sample order, independent of timing, readahead depth, or fetch
    interleaving;
  * **host-independent**: host h's order never depends on which other
    hosts exist beyond ``num_hosts`` (a strided partition of one global
    shuffle).

Order is planned as: shuffle shard order, shuffle sample order within
each shard, flatten, stride-partition by host (``flat[host::hosts]``),
then interleave each host's items across up to K open shards for read
spread. All randomness flows from ``random.Random(seed-string)`` (which
seeds via SHA-512, stable across processes and machines — never
``hash()``, which is salted per process).

Fetching is pipelined: a bounded readahead window of in-flight
``ShardReader.read_sample`` futures (each a ranged P2P task) runs ahead
of the consumer; yield order stays the planned order.
"""

from __future__ import annotations

import asyncio
import random
from collections import deque
from dataclasses import dataclass

from dragonfly2_tpu.pkg import dflog, metrics
from dragonfly2_tpu.pkg.bufpool import BufferPool
from dragonfly2_tpu.dataset import tar_index
from dragonfly2_tpu.dataset.shard_reader import GatewayRangeFetcher, ShardReader

log = dflog.get("dataset.loader")

SAMPLES = metrics.counter(
    "dataset_samples_total", "Samples yielded by the streaming loader")
READAHEAD_DEPTH = metrics.gauge(
    "dataset_readahead_depth", "In-flight prefetched samples")
EPOCHS = metrics.counter(
    "dataset_epochs_total", "Epoch iterations started")


class LoaderError(Exception):
    pass


@dataclass(frozen=True)
class LoaderOptions:
    seed: int = 0
    num_hosts: int = 1
    host_id: int = 0
    interleave: int = 4       # concurrently-open shards per host
    readahead: int = 8        # in-flight prefetched samples
    extensions: tuple[str, ...] | None = None   # fetch only these members

    def __post_init__(self):
        if self.num_hosts < 1:
            raise LoaderError(f"num_hosts must be >= 1, got {self.num_hosts}")
        if not 0 <= self.host_id < self.num_hosts:
            raise LoaderError(
                f"host_id {self.host_id} outside [0, {self.num_hosts})")


# -- pure planning (what the determinism tests pin down) ---------------------

def epoch_order(samples_per_shard: list[int], seed: int,
                epoch: int) -> list[tuple[int, int]]:
    """The GLOBAL epoch order: (shard_idx, sample_idx) pairs — shards
    shuffled, samples shuffled within each shard. Identical on every
    host (pure function of the arguments)."""
    rng = random.Random(f"dfdataset:{seed}:{epoch}")
    shard_order = list(range(len(samples_per_shard)))
    rng.shuffle(shard_order)
    flat: list[tuple[int, int]] = []
    for si in shard_order:
        order = list(range(samples_per_shard[si]))
        rng.shuffle(order)
        flat.extend((si, k) for k in order)
    return flat


def host_partition(flat: list[tuple[int, int]], num_hosts: int,
                   host_id: int) -> list[tuple[int, int]]:
    """Strided partition: hosts' slices are disjoint and their union is
    ``flat`` — the exactly-once contract by construction."""
    return flat[host_id::num_hosts]


def interleave_shards(items: list[tuple[int, int]],
                      k: int) -> list[tuple[int, int]]:
    """Round-robin a host's items across up to ``k`` open shards (in
    first-appearance order). A permutation of ``items`` — membership is
    untouched, so exactly-once survives."""
    if k <= 1 or not items:
        return list(items)
    groups: dict[int, deque] = {}
    order: list[int] = []
    for si, ki in items:
        if si not in groups:
            groups[si] = deque()
            order.append(si)
        groups[si].append((si, ki))
    pending = deque(groups[si] for si in order)
    active: deque = deque()
    out: list[tuple[int, int]] = []
    while active or pending:
        while len(active) < k and pending:
            active.append(pending.popleft())
        g = active.popleft()
        out.append(g.popleft())
        if g:
            active.append(g)
    return out


def plan_host_epoch(samples_per_shard: list[int], opts: LoaderOptions,
                    epoch: int) -> list[tuple[int, int]]:
    """This host's full epoch plan (ordered (shard_idx, sample_idx))."""
    flat = epoch_order(samples_per_shard, opts.seed, epoch)
    mine = host_partition(flat, opts.num_hosts, opts.host_id)
    return interleave_shards(mine, opts.interleave)


# -- the loader --------------------------------------------------------------

class PodShardedLoader:
    """Streams webdataset samples out of P2P tar shards for ONE host of a
    pod. Construct with a Dfstore (gateway transport) or pass
    ``fetcher_factory`` to ride an embedded daemon
    (shard_reader.DaemonRangeFetcher). ``prepare()`` resolves every
    shard's index (cached P2P object or one-pass build), then
    ``epoch(n)`` yields sample dicts."""

    def __init__(self, store, bucket: str, shard_keys: list[str], *,
                 options: LoaderOptions | None = None,
                 fetcher_factory=None,
                 coalesce_gap: int = 256 << 10,
                 index_concurrency: int = 4,
                 pool: BufferPool | None = None):
        if not shard_keys:
            raise LoaderError("no shards given")
        if len(set(shard_keys)) != len(shard_keys):
            raise LoaderError("duplicate shard keys")
        self.store = store
        self.bucket = bucket
        self.shard_keys = list(shard_keys)
        self.opts = options or LoaderOptions()
        self._fetcher_factory = fetcher_factory or (
            lambda key: GatewayRangeFetcher(store, bucket, key))
        self._coalesce_gap = coalesce_gap
        self._index_concurrency = max(1, index_concurrency)
        self.pool = pool if pool is not None else BufferPool(
            name="dataset_span")
        self.indexes: list[tar_index.ShardIndex] | None = None
        self.readers: list[ShardReader] | None = None

    async def prepare(self) -> "PodShardedLoader":
        """Resolve all shard indexes (bounded concurrency) and build the
        per-shard readers. Idempotent."""
        if self.readers is not None:
            return self
        sem = asyncio.Semaphore(self._index_concurrency)

        async def resolve(key: str) -> tar_index.ShardIndex:
            async with sem:
                return await tar_index.fetch_or_build_index(
                    self.store, self.bucket, key)

        tasks = [asyncio.ensure_future(resolve(k)) for k in self.shard_keys]
        try:
            self.indexes = list(await asyncio.gather(*tasks))
        except BaseException:
            for t in tasks:
                t.cancel()
            await asyncio.gather(*tasks, return_exceptions=True)
            raise
        self.readers = [
            ShardReader(self._fetcher_factory(key), idx,
                        extensions=self.opts.extensions,
                        coalesce_gap=self._coalesce_gap, pool=self.pool)
            for key, idx in zip(self.shard_keys, self.indexes)]
        log.info("loader prepared", shards=len(self.shard_keys),
                 samples=sum(i.num_samples for i in self.indexes),
                 host=f"{self.opts.host_id}/{self.opts.num_hosts}")
        return self

    @property
    def num_samples(self) -> int:
        """Pod-wide sample count (all hosts, one epoch)."""
        if self.indexes is None:
            raise LoaderError("call prepare() first")
        return sum(i.num_samples for i in self.indexes)

    def plan(self, epoch: int) -> list[tuple[str, str]]:
        """This host's planned (shard_key, sample_key) order — exposed
        for determinism tests and debugging."""
        if self.indexes is None:
            raise LoaderError("call prepare() first")
        counts = [i.num_samples for i in self.indexes]
        return [(self.shard_keys[si], self.indexes[si].samples[ki].key)
                for si, ki in plan_host_epoch(counts, self.opts, epoch)]

    async def epoch(self, epoch: int = 0):
        """Async iterator over this host's samples for ``epoch``, with a
        bounded readahead window of in-flight ranged fetches. Yield order
        is exactly ``plan(epoch)``'s order."""
        if self.readers is None or self.indexes is None:
            raise LoaderError("call prepare() first")
        EPOCHS.inc()
        counts = [i.num_samples for i in self.indexes]
        plan = plan_host_epoch(counts, self.opts, epoch)
        plan_iter = iter(plan)
        window = max(1, self.opts.readahead)
        inflight: deque[asyncio.Future] = deque()

        def launch():
            while len(inflight) < window:
                nxt = next(plan_iter, None)
                if nxt is None:
                    break
                si, ki = nxt
                inflight.append(asyncio.ensure_future(
                    self.readers[si].read_sample(self.indexes[si].samples[ki])))
            READAHEAD_DEPTH.set(len(inflight))

        try:
            launch()
            while inflight:
                sample = await inflight.popleft()
                launch()
                SAMPLES.inc()
                yield sample
        finally:
            READAHEAD_DEPTH.set(0)
            for f in inflight:
                f.cancel()
            if inflight:
                await asyncio.gather(*inflight, return_exceptions=True)
