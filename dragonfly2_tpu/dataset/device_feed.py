"""Batch landing: loader samples → fixed-size record batches on device.

The last hop of the dataset plane: instead of a host-side copy loop
(bytes → np.stack → device_put), fixed-size records land through
``ops.hbm_sink.HBMSink`` piece-per-record — each record stages into a
device batch exactly like a P2P piece, the batch is verified ON DEVICE
against host checksums (the same verify-on-land contract as the
``--device=tpu`` sink, daemon/peer/device_sink.py), and the batch
materializes as a ``(batch, record_bytes)`` uint8 device array in one
fused assembly dispatch.

On a CPU-only JAX backend (``JAX_PLATFORMS=cpu``) — or with no usable
jax at all — the feed degrades to plain NumPy batches (``force_hbm=True``
keeps the sink path for tests and CPU-backend verification).
"""

from __future__ import annotations

from dataclasses import dataclass

from dragonfly2_tpu.pkg import dflog, metrics

log = dflog.get("dataset.device_feed")

DEVICE_BATCHES = metrics.counter(
    "dataset_device_batches_total",
    "Record batches produced by the device feed", ("path",))


class DeviceFeedError(Exception):
    pass


@dataclass
class DeviceBatch:
    """One landed batch: ``array`` is (n, record_bytes) uint8 — a device
    array on the HBM path, np.ndarray on the fallback."""

    keys: list[str]
    array: object
    on_device: bool


def _hbm_available() -> bool:
    try:
        import jax

        return jax.default_backend() != "cpu"
    except Exception:
        return False


class DeviceFeed:
    """Consumes a sample iterator (``PodShardedLoader.epoch()``) and
    yields fixed-size record batches of one member extension.

    ``record_bytes``: every record must be exactly this long, unless
    ``pad=True`` (shorter records are zero-padded; longer ones always
    raise — silent truncation would corrupt training data). The final
    short batch is yielded unless ``drop_last``.
    """

    def __init__(self, ext: str, record_bytes: int, batch_size: int, *,
                 pad: bool = False, drop_last: bool = False,
                 device=None, force_hbm: bool = False):
        if record_bytes <= 0 or batch_size <= 0:
            raise DeviceFeedError("record_bytes and batch_size must be > 0")
        self.ext = ext
        self.record_bytes = record_bytes
        self.batch_size = batch_size
        self.pad = pad
        self.drop_last = drop_last
        self.device = device
        self.use_hbm = force_hbm or _hbm_available()

    def _record(self, sample: dict) -> bytes:
        data = sample.get(self.ext)
        if data is None:
            raise DeviceFeedError(
                f"sample {sample.get('__key__')!r} has no {self.ext!r} member")
        if len(data) > self.record_bytes:
            raise DeviceFeedError(
                f"sample {sample.get('__key__')!r}: {self.ext} is "
                f"{len(data)}B > record_bytes={self.record_bytes}")
        if len(data) < self.record_bytes:
            if not self.pad:
                raise DeviceFeedError(
                    f"sample {sample.get('__key__')!r}: {self.ext} is "
                    f"{len(data)}B != record_bytes={self.record_bytes} "
                    "(pass pad=True to zero-pad)")
            data = data + b"\0" * (self.record_bytes - len(data))
        return data

    def _land_hbm(self, keys: list[str], records: list[bytes]) -> DeviceBatch:
        from dragonfly2_tpu.ops.hbm_sink import HBMSink

        padded = self.record_bytes + ((-self.record_bytes) % 4)
        sink = HBMSink(padded * len(records), padded, device=self.device,
                       batch_pieces=min(len(records), 64))
        for i, rec in enumerate(records):
            sink.land_piece(i, rec)
        sink.verify()   # on-device checksums vs host values
        arr = sink.as_record_batch(len(records), self.record_bytes)
        DEVICE_BATCHES.labels("hbm").inc()
        return DeviceBatch(keys=keys, array=arr, on_device=True)

    def _land_numpy(self, keys: list[str], records: list[bytes]) -> DeviceBatch:
        import numpy as np

        arr = np.frombuffer(b"".join(records), dtype=np.uint8).reshape(
            len(records), self.record_bytes)
        DEVICE_BATCHES.labels("numpy").inc()
        return DeviceBatch(keys=keys, array=arr, on_device=False)

    def _land(self, keys: list[str], records: list[bytes]) -> DeviceBatch:
        if self.use_hbm:
            try:
                return self._land_hbm(keys, records)
            except DeviceFeedError:
                raise
            except Exception as e:
                # Device trouble (OOM, runtime) degrades to host batches —
                # the input pipeline must outlive a sink hiccup.
                log.warning("HBM batch landing failed; numpy fallback",
                            error=str(e)[:200])
                self.use_hbm = False
        return self._land_numpy(keys, records)

    async def batches(self, samples):
        """Async generator: sample dicts in → DeviceBatch out."""
        keys: list[str] = []
        records: list[bytes] = []
        async for sample in samples:
            keys.append(sample.get("__key__", ""))
            records.append(self._record(sample))
            if len(records) == self.batch_size:
                yield self._land(keys, records)
                keys, records = [], []
        if records and not self.drop_last:
            yield self._land(keys, records)
