"""Dataset plane: webdataset tar shards as a JAX-ready streaming input.

Layers (each its own module, importable without jax until device landing
is actually requested):

  tar_index     one-pass tar header walk → compact per-shard sample
                index, cached pod-wide as a P2P object
  shard_reader  sample byte spans → ranged P2P tasks (embedded daemon or
                object-gateway transport), pooled span buffers
  loader        deterministic pod-sharded epoch iterator with bounded
                readahead (exactly-once per epoch across hosts)
  device_feed   fixed-size record batches landed via ops.hbm_sink with
                on-device verification; NumPy fallback on CPU backends
"""

from dragonfly2_tpu.dataset.tar_index import (   # noqa: F401
    Sample,
    ShardIndex,
    TarIndexer,
    TarIndexError,
    TarMember,
    TruncatedShardError,
    fetch_or_build_index,
    index_tar_bytes,
)
from dragonfly2_tpu.dataset.shard_reader import (   # noqa: F401
    DaemonRangeFetcher,
    GatewayRangeFetcher,
    ShardReadError,
    ShardReader,
)
from dragonfly2_tpu.dataset.loader import (   # noqa: F401
    LoaderError,
    LoaderOptions,
    PodShardedLoader,
    epoch_order,
    host_partition,
    interleave_shards,
    plan_host_epoch,
)


def __getattr__(name):
    # device_feed pulls in ops/hbm_sink (jax) lazily.
    if name in ("DeviceFeed", "DeviceBatch", "DeviceFeedError"):
        from dragonfly2_tpu.dataset import device_feed

        return getattr(device_feed, name)
    raise AttributeError(f"module {__name__!r} has no attribute {name!r}")
