"""Sample-addressed reads: a sample's tar byte spans → ranged P2P tasks.

The point of the dataset plane: a host that needs sample ``000123`` of a
16 GB shard must fetch the few hundred KB covering that sample's members,
not the shard. Both fetchers below resolve a byte span to a RANGED file
task on a daemon — range is part of task identity (pkg/idgen
task_id_v1), so every host in the pod pulling the same sample issues a
byte-identical task and the fabric dedupes per SPAN, exactly like
sharded checkpoint pulls (client/device.py _pull_ranges). Warm spans are
imported from the local whole-shard parent store without touching origin
(task_manager.import_range_from_local_parent); repeated reads ride
completed-task reuse.

Two transports:
  * ``DaemonRangeFetcher`` — embedded daemon (the north-star JAX process
    hosting its own dfdaemon): ranged FileTasks directly on the TaskManager.
  * ``GatewayRangeFetcher`` — over HTTP against the daemon's object
    gateway (`?ranged_task=1` GETs, daemon/objectstorage.py).

Span buffers ride the shared BufferPool (pkg/bufpool): readahead keeps a
bounded fleet of in-flight spans, and pooled backing arrays stop the
per-sample allocate/free churn.
"""

from __future__ import annotations

import asyncio

from dragonfly2_tpu.pkg import dflog, metrics
from dragonfly2_tpu.pkg.bufpool import BufferPool
from dragonfly2_tpu.dataset.tar_index import Sample, ShardIndex

log = dflog.get("dataset.shard_reader")

DATASET_BYTES = metrics.counter(
    "dataset_bytes_total",
    "Dataset plane bytes: fetched (ranged spans) vs yielded (sample "
    "member payloads)", ("direction",))
RANGE_READS = metrics.counter(
    "dataset_range_reads_total",
    "Sample span reads by outcome", ("result",))


class ShardReadError(Exception):
    pass


class DaemonRangeFetcher:
    """Ranged file tasks on an in-process daemon/TaskManager. ``url`` is
    the shard's origin URL (e.g. backend.object_url(bucket, key)); ``tag``
    must match whatever other consumers use (the gateway uses the bucket
    name) so ranged tasks dedupe across surfaces."""

    def __init__(self, task_manager, url: str, *, tag: str = "",
                 application: str = "", header: dict | None = None,
                 pod_broadcast: bool = False):
        self.tm = task_manager
        self.url = url
        self.tag = tag
        # Extra task-identity fields for consumers whose spans must dedup
        # with other surfaces carrying them (the delta plane threads the
        # original request's application/header through so every host
        # running the same delta issues byte-identical span tasks).
        self.application = application
        self.header = dict(header or {})
        self.pod_broadcast = pod_broadcast
        self.stats = {"cold": 0, "reuse": 0}

    async def fetch_into(self, start: int, end: int, buf: memoryview) -> None:
        from dragonfly2_tpu.daemon.peer.task_manager import FileTaskRequest
        from dragonfly2_tpu.pkg.errors import Code, DfError
        from dragonfly2_tpu.pkg.piece import Range
        from dragonfly2_tpu.proto.common import UrlMeta

        rng = Range.normalize_header(f"{start}-{end - 1}")
        req = FileTaskRequest(url=self.url, output="",
                              meta=UrlMeta(tag=self.tag,
                                           application=self.application,
                                           header=dict(self.header),
                                           range=rng),
                              pod_broadcast=self.pod_broadcast)
        req.range = Range.parse_http(rng)
        final = None
        async for p in self.tm.start_file_task(req):
            if p.state == "failed":
                raise DfError.from_wire(p.error or {})
            if p.state == "done":
                final = p
        if final is None:
            raise DfError(Code.UnknownError, "ranged task ended silently")
        store = self.tm.storage.find_completed_task(final.task_id)
        if store is None:
            raise DfError(Code.StorageTaskNotFound,
                          f"ranged task {final.task_id[:16]} has no store")
        n = end - start
        if store.metadata.content_length != n:
            raise ShardReadError(
                f"ranged task returned {store.metadata.content_length}B "
                f"for a {n}B span of {self.url}")
        with store:   # pin across the off-loop read
            # Unified read path: preadv straight into the caller's pooled
            # span buffer — no intermediate store buffer, no copy.
            await asyncio.to_thread(store.read_into, 0, n, buf)
        self.stats["reuse" if final.from_reuse else "cold"] += 1
        RANGE_READS.labels("reuse" if final.from_reuse else "cold").inc()


class GatewayRangeFetcher:
    """Ranged-task GETs over the daemon's object gateway (Dfstore
    ``read_object_range`` with ranged_task=1)."""

    def __init__(self, store, bucket: str, key: str):
        self.store = store
        self.bucket = bucket
        self.key = key
        self.stats = {"cold": 0, "reuse": 0}

    async def fetch_into(self, start: int, end: int, buf: memoryview) -> None:
        attrs, _ = await self.store.read_object_range(
            self.bucket, self.key, start, end, buf=buf)
        outcome = "reuse" if attrs.get("from_reuse") else "cold"
        self.stats[outcome] += 1
        RANGE_READS.labels(outcome).inc()


class ShardReader:
    """Sample-level reads over one indexed shard. Adjacent member spans
    closer than ``coalesce_gap`` merge into one ranged task (the gap
    bytes ride along — fewer tasks beats fewer bytes at tar header
    granularity, and webdataset members are adjacent by construction)."""

    def __init__(self, fetcher, index: ShardIndex, *,
                 extensions=None, coalesce_gap: int = 256 << 10,
                 include_headers: bool = False,
                 pool: BufferPool | None = None):
        self.fetcher = fetcher
        self.index = index
        self.extensions = (None if extensions is None
                           else tuple(extensions))
        self.coalesce_gap = coalesce_gap
        # include_headers widens spans to the members' header blocks —
        # useful when re-emitting valid tar bytes rather than payloads.
        self.include_headers = include_headers
        self.pool = pool if pool is not None else BufferPool(
            name="dataset_span")

    def sample_spans(self, sample: Sample) -> list[tuple[int, int]]:
        """Coalesced absolute byte spans covering the sample's members."""
        pairs = self.index.members_of(sample, self.extensions)
        if not pairs:
            raise ShardReadError(
                f"sample {sample.key!r} has no members"
                + (f" for extensions {self.extensions}" if self.extensions
                   else ""))
        raw = sorted(
            ((m.offset if self.include_headers else m.data_offset),
             m.data_offset + m.size)
            for _, m in pairs)
        spans: list[list[int]] = []
        for s, e in raw:
            if spans and s - spans[-1][1] <= self.coalesce_gap:
                spans[-1][1] = max(spans[-1][1], e)
            else:
                spans.append([s, e])
        return [(s, e) for s, e in spans]

    async def read_sample(self, sample: Sample) -> dict:
        """Fetch one sample; returns ``{"__key__", "__shard__",
        <ext>: bytes, ...}``. Multiple spans fetch concurrently (rare —
        coalescing usually leaves one)."""
        spans = self.sample_spans(sample)
        bufs: dict[tuple[int, int], memoryview] = {}
        try:
            for s, e in spans:
                bufs[(s, e)] = self.pool.acquire(e - s)

            async def pull(s: int, e: int) -> None:
                await self.fetcher.fetch_into(s, e, bufs[(s, e)])

            if len(spans) == 1:
                await pull(*spans[0])
            else:
                tasks = [asyncio.ensure_future(pull(s, e)) for s, e in spans]
                try:
                    await asyncio.gather(*tasks)
                except BaseException:
                    for t in tasks:
                        t.cancel()
                    await asyncio.gather(*tasks, return_exceptions=True)
                    raise
            out: dict = {"__key__": sample.key, "__shard__": self.index.shard}
            yielded = 0
            for ext, m in self.index.members_of(sample, self.extensions):
                span = next((s, e) for s, e in spans
                            if s <= m.data_offset
                            and m.data_offset + m.size <= e)
                buf = bufs[span]
                lo = m.data_offset - span[0]
                out[ext] = bytes(buf[lo:lo + m.size])
                yielded += m.size
            DATASET_BYTES.labels("fetched").inc(
                sum(e - s for s, e in spans))
            DATASET_BYTES.labels("yielded").inc(yielded)
            return out
        finally:
            for buf in bufs.values():
                self.pool.release(buf)
