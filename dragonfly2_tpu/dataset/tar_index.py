"""Tar-shard indexing for the dataset plane.

WebDataset-style training data ships as plain tar shards (Aizman et al.,
*High-Performance I/O for Large-Scale Deep Learning*): samples are groups
of adjacent files sharing a basename key (``000123.jpg`` + ``000123.cls``).
Random access into a shard therefore needs exactly one thing: a map from
sample key to the byte spans of its members. This module builds that map
with a single streaming pass over the shard (``TarIndexer`` consumes
chunks as they arrive — it never buffers file data, only header blocks),
and serializes it compactly so the index itself can live as a P2P object:
one host pays the header walk, every other host fetches a few KB
(``fetch_or_build_index``).

Handled tar dialects: ustar name+prefix, GNU long name ('L') / long link
('K') extensions, pax extended headers ('x' per-file, 'g' global), links,
and header-checksum validation. Truncation is a TYPED failure
(``TruncatedShardError``) — a shard cut mid-member must never silently
yield partial samples — while a shard that merely ends without the
end-of-archive zero blocks or without the final data block's 512-byte
padding indexes fine (both occur in the wild).

No reference analog: Dragonfly2 moves opaque objects; sample-granular
addressing is new with this layer.
"""

from __future__ import annotations

import json
from dataclasses import dataclass, field

from dragonfly2_tpu.pkg import dflog, metrics

log = dflog.get("dataset.tar_index")

BLOCK = 512
INDEX_VERSION = 1
# Hidden bucket prefix for cached shard indexes (kept out of normal
# listings' way; same bucket as the shard so ACL/lifecycle follow it).
INDEX_PREFIX = ".dfidx/"

INDEX_FETCHES = metrics.counter(
    "dataset_index_total",
    "Shard index resolutions by outcome", ("result",))

# Typeflags whose member body is file data. POSIX says link/dir/device
# sizes are to be ignored; unknown flags are treated as regular files for
# forward compatibility (same rule as Python's tarfile).
_REGTYPES = ("0", "\0", "7")
_LINKTYPES = ("1", "2")
_NODATA_TYPES = ("1", "2", "3", "4", "5", "6")


class TarIndexError(Exception):
    """Malformed tar content (bad checksum, bogus field, corrupt pax)."""


class TruncatedShardError(TarIndexError):
    """The shard ends mid-member: indexing it would drop samples."""


@dataclass(frozen=True)
class TarMember:
    name: str
    offset: int        # offset of the member's header block
    data_offset: int   # offset of the member's first data byte
    size: int          # data bytes (0 for links)
    typeflag: str = "0"
    linkname: str = ""


@dataclass(frozen=True)
class Sample:
    """One webdataset sample: the members sharing a basename key."""

    key: str
    parts: tuple[tuple[str, int], ...]   # (extension, member index), tar order


@dataclass
class ShardIndex:
    shard: str                 # object key (or url) this index describes
    size: int                  # total shard bytes walked
    members: list[TarMember]
    samples: list[Sample]
    links: list[TarMember] = field(default_factory=list)
    version: int = INDEX_VERSION

    @property
    def num_samples(self) -> int:
        return len(self.samples)

    def sample(self, i: int) -> Sample:
        return self.samples[i]

    def members_of(self, sample: Sample,
                   extensions=None) -> list[tuple[str, TarMember]]:
        """(extension, member) pairs of a sample, optionally filtered to
        ``extensions``; unknown requested extensions are simply absent."""
        out = []
        for ext, mi in sample.parts:
            if extensions is not None and ext not in extensions:
                continue
            out.append((ext, self.members[mi]))
        return out

    # -- serialization (the P2P-cached form) -------------------------------

    def to_json_bytes(self) -> bytes:
        doc = {
            "v": self.version,
            "shard": self.shard,
            "size": self.size,
            "members": [[m.name, m.offset, m.data_offset, m.size]
                        for m in self.members],
            "samples": [[s.key, [[e, i] for e, i in s.parts]]
                        for s in self.samples],
            "links": [[m.name, m.offset, m.typeflag, m.linkname]
                      for m in self.links],
        }
        return json.dumps(doc, separators=(",", ":")).encode()

    @classmethod
    def from_json_bytes(cls, raw: bytes) -> "ShardIndex":
        try:
            doc = json.loads(raw)
            if doc["v"] != INDEX_VERSION:
                raise TarIndexError(f"index version {doc['v']} unsupported")
            members = [TarMember(name=n, offset=o, data_offset=d, size=s)
                       for n, o, d, s in doc["members"]]
            samples = [Sample(key=k, parts=tuple((e, int(i)) for e, i in p))
                       for k, p in doc["samples"]]
            links = [TarMember(name=n, offset=o, data_offset=0, size=0,
                               typeflag=t, linkname=ln)
                     for n, o, t, ln in doc.get("links", [])]
            idx = cls(shard=doc["shard"], size=int(doc["size"]),
                      members=members, samples=samples, links=links)
        except TarIndexError:
            raise
        except Exception as e:
            raise TarIndexError(f"corrupt shard index: {e}") from e
        for s in idx.samples:
            for _, mi in s.parts:
                if not 0 <= mi < len(members):
                    raise TarIndexError(
                        f"index sample {s.key!r} references member {mi} "
                        f"of {len(members)}")
        return idx


# -- header field parsing ----------------------------------------------------

def _field_str(b: bytes) -> str:
    return b.split(b"\0", 1)[0].decode("utf-8", "surrogateescape")

def _field_num(b: bytes, what: str, offset: int) -> int:
    if b and b[0] & 0x80:
        # GNU base-256: leading bit flags a big-endian binary number.
        return int.from_bytes(b, "big") - (0x80 << (8 * (len(b) - 1)))
    s = b.split(b"\0", 1)[0].strip(b" \0")
    if not s:
        return 0
    try:
        return int(s, 8)
    except ValueError as e:
        raise TarIndexError(
            f"bad {what} field at offset {offset}: {b!r}") from e


def _checksum_ok(block: bytes) -> bool:
    raw = block[148:156]
    s = raw.split(b"\0", 1)[0].strip(b" \0")
    try:
        want = int(s, 8)
    except ValueError:
        return False
    unsigned = sum(block) - sum(raw) + 8 * 0x20
    # Some ancient writers summed signed chars; accept both.
    signed = unsigned - 256 * sum(1 for c in block if c > 127) \
        + 256 * sum(1 for c in raw if c > 127)
    return want in (unsigned, signed)


def _parse_pax(data: bytes, offset: int) -> dict[str, str]:
    """pax records: ``<decimal len> <key>=<value>\\n`` — len counts the
    whole record including itself and the newline."""
    out: dict[str, str] = {}
    pos = 0
    while pos < len(data):
        try:
            sp = data.index(b" ", pos)
            length = int(data[pos:sp])
            if length <= 0 or pos + length > len(data):
                raise ValueError(f"record length {length}")
            record = data[pos:pos + length]
            if not record.endswith(b"\n"):
                raise ValueError("record missing newline")
            k, sep, v = record[sp - pos + 1:-1].partition(b"=")
            if not sep:
                raise ValueError("record missing '='")
            out[k.decode()] = v.decode("utf-8", "surrogateescape")
            pos += length
        except (ValueError, UnicodeDecodeError) as e:
            raise TarIndexError(
                f"corrupt pax header at offset {offset}: {e}") from e
    return out


# -- sample grouping ---------------------------------------------------------

def group_samples(members: list[TarMember]) -> list[Sample]:
    """Webdataset grouping: key = dirname + basename-up-to-first-dot;
    extension = everything after the first dot. Members keep tar order;
    sample order is first appearance of the key; a duplicated extension
    within one key keeps the first occurrence."""
    parts: dict[str, list[tuple[str, int]]] = {}
    order: list[str] = []
    for i, m in enumerate(members):
        slash = m.name.rfind("/")
        base = m.name[slash + 1:]
        stem, _, ext = base.partition(".")
        if not stem:
            continue   # dotfiles / metadata are not sample parts
        key = m.name[:slash + 1] + stem
        if key not in parts:
            parts[key] = []
            order.append(key)
        if any(e == ext for e, _ in parts[key]):
            continue
        parts[key].append((ext, i))
    return [Sample(key=k, parts=tuple(parts[k])) for k in order]


# -- the incremental indexer -------------------------------------------------

class TarIndexer:
    """Single-pass streaming tar header walk. ``feed()`` arbitrary chunks
    (any split), then ``finish()`` for the ShardIndex. File data is never
    buffered — only 512-byte header blocks and GNU/pax extension payloads
    are captured; everything else adjusts skip counters."""

    _HEADER = "header"

    def __init__(self):
        self._consumed = 0
        self._pend = bytearray()
        self._need = BLOCK
        self._capture = self._HEADER      # or the extension typeflag
        self._ext_size = 0
        self._skip_data = 0
        self._skip_pad = 0
        self._zero_blocks = 0
        self._done = False
        self._next_name: str | None = None
        self._next_link: str | None = None
        self._pax_next: dict[str, str] = {}
        self._pax_global: dict[str, str] = {}
        self._pending_override = False
        self.members: list[TarMember] = []
        self.links: list[TarMember] = []

    def feed(self, chunk: bytes) -> None:
        mv = memoryview(chunk)
        i, n = 0, len(chunk)
        while i < n:
            if self._done:
                # Trailing blocking-factor padding after end-of-archive.
                self._consumed += n - i
                return
            if self._skip_data:
                take = min(self._skip_data, n - i)
                self._skip_data -= take
                self._consumed += take
                i += take
                continue
            if self._skip_pad:
                take = min(self._skip_pad, n - i)
                self._skip_pad -= take
                self._consumed += take
                i += take
                continue
            take = min(self._need - len(self._pend), n - i)
            self._pend += mv[i:i + take]
            self._consumed += take
            i += take
            if len(self._pend) == self._need:
                block = bytes(self._pend)
                self._pend.clear()
                if self._capture == self._HEADER:
                    self._on_header(block)
                else:
                    self._on_extension(block)

    def finish(self, shard: str = "") -> ShardIndex:
        """Validate the end state and build the index. Tolerated endings: clean
        end-of-archive marker, EOF at a member boundary (no zero blocks),
        EOF with only the final data block's padding missing. Anything
        else is a truncation."""
        if not self._done:
            if self._pend or self._capture != self._HEADER:
                raise TruncatedShardError(
                    f"shard truncated mid-{'header' if self._capture == self._HEADER else 'extension'}"
                    f" at offset {self._consumed}")
            if self._skip_data:
                raise TruncatedShardError(
                    f"shard truncated: {self._skip_data} data bytes missing "
                    f"at offset {self._consumed}")
            if self._pending_override:
                raise TruncatedShardError(
                    "shard truncated: extension header without its member")
        return ShardIndex(shard=shard, size=self._consumed,
                          members=self.members,
                          samples=group_samples(self.members),
                          links=self.links)

    # -- internals ---------------------------------------------------------

    def _on_header(self, block: bytes) -> None:
        off = self._consumed - BLOCK
        if block.count(0) == BLOCK:
            self._zero_blocks += 1
            if self._zero_blocks >= 2:
                self._done = True
            return
        if self._zero_blocks:
            raise TarIndexError(f"lone zero block at offset {off - BLOCK}")
        if not _checksum_ok(block):
            raise TarIndexError(f"bad header checksum at offset {off}")
        typeflag = chr(block[156]) or "0"
        size = _field_num(block[124:136], "size", off)
        if size < 0:
            raise TarIndexError(f"negative size at offset {off}")
        if typeflag in ("L", "K", "x", "g"):
            if size > (1 << 24):
                raise TarIndexError(
                    f"implausible {size}-byte extension header at {off}")
            self._capture = typeflag
            self._ext_size = size
            self._need = size + ((-size) % BLOCK)
            if self._need == 0:
                # Zero-length extension: process immediately (degenerate
                # but legal — an empty pax record set).
                self._capture = self._HEADER
                self._need = BLOCK
            return
        self._on_member(block, off, typeflag, size)

    def _on_member(self, block: bytes, off: int, typeflag: str,
                   size: int) -> None:
        pax = {**self._pax_global, **self._pax_next}
        name = pax.get("path")
        if name is None:
            name = self._next_name
        if name is None:
            name = _field_str(block[0:100])
            prefix = (_field_str(block[345:500])
                      if block[257:262] == b"ustar" else "")
            if prefix:
                name = f"{prefix}/{name}"
        linkname = pax.get("linkpath")
        if linkname is None:
            linkname = self._next_link
        if linkname is None:
            linkname = _field_str(block[157:257])
        if "size" in pax:
            try:
                size = int(pax["size"])
            except ValueError as e:
                raise TarIndexError(
                    f"bad pax size at offset {off}: {pax['size']!r}") from e
        data = 0 if typeflag in _NODATA_TYPES else size
        if typeflag in _REGTYPES:
            self.members.append(TarMember(
                name=name, offset=off, data_offset=off + BLOCK, size=size,
                typeflag="0" if typeflag == "\0" else typeflag))
        elif typeflag in _LINKTYPES:
            self.links.append(TarMember(
                name=name, offset=off, data_offset=off + BLOCK, size=0,
                typeflag=typeflag, linkname=linkname))
        self._skip_data = data
        self._skip_pad = (-data) % BLOCK
        self._next_name = self._next_link = None
        self._pax_next = {}
        self._pending_override = False

    def _on_extension(self, block: bytes) -> None:
        off = self._consumed - self._need
        data = block[: self._ext_size]
        kind = self._capture
        self._capture = self._HEADER
        self._need = BLOCK
        if kind == "L":
            self._next_name = data.rstrip(b"\0").decode(
                "utf-8", "surrogateescape")
            self._pending_override = True
        elif kind == "K":
            self._next_link = data.rstrip(b"\0").decode(
                "utf-8", "surrogateescape")
            self._pending_override = True
        elif kind == "x":
            self._pax_next.update(_parse_pax(data, off))
            self._pending_override = True
        else:   # 'g'
            self._pax_global.update(_parse_pax(data, off))


def index_tar_bytes(data: bytes, shard: str = "") -> ShardIndex:
    """Index an in-memory shard (tests, local files)."""
    ix = TarIndexer()
    ix.feed(data)
    return ix.finish(shard)


# -- P2P-cached index lifecycle ----------------------------------------------

def index_object_key(shard_key: str) -> str:
    return f"{INDEX_PREFIX}{shard_key}.json"


async def fetch_or_build_index(store, bucket: str, shard_key: str, *,
                               publish: bool = True) -> ShardIndex:
    """The pod-wide index contract: try the cached index object first
    (computed once, fetched everywhere); on miss, stream the shard ONE
    pass through the indexer — which also warms this host's piece store
    with the shard it is about to consume — and publish the result back
    as a P2P object (best effort; racing builders converge on identical
    bytes). A cached index whose recorded size disagrees with the shard's
    current length is stale (shard replaced in place) and is rebuilt."""
    from dragonfly2_tpu.client.dfstore import DfstoreError

    meta = await store.stat_object(bucket, shard_key)   # missing shard raises
    try:
        raw = await store.get_object(bucket, index_object_key(shard_key))
        idx = ShardIndex.from_json_bytes(raw)
        if idx.shard == shard_key and idx.size == meta.content_length:
            INDEX_FETCHES.labels("hit").inc()
            return idx
        log.info("cached shard index stale; rebuilding", shard=shard_key,
                 cached=idx.size, actual=meta.content_length)
        INDEX_FETCHES.labels("stale").inc()
    except DfstoreError:
        pass
    except TarIndexError as e:
        log.warning("cached shard index corrupt; rebuilding",
                    shard=shard_key, error=str(e)[:200])
        INDEX_FETCHES.labels("corrupt").inc()
    ix = TarIndexer()
    async for chunk in await store.stream_object(bucket, shard_key):
        ix.feed(chunk)
    idx = ix.finish(shard_key)
    INDEX_FETCHES.labels("built").inc()
    if publish:
        try:
            await store.put_object(bucket, index_object_key(shard_key),
                                   idx.to_json_bytes())
        except DfstoreError as e:
            log.warning("shard index publish failed (non-fatal)",
                        shard=shard_key, error=str(e)[:200])
    return idx
