"""Flight recorder: always-on per-task event timelines + critical-path autopsy.

Reference posture: the reference wires OpenTelemetry per binary
(cmd/dependency/dependency.go:263-271), but spans answer "what called
what", not "where did the wall time go" — when a pod broadcast degrades,
the question is Dapper/Mystery-Machine shaped: reconstruct the critical
path from always-on, bounded-cost event logs. This module is that black
box for the data plane:

  * every task gets a bounded ring of typed, monotonic-clocked events
    emitted at the choke points chaos already instruments (register,
    schedule pushes, piece assign/request/first-byte/landed/verified,
    parent drops, quarantine, stripe reshuffles, back-to-source, HBM
    landing, upload serving);
  * ``analyze()`` folds a task's events into a phase breakdown
    (sched_wait / dcn / ici / verify / store / stall / origin) whose
    segments partition the task's wall time exactly (a residual bucket
    ``other`` absorbs uninstrumented gaps), plus a per-piece waterfall;
  * the daemon serves it at ``/debug/flight[/<task_id>]`` (pkg/
    metrics_server), dumps a post-mortem JSON bundle on task failure,
    and feeds ``peer_task_phase_seconds{phase}`` histograms;
  * piece reports carry per-piece phase timings on the wire so the
    scheduler's ``PodAggregator`` can attribute stragglers per host
    (``/debug/pod/<task_id>``: slowest host, dominant phase, quarantine
    correlation).

Hot-path contract: ``TaskFlight.record`` appends ONE tuple into a
preallocated ring — no per-event dict, no I/O, no lock — so the recorder
stays on in production (tests/test_flight.py pins the bound and the
no-dict property).
"""

from __future__ import annotations

import gzip
import json
import os
import threading
import time
from collections import OrderedDict

from dragonfly2_tpu.pkg import dflog, metrics

log = dflog.get("flight")

# Monotonic-anchored wall clock: wall is sampled ONCE at import and every
# later reading is anchor + perf_counter delta, so an NTP step mid-run
# cannot skew any timeline or clock sample built from it. Everything this
# module (and the pod-lens clock alignment on top of it) calls "wall time"
# is this clock, optionally plus a per-recorder offset (the chaos knob
# that lets a test inject a known skew).
_WALL_ANCHOR = time.time()
_PC_ANCHOR = time.perf_counter()


def anchored_wall() -> float:
    return _WALL_ANCHOR + (time.perf_counter() - _PC_ANCHOR)

# --------------------------------------------------------------------- #
# Event vocabulary (ints in the ring; names only at export time)
# --------------------------------------------------------------------- #

EV_REGISTER = 1        # announce register sent
EV_SCHEDULED = 2       # scheduler answered the register (note=kind)
EV_SCHED_PUSH = 3      # mid-task scheduler push (note=kind)
EV_RESCHEDULE = 4      # reschedule sent (starvation)
EV_SCHED_ANSWER = 5    # reschedule answered / schedule update applied
EV_RECONNECT = 6       # announce-stream recovery attempt (note=result)
EV_REQUEST = 7         # piece GET issued (note=parent ip:port)
EV_FIRST_BYTE = 8      # first body chunk arrived
EV_LANDED = 9          # piece verified+recorded (aux=cost_ms, note=locality)
EV_FAILED = 10         # piece attempt failed (note=typed reason)
EV_STORE_START = 11    # store write handed to the executor
EV_STORED = 12         # store write committed
EV_VERIFY_START = 13   # completion whole-content re-hash started
EV_VERIFIED = 14       # completion re-hash done
EV_PARENT_DROP = 15    # dispatcher dropped a parent (note=peer id)
EV_QUARANTINE = 16     # parent entered quarantine (note=endpoint|reason)
EV_STRIPE = 17         # stripe plan applied/cleared (aux=slice_size)
EV_BACK_SOURCE = 18    # task demoted to origin
EV_SOURCE_LANDED = 19  # origin piece landed (aux=cost_ms)
EV_HBM_START = 20      # device-sink landing started
EV_HBM_LANDED = 21     # device-sink landing done
EV_UPLOAD_SERVE = 22   # this daemon served a piece of the task (aux=bytes)
EV_TASK_DONE = 23
EV_TASK_FAILED = 24
EV_DELTA_REUSE = 25    # delta chunk copied from the local base (aux=cost_ms)
EV_DELTA_FETCH = 26    # delta chunk pulled as a ranged task (aux=cost_ms)
EV_LOOP_LAG = 27       # event loop wedged during this task (aux=lag_s)
EV_GC_PAUSE = 28       # slow cyclic-GC pause during this task (aux=pause_s)

EVENT_NAMES = {
    EV_REGISTER: "register", EV_SCHEDULED: "scheduled",
    EV_SCHED_PUSH: "sched_push", EV_RESCHEDULE: "reschedule",
    EV_SCHED_ANSWER: "sched_answer", EV_RECONNECT: "reconnect",
    EV_REQUEST: "request", EV_FIRST_BYTE: "first_byte",
    EV_LANDED: "landed", EV_FAILED: "failed",
    EV_STORE_START: "store_start", EV_STORED: "stored",
    EV_VERIFY_START: "verify_start", EV_VERIFIED: "verified",
    EV_PARENT_DROP: "parent_drop", EV_QUARANTINE: "quarantine",
    EV_STRIPE: "stripe", EV_BACK_SOURCE: "back_source",
    EV_SOURCE_LANDED: "source_landed", EV_HBM_START: "hbm_start",
    EV_HBM_LANDED: "hbm_landed", EV_UPLOAD_SERVE: "upload_serve",
    EV_TASK_DONE: "task_done", EV_TASK_FAILED: "task_failed",
    EV_DELTA_REUSE: "delta_reuse", EV_DELTA_FETCH: "delta_fetch",
    EV_LOOP_LAG: "loop_lag", EV_GC_PAUSE: "gc_pause",
}

# Runtime-interference events (pkg/prof stamps them into every RUNNING
# flight): not phase markers — the analyzer summarizes them separately
# so --explain can say the LOOP was wedged, not just "nothing happened".
_RUNTIME_EVENTS = (EV_LOOP_LAG, EV_GC_PAUSE)

# Canonical phase model. ``other`` (residual uninstrumented time) rides
# alongside so the fold partitions wall time exactly.
PHASES = ("sched_wait", "dcn", "ici", "verify", "store", "stall", "origin")

# Overlap priority: when two phases cover the same wall segment, the one
# doing WORK wins (a stall that overlaps a concurrent healthy transfer
# did not cost wall time).
_PRIORITY = {"verify": 6, "store": 5, "ici": 4, "dcn": 3, "origin": 2,
             "stall": 1, "sched_wait": 0}

# A first byte later than this after the request counts the gap as stall
# (the parent was connected but silent) instead of transfer time.
STALL_TTFB_S = 0.25

PHASE_SECONDS = metrics.histogram(
    "peer_task_phase_seconds",
    "Per-task phase durations from the flight recorder's critical-path fold",
    ("phase",),
    buckets=(0.005, 0.02, 0.05, 0.1, 0.25, 0.5, 1.0, 2.5, 5.0, 10.0, 30.0,
             60.0))

# record() keeps per-piece slots for the wire-report timings; maps event
# code -> slot index in the 5-float row [request, first_byte, landed,
# store_start, stored].
_TRACK_SLOT = {EV_REQUEST: 0, EV_FIRST_BYTE: 1, EV_LANDED: 2,
               EV_STORE_START: 3, EV_STORED: 4}


class TaskFlight:
    """One task's bounded event ring. All times are seconds relative to
    the task's start on the monotonic clock (NTP steps cannot skew a
    timeline); ``start_wall`` anchors export to wall time."""

    __slots__ = ("task_id", "start_wall", "_start_pc", "_cap", "_ring",
                 "_n", "state", "note", "_end_pc", "_piece_track",
                 "_piece_cap", "__weakref__")

    def __init__(self, task_id: str, capacity: int = 2048,
                 piece_track_cap: int = 4096, wall_offset: float = 0.0):
        self.task_id = task_id
        self.start_wall = anchored_wall() + wall_offset
        self._start_pc = time.perf_counter()
        self._cap = capacity
        self._ring: list = [None] * capacity
        self._n = 0
        self.state = "running"
        self.note = ""
        self._end_pc = -1.0
        self._piece_track: dict[int, list] = {}
        self._piece_cap = piece_track_cap

    # -- hot path ----------------------------------------------------------

    def record(self, code: int, piece: int = -1, aux: float = 0.0,
               note: str = "") -> None:
        """Append one event: a tuple into the preallocated ring. MUST stay
        allocation-light (no dict literals / kwargs expansion on this
        path — test_flight pins the bytecode)."""
        t = time.perf_counter() - self._start_pc
        self._ring[self._n % self._cap] = (t, code, piece, aux, note)
        self._n += 1
        if piece >= 0 and code in _TRACK_SLOT:
            slot = _TRACK_SLOT[code]
            track = self._piece_track.get(piece)
            if track is None:
                if len(self._piece_track) >= self._piece_cap:
                    self._piece_track.pop(next(iter(self._piece_track)))
                track = self._piece_track[piece] = [-1.0, -1.0, -1.0, -1.0,
                                                    -1.0]
            if slot == 0:
                # New attempt: the previous attempt's marks are stale.
                track[1] = track[2] = track[3] = track[4] = -1.0
            track[slot] = t

    # -- accessors ---------------------------------------------------------

    @property
    def events_total(self) -> int:
        return self._n

    @property
    def events_dropped(self) -> int:
        return max(0, self._n - self._cap)

    def wall_s(self) -> float:
        end = self._end_pc if self._end_pc >= 0 else (
            time.perf_counter() - self._start_pc)
        return max(0.0, end)

    def wall_now(self) -> float:
        """This task's anchored wall clock right now (start_wall + the
        monotonic delta, so it carries the recorder's wall offset and is
        NTP-step-proof) — what clock-alignment samples stamp."""
        return self.start_wall + (time.perf_counter() - self._start_pc)

    def events(self) -> list:
        """Chronological retained events (oldest dropped on overflow)."""
        if self._n <= self._cap:
            return [e for e in self._ring[:self._n]]
        head = self._n % self._cap
        return [e for e in self._ring[head:] + self._ring[:head]]

    def finish(self, state: str, note: str = "") -> None:
        self.record(EV_TASK_DONE if state == "done" else EV_TASK_FAILED,
                    -1, 0.0, note)
        self.state = state
        self.note = note
        self._end_pc = time.perf_counter() - self._start_pc

    def piece_report_timings(self, piece: int) -> "dict | None":
        """Per-phase ms for the wire piece report (scheduler straggler
        attribution): dcn_ms / stall_ms / store_ms. None when this piece
        recorded no request (origin/imported pieces)."""
        tr = self._piece_track.get(piece)
        if tr is None or tr[0] < 0:
            return None
        out: dict = {}
        store = 0.0
        if tr[3] >= 0 and tr[4] >= tr[3]:
            store = (tr[4] - tr[3]) * 1000.0
            out["store_ms"] = int(store)
        if tr[2] >= 0:
            total = (tr[2] - tr[0]) * 1000.0
            stall = 0.0
            if tr[1] >= 0 and (tr[1] - tr[0]) > STALL_TTFB_S:
                stall = (tr[1] - tr[0]) * 1000.0
            # dcn is what remains of the attempt after the silent gap and
            # the store write — the phases must not double-count.
            out["dcn_ms"] = int(max(0.0, total - stall - store))
            out["stall_ms"] = int(stall)
        return out or None


# --------------------------------------------------------------------- #
# Critical-path analyzer
# --------------------------------------------------------------------- #

def _fold_phases(intervals: list, wall: float) -> "tuple[dict, float, list]":
    """Partition [0, wall] across phase intervals: a sweep assigns each
    elementary segment to the highest-priority phase active in it, so the
    per-phase sums plus the residual ``other`` equal ``wall`` exactly.
    Also returns the assigned timeline as merged ``(start, end, phase)``
    segments (gaps omitted) — the pod lens ships these so a cross-host
    merge can draw phase-colored bars without re-shipping raw rings."""
    marks: list = []
    for s, e, ph in intervals:
        s = min(max(s, 0.0), wall)
        e = min(max(e, 0.0), wall)
        if e > s:
            marks.append((s, 1, ph))
            marks.append((e, -1, ph))
    phases = {ph: 0.0 for ph in PHASES}
    if not marks:
        return phases, wall, []
    marks.sort(key=lambda m: m[0])
    active = {ph: 0 for ph in PHASES}
    other = 0.0
    prev = 0.0
    segments: list = []
    i, n = 0, len(marks)
    while i < n:
        t = marks[i][0]
        if t > prev:
            best, bp = "", -1
            for ph, count in active.items():
                if count > 0 and _PRIORITY[ph] > bp:
                    best, bp = ph, _PRIORITY[ph]
            if best:
                phases[best] += t - prev
                if segments and segments[-1][2] == best \
                        and segments[-1][1] == prev:
                    segments[-1][1] = t
                else:
                    segments.append([prev, t, best])
            else:
                other += t - prev
            prev = t
        while i < n and marks[i][0] == t:
            active[marks[i][2]] += marks[i][1]
            i += 1
    if wall > prev:
        other += wall - prev
    return phases, other, segments


def analyze(tf: TaskFlight, *, stall_ttfb_s: float = STALL_TTFB_S,
            max_waterfall: int = 256, max_segments: int = 256) -> dict:
    """Fold a task's event ring into the phase breakdown + per-piece
    waterfall. Pure function of the ring — safe to call on a live task
    (the in-flight tail classifies as stall/sched_wait as appropriate)."""
    events = tf.events()
    wall = tf.wall_s()
    intervals: list = []          # (start_s, end_s, phase)
    open_req: dict = {}           # piece -> [t_req, t_first_byte, parent]
    open_marks: dict = {}         # paired-mark key -> t
    rows: dict = {}               # piece -> waterfall row
    sched_open: "float | None" = None

    def row_for(piece: int) -> dict:
        row = rows.get(piece)
        if row is None:
            row = rows[piece] = {
                "piece": piece, "attempts": 0, "parent": "",
                "t_request": -1.0, "t_first_byte": -1.0, "t_landed": -1.0,
                "status": "pending", "reason": "", "cost_ms": 0}
        return row

    for t, code, piece, aux, note in events:
        if code in (EV_REGISTER, EV_RESCHEDULE):
            if sched_open is None:
                sched_open = t
        elif code in (EV_SCHEDULED, EV_SCHED_ANSWER, EV_SCHED_PUSH):
            if sched_open is not None:
                intervals.append((sched_open, t, "sched_wait"))
                sched_open = None
        elif code == EV_REQUEST:
            open_req[piece] = [t, -1.0, note]
            row = row_for(piece)
            row["attempts"] += 1
            row["parent"] = note
            row["t_request"] = t
            row["t_first_byte"] = row["t_landed"] = -1.0
        elif code == EV_FIRST_BYTE:
            r = open_req.get(piece)
            if r is not None and r[1] < 0:
                r[1] = t
            if piece in rows:
                rows[piece]["t_first_byte"] = t
        elif code in (EV_LANDED, EV_FAILED):
            r = open_req.pop(piece, None)
            row = row_for(piece)
            if code == EV_LANDED:
                row["status"] = "ok"
                row["t_landed"] = t
                row["cost_ms"] = int(aux)
            else:
                row["status"] = "failed"
                row["reason"] = note
            if r is None:
                # Landed without a recorded request (native span interior,
                # local import): back out the interval from the cost.
                if code == EV_LANDED and aux > 0:
                    phase = "ici" if note == "intra" else "dcn"
                    intervals.append((max(0.0, t - aux / 1000.0), t, phase))
                continue
            t_req, t_fb = r[0], r[1]
            if code == EV_FAILED and note == "stall":
                intervals.append((t_req, t, "stall"))
                continue
            phase = "ici" if (code == EV_LANDED and note == "intra") \
                else "dcn"
            if t_fb >= 0 and (t_fb - t_req) > stall_ttfb_s:
                intervals.append((t_req, t_fb, "stall"))
                intervals.append((t_fb, t, phase))
            else:
                intervals.append((t_req, t, phase))
        elif code in (EV_DELTA_REUSE, EV_DELTA_FETCH):
            # Delta tasks: local base copies book as store (host-local
            # work), ranged-span pulls as dcn — so --explain separates
            # local-copy time from wire time while the partition stays
            # wall-time-exact (cost-backed intervals like source_landed).
            if aux > 0:
                phase = "store" if code == EV_DELTA_REUSE else "dcn"
                intervals.append((max(0.0, t - aux / 1000.0), t, phase))
        elif code == EV_SOURCE_LANDED:
            intervals.append((max(0.0, t - aux / 1000.0), t, "origin"))
            row = row_for(piece)
            row["status"] = "ok"
            row["parent"] = "origin"
            row["t_landed"] = t
            row["cost_ms"] = int(aux)
        elif code == EV_STORE_START:
            open_marks[("store", piece)] = t
        elif code == EV_STORED:
            t0 = open_marks.pop(("store", piece), None)
            if t0 is not None:
                intervals.append((t0, t, "store"))
        elif code == EV_VERIFY_START:
            open_marks["verify"] = t
        elif code == EV_VERIFIED:
            t0 = open_marks.pop("verify", None)
            if t0 is not None:
                intervals.append((t0, t, "verify"))
        elif code == EV_HBM_START:
            open_marks[("hbm", piece)] = t
        elif code == EV_HBM_LANDED:
            t0 = open_marks.pop(("hbm", piece), None)
            if t0 is not None:
                intervals.append((t0, t, "ici"))

    # Tails: a request still open at the end of the timeline is the
    # black-box case — the piece never came back. Beyond the first-byte
    # threshold that is a stall, not transfer time.
    for piece, (t_req, t_fb, _parent) in open_req.items():
        if wall - t_req > stall_ttfb_s:
            intervals.append((t_req, wall, "stall"))
        else:
            intervals.append((t_req, wall, "dcn"))
    if sched_open is not None:
        intervals.append((sched_open, wall, "sched_wait"))

    phases, other, segments = _fold_phases(intervals, wall)
    dominant = ""
    if any(v > 0 for v in phases.values()):
        dominant = max(PHASES, key=lambda p: phases[p])

    ordered = [rows[k] for k in sorted(rows)]
    truncated = len(ordered) > max_waterfall
    counts: dict = {}
    runtime: dict = {}
    for _t, code, _p, aux, _n in events:
        name = EVENT_NAMES.get(code, str(code))
        counts[name] = counts.get(name, 0) + 1
        if code in _RUNTIME_EVENTS:
            r = runtime.get(name)
            if r is None:
                r = runtime[name] = {"count": 0, "max_s": 0.0, "total_s": 0.0}
            r["count"] += 1
            r["total_s"] += aux
            if aux > r["max_s"]:
                r["max_s"] = aux
    for r in runtime.values():
        r["max_s"] = round(r["max_s"], 4)
        r["total_s"] = round(r["total_s"], 4)
    return {
        "task_id": tf.task_id,
        "state": tf.state,
        "note": tf.note,
        "started_at": tf.start_wall,
        "wall_s": round(wall, 6),
        "phases": {ph: round(v, 6) for ph, v in phases.items()},
        "other_s": round(other, 6),
        "dominant_phase": dominant,
        "segments": [[round(s, 6), round(e, 6), ph]
                     for s, e, ph in segments[:max_segments]],
        "segments_truncated": len(segments) > max_segments,
        "events": tf.events_total,
        "events_dropped": tf.events_dropped,
        "event_counts": counts,
        "runtime": runtime,
        "pieces": ordered[:max_waterfall],
        "pieces_truncated": truncated,
    }


def runtime_advisory(report: dict) -> str:
    """One-line loop-lag/GC advisory from an ``analyze()`` report's
    runtime-interference events, or "" when the runtime stayed quiet.
    Rendered under the --explain waterfall so a stall phase caused by a
    wedged loop or a GC storm names its culprit."""
    rt = report.get("runtime") or {}
    parts = []
    ll = rt.get("loop_lag")
    if ll:
        parts.append(f"event loop wedged {ll['count']}x "
                     f"(max {ll['max_s']:.2f}s, {ll['total_s']:.2f}s total)")
    gp = rt.get("gc_pause")
    if gp:
        parts.append(f"gc paused {gp['count']}x "
                     f"(max {gp['max_s']:.2f}s, {gp['total_s']:.2f}s total)")
    if not parts:
        return ""
    return ("runtime interference: " + ", ".join(parts) +
            " during this task — see /debug/prof")


def render_waterfall(report: dict) -> str:
    """Text rendering of an ``analyze()`` report: phase bars + per-piece
    waterfall. The SAME renderer backs ``/debug/flight/<id>?format=text``
    and ``dfget --explain`` so the two can never diverge."""
    wall = report["wall_s"] or 1e-9
    width = 30
    lines = [
        f"task {report['task_id'][:40]} state={report['state']} "
        f"wall={report['wall_s']:.3f}s "
        f"dominant={report['dominant_phase'] or '-'}",
        "phase breakdown:",
    ]
    entries = [(ph, report["phases"].get(ph, 0.0)) for ph in PHASES]
    entries.append(("other", report.get("other_s", 0.0)))
    for ph, v in entries:
        bar = "#" * int(round(width * v / wall))
        lines.append(f"  {ph:<10} {v:8.3f}s {100 * v / wall:5.1f}% {bar}")
    advisory = runtime_advisory(report)
    if advisory:
        lines.append(advisory)
    pieces = report.get("pieces") or []
    suffix = " (truncated)" if report.get("pieces_truncated") else ""
    lines.append(f"pieces: {len(pieces)}{suffix}")
    for row in pieces:
        start = row["t_request"] if row["t_request"] >= 0 else row["t_landed"]
        end = row["t_landed"] if row["t_landed"] >= 0 else start
        if start < 0:
            continue
        lead = int(width * min(start, wall) / wall)
        span = max(1, int(width * max(0.0, end - start) / wall))
        bar = ("." * lead + "#" * span)[:width]
        extra = f" reason={row['reason']}" if row["reason"] else ""
        lines.append(
            f"  p{row['piece']:<5} {bar:<{width}} +{start:7.3f}s "
            f"{max(0.0, end - start) * 1000:7.1f}ms "
            f"x{row['attempts']} {row['status']}{extra} {row['parent']}")
    return "\n".join(lines)


# --------------------------------------------------------------------- #
# Flight digest: the compact, bounded form that ships off-host
# --------------------------------------------------------------------- #

# Hard byte budget for one shipped digest (serialized JSON). The daemon
# attaches one per task to its terminal announce message, so the bound is
# per TASK, not per piece — podlens_bench publishes the measured maximum.
DIGEST_MAX_BYTES = 16384

# Compact piece row order inside a digest (arrays, not dicts — at 64
# pieces the keys would dominate the byte budget):
# [piece, attempts, t_request, t_first_byte, t_landed, ok, reason, parent]
DIGEST_PIECE_FIELDS = ("piece", "attempts", "t_request", "t_first_byte",
                       "t_landed", "ok", "reason", "parent")


def _digest_encoded_len(d: dict) -> int:
    return len(json.dumps(d, separators=(",", ":")))


def digest(tf: TaskFlight, *, max_bytes: int = DIGEST_MAX_BYTES,
           max_pieces: int = 64, max_events: int = 96,
           max_segments: int = 64,
           clock_samples: "list | None" = None) -> dict:
    """Fold a task's ring into the compact digest the daemon ships to the
    scheduler on task completion/failure: phase totals + merged phase
    segments + a truncated piece waterfall + the newest named events,
    hard-capped at ``max_bytes`` of serialized JSON (pieces, events and
    segments are halved until the cap holds). ``clock_samples`` carries
    the announce-stream round-trip samples ([t0, t1, sched_echo] triples
    on this host's anchored wall clock) the scheduler's clock aligner
    consumes."""
    report = analyze(tf, max_waterfall=max_pieces,
                     max_segments=max_segments)
    pieces = [[r["piece"], r["attempts"], round(r["t_request"], 4),
               round(r["t_first_byte"], 4), round(r["t_landed"], 4),
               1 if r["status"] == "ok" else 0, r["reason"],
               r["parent"]] for r in report["pieces"]]
    events = [[round(t, 4), EVENT_NAMES.get(code, str(code)), piece,
               note] for t, code, piece, _aux, note
              in tf.events()[-max_events:]]
    d = {
        "v": 1,
        "task_id": tf.task_id,
        "state": tf.state,
        "note": tf.note[:200],
        "start_wall": round(tf.start_wall, 6),
        "wall_s": report["wall_s"],
        "phases": report["phases"],
        "other_s": report["other_s"],
        "dominant_phase": report["dominant_phase"],
        "segments": report["segments"],
        "pieces": pieces,
        "pieces_total": len(report["pieces"]),
        "pieces_truncated": report["pieces_truncated"],
        "events": events,
        "events_total": tf.events_total,
        "events_dropped": tf.events_dropped,
    }
    if clock_samples:
        d["clock"] = [[round(t0, 6), round(t1, 6), round(echo, 6)]
                      for t0, t1, echo in clock_samples[-4:]]
    # Byte cap: drop detail (events first — the segments/pieces carry the
    # analytic payload), never the phase totals.
    size = _digest_encoded_len(d)
    while size > max_bytes and (d["events"] or len(d["pieces"]) > 8
                                or len(d["segments"]) > 16):
        if d["events"]:
            d["events"] = d["events"][len(d["events"]) // 2:] \
                if len(d["events"]) > 8 else []
        elif len(d["pieces"]) > 8:
            d["pieces"] = d["pieces"][:len(d["pieces"]) // 2]
            d["pieces_truncated"] = True
        else:
            d["segments"] = d["segments"][:len(d["segments"]) // 2]
        size = _digest_encoded_len(d)
    d["bytes"] = size
    return d


def digest_piece_rows(d: dict) -> list:
    """Expand a digest's compact piece arrays back into dict rows."""
    return [dict(zip(DIGEST_PIECE_FIELDS, row))
            for row in d.get("pieces") or []]


# --------------------------------------------------------------------- #
# Recorder: the bounded per-process task index
# --------------------------------------------------------------------- #

class FlightRecorder:
    """Bounded index of TaskFlights. Eviction prefers finished tasks;
    the caps make "always-on" safe (memory is O(max_tasks * capacity)
    tuples regardless of how many tasks a daemon serves)."""

    def __init__(self, *, capacity: int = 2048, max_tasks: int = 128,
                 dump_dir: str = "", keep_bundles: int = 32,
                 wall_offset: float = 0.0):
        self.capacity = capacity
        self.max_tasks = max_tasks
        self.dump_dir = dump_dir
        self.keep_bundles = keep_bundles
        # Chaos/test knob: skew every wall stamp this recorder's flights
        # report (start_wall, clock samples) by a known amount — what the
        # pod-lens alignment e2e injects and must then recover.
        self.wall_offset = wall_offset
        # Latest fleet-scorecard row the scheduler returned for THIS host
        # (announcer stashes it each announce); embedded into post-mortem
        # bundles so a failure autopsy carries the subject host's
        # fleet-wide standing at failure time.
        self.scorecard_snapshot: dict = {}
        # Runtime observatory (pkg/prof), when this role armed one: its
        # pruned snapshot rides along in post-mortem bundles so a failed
        # task's autopsy shows what the PROCESS was doing, not just what
        # the task saw.
        self.runtime = None
        self._tasks: "OrderedDict[str, TaskFlight]" = OrderedDict()
        self._lock = threading.Lock()

    def task(self, task_id: str) -> TaskFlight:
        tf = self._tasks.get(task_id)
        if tf is not None:
            return tf
        with self._lock:
            tf = self._tasks.get(task_id)
            if tf is None:
                while len(self._tasks) >= self.max_tasks:
                    self._evict_one()
                tf = self._tasks[task_id] = TaskFlight(
                    task_id, self.capacity,
                    wall_offset=self.wall_offset)
        return tf

    def _evict_one(self) -> None:
        for tid, tf in self._tasks.items():
            if tf.state != "running":
                del self._tasks[tid]
                return
        self._tasks.popitem(last=False)

    def get(self, task_id: str) -> "TaskFlight | None":
        return self._tasks.get(task_id)

    def stamp_running(self, code: int, aux: float = 0.0,
                      note: str = "") -> None:
        """Record one event into EVERY running flight — how pkg/prof
        stamps runtime interference (a wedged loop, a slow GC pause)
        into the task windows it overlapped. Bounded by max_tasks."""
        for tf in list(self._tasks.values()):
            if tf.state == "running":
                tf.record(code, -1, aux, note)

    def summary(self) -> list:
        return [{"task_id": tf.task_id, "state": tf.state,
                 "wall_s": round(tf.wall_s(), 3),
                 "events": tf.events_total,
                 "events_dropped": tf.events_dropped}
                for tf in self._tasks.values()]

    def finish_task(self, task_id: str, state: str,
                    note: str = "") -> "TaskFlight | None":
        """Terminal transition: stamps the wall clock, feeds the phase
        histograms, and (on failure, with a dump dir configured) writes
        the post-mortem bundle. Idempotent per task."""
        tf = self._tasks.get(task_id)
        if tf is None or tf.state != "running":
            return tf
        tf.finish(state, note)
        report = analyze(tf)
        for ph in PHASES:
            v = report["phases"][ph]
            if v > 0:
                PHASE_SECONDS.labels(ph).observe(v)
        if state == "failed" and self.dump_dir:
            self._dump(tf, report)
        return tf

    def _dump(self, tf: TaskFlight, report: dict) -> None:
        """Post-mortem bundle: the autopsy + the raw (named) event
        timeline + this host's latest fleet-scorecard row, gzipped
        (bundles are JSON text — gzip is ~10x on event timelines), pruned
        to ``keep_bundles`` files. Best-effort — a full disk must never
        fail the task path that triggered the dump."""
        try:
            os.makedirs(self.dump_dir, exist_ok=True)
            path = os.path.join(
                self.dump_dir,
                f"flight-{tf.task_id[:16]}-"
                f"{int(time.time() * 1000)}.json.gz")
            bundle = {
                "report": report,
                "events": [
                    {"t": round(t, 6),
                     "event": EVENT_NAMES.get(code, str(code)),
                     "piece": piece, "aux": aux, "note": note}
                    for t, code, piece, aux, note in tf.events()],
            }
            if self.scorecard_snapshot:
                bundle["scorecard"] = dict(self.scorecard_snapshot)
            if self.runtime is not None:
                # Pruned prof snapshot + loop-lag/GC summary: best-effort
                # like the rest of the dump path.
                try:
                    bundle["runtime"] = self.runtime.postmortem()
                except Exception:
                    log.warning("runtime snapshot for bundle failed",
                                exc_info=True)
            with gzip.open(path, "wt") as f:
                json.dump(bundle, f)
            log.info("flight post-mortem dumped", task=tf.task_id[:16],
                     path=path)
            self._prune()
        except OSError:
            pass

    def _prune(self) -> None:
        """Newest-``keep_bundles`` rotation: a crash-looping task dumping
        a bundle per attempt must not grow the log volume forever. mtime
        orders; the filename's ms stamp breaks same-second ties. Counts
        ``.json`` (pre-gzip era) and ``.json.gz`` bundles alike — one
        budget, not one per extension."""

        def stamp(path: str) -> int:
            tail = path.rsplit("-", 1)[-1]
            for suffix in (".json.gz", ".json"):
                if tail.endswith(suffix):
                    tail = tail[:-len(suffix)]
                    break
            try:
                return int(tail)
            except ValueError:
                return 0

        try:
            bundles = sorted(
                (os.path.join(self.dump_dir, name)
                 for name in os.listdir(self.dump_dir)
                 if name.startswith("flight-")
                 and name.endswith((".json", ".json.gz"))),
                key=lambda p: (os.path.getmtime(p), stamp(p)))
            drop = bundles[:-self.keep_bundles] if self.keep_bundles > 0 \
                else bundles
            for path in drop:
                os.unlink(path)
        except OSError:
            pass


_RECORDER = FlightRecorder()


def recorder() -> FlightRecorder:
    return _RECORDER


def for_task(task_id: str) -> TaskFlight:
    """Get-or-create the default recorder's flight for ``task_id`` — the
    one call every instrumented choke point makes."""
    return _RECORDER.task(task_id)


def get(task_id: str) -> "TaskFlight | None":
    return _RECORDER.get(task_id)


# --------------------------------------------------------------------- #
# Pod-level aggregation (scheduler side)
# --------------------------------------------------------------------- #

class PodAggregator:
    """Per-task, per-host phase attribution from the piece reports'
    ``timings`` (proto/wire PIECE), plus typed failure / quarantine
    correlation — the ``/debug/pod/<task_id>`` straggler view. Bounded
    like the recorder: the oldest task aggregate is evicted past
    ``max_tasks``."""

    _PHASE_KEYS = ("dcn", "stall", "store")

    def __init__(self, max_tasks: int = 256):
        self.max_tasks = max_tasks
        self._tasks: "OrderedDict[str, dict]" = OrderedDict()

    def _task(self, task_id: str) -> dict:
        entry = self._tasks.get(task_id)
        if entry is None:
            while len(self._tasks) >= self.max_tasks:
                self._tasks.popitem(last=False)
            entry = self._tasks[task_id] = {"hosts": {}, "quarantine": []}
        return entry

    def _host(self, task_id: str, host_id: str) -> dict:
        hosts = self._task(task_id)["hosts"]
        h = hosts.get(host_id)
        if h is None:
            h = hosts[host_id] = {
                "pieces": 0,
                "ms": {k: 0 for k in self._PHASE_KEYS},
                "failures": {},
            }
        return h

    def note_piece(self, task_id: str, host_id: str,
                   timings: "dict | None", cost_ms: int = 0) -> None:
        h = self._host(task_id, host_id)
        h["pieces"] += 1
        ms = h["ms"]
        if timings:
            ms["dcn"] += int(timings.get("dcn_ms", 0) or 0)
            ms["stall"] += int(timings.get("stall_ms", 0) or 0)
            ms["store"] += int(timings.get("store_ms", 0) or 0)
        else:
            # Legacy report (no per-phase split): the whole cost is
            # transfer time.
            ms["dcn"] += int(cost_ms or 0)

    def note_pieces(self, task_id: str, host_id: str, n: int,
                    phase_ms) -> None:
        """Batch form of note_piece for the packed ingest fast path:
        ``n`` pieces with pre-summed (dcn, stall, store) milliseconds —
        untimed pieces already folded their whole cost into dcn
        (proto/reportcodec computes the sums with note_piece's exact
        semantics, so N note_piece calls and one note_pieces call land
        the same aggregate)."""
        h = self._host(task_id, host_id)
        h["pieces"] += n
        ms = h["ms"]
        ms["dcn"] += phase_ms[0]
        ms["stall"] += phase_ms[1]
        ms["store"] += phase_ms[2]

    def note_failure(self, task_id: str, host_id: str, reason: str) -> None:
        h = self._host(task_id, host_id)
        h["failures"][reason] = h["failures"].get(reason, 0) + 1

    def note_quarantine(self, task_id: str, host_id: str,
                        reason: str) -> None:
        q = self._task(task_id)["quarantine"]
        q.append({"host": host_id, "reason": reason})
        del q[:-64]   # bounded

    def report(self, task_id: str) -> "dict | None":
        entry = self._tasks.get(task_id)
        if entry is None:
            return None
        hosts = []
        totals = {k: 0 for k in self._PHASE_KEYS}
        for host_id, h in entry["hosts"].items():
            total_ms = sum(h["ms"].values())
            for k in self._PHASE_KEYS:
                totals[k] += h["ms"][k]
            dominant = max(self._PHASE_KEYS, key=lambda k: h["ms"][k]) \
                if total_ms else ""
            hosts.append({
                "host": host_id,
                "pieces": h["pieces"],
                "ms": dict(h["ms"]),
                "mean_piece_ms": round(total_ms / h["pieces"], 2)
                if h["pieces"] else 0.0,
                "dominant_phase": dominant,
                "failures": dict(h["failures"]),
            })
        hosts.sort(key=lambda h: -h["mean_piece_ms"])
        slowest = hosts[0]["host"] if hosts and hosts[0]["mean_piece_ms"] > 0 \
            else ""
        dominant = max(self._PHASE_KEYS, key=lambda k: totals[k]) \
            if any(totals.values()) else ""
        return {
            "task_id": task_id,
            "hosts": hosts,
            "slowest_host": slowest,
            "dominant_phase": dominant,
            "quarantine": list(entry["quarantine"]),
        }
