"""Digest type, parser and hashing readers.

Reference: pkg/digest/digest.go:58-158 (algorithm:encoded string form,
parser, validation) and pkg/digest/digest_reader.go (readers that hash as
they stream). We additionally expose crc32c — used by piece verification on
the TPU-sidecar path — with backend selection in strict preference order
(``crc32c_backend()`` names the one in use):

  1. ``native``  — the C++ engine's SIMD kernel (dragonfly2_tpu/native,
     hardware CRC32C instructions); accepts any buffer zero-copy and
     releases the GIL for the call.
  2. ``google-crc32c`` — the C extension's SIMD kernel; ~2x the native
     kernel on ``bytes`` but its converter only takes read-only bytes, so
     writable pooled views pay one bounded slice-copy.
  3. ``python`` — table-driven pure Python (correctness backstop only:
     ~3 orders of magnitude slower; the hash-fallback round in
     benchmarks/ingest_micro.py keeps the gap honest).

Large buffers hash in bounded slices (``_CRC_SLICE``) so no single C call
holds memory/GIL attention for tens of MB, and the per-slice copies of
backend 2 stay allocator-friendly.
"""

from __future__ import annotations

import hashlib
import re
from dataclasses import dataclass
from typing import BinaryIO, Iterable

ALGORITHM_MD5 = "md5"
ALGORITHM_SHA1 = "sha1"
ALGORITHM_SHA256 = "sha256"
ALGORITHM_SHA512 = "sha512"
ALGORITHM_CRC32C = "crc32c"

_ALGORITHMS = (ALGORITHM_MD5, ALGORITHM_SHA1, ALGORITHM_SHA256, ALGORITHM_SHA512, ALGORITHM_CRC32C)

_ENCODED_RE = {
    ALGORITHM_MD5: re.compile(r"^[a-f0-9]{32}$"),
    ALGORITHM_SHA1: re.compile(r"^[a-f0-9]{40}$"),
    ALGORITHM_SHA256: re.compile(r"^[a-f0-9]{64}$"),
    ALGORITHM_SHA512: re.compile(r"^[a-f0-9]{128}$"),
    ALGORITHM_CRC32C: re.compile(r"^[a-f0-9]{8}$"),
}


class InvalidDigestError(ValueError):
    pass


@dataclass(frozen=True)
class Digest:
    """A digest in ``algorithm:encoded`` string form (reference digest.go:58-76)."""

    algorithm: str
    encoded: str

    def __post_init__(self):
        if self.algorithm not in _ALGORITHMS:
            raise InvalidDigestError(f"unsupported digest algorithm {self.algorithm!r}")
        if not _ENCODED_RE[self.algorithm].match(self.encoded):
            raise InvalidDigestError(f"invalid {self.algorithm} encoded value {self.encoded!r}")

    def __str__(self) -> str:
        return f"{self.algorithm}:{self.encoded}"


def parse(value: str) -> Digest:
    """Parse ``algorithm:encoded`` (reference digest.go:120-158)."""
    algorithm, sep, encoded = value.partition(":")
    if not sep:
        raise InvalidDigestError(f"digest {value!r} missing ':' separator")
    return Digest(algorithm, encoded.lower())


def _crc32c_py(data: bytes, crc: int = 0) -> int:
    """Pure-python CRC-32C (Castagnoli), table-driven fallback."""
    table = _crc32c_table()
    crc ^= 0xFFFFFFFF
    for b in data:
        crc = table[(crc ^ b) & 0xFF] ^ (crc >> 8)
    return crc ^ 0xFFFFFFFF


_CRC32C_TABLE: list[int] | None = None


def _crc32c_table() -> list[int]:
    global _CRC32C_TABLE
    if _CRC32C_TABLE is None:
        poly = 0x82F63B78
        table = []
        for i in range(256):
            crc = i
            for _ in range(8):
                crc = (crc >> 1) ^ poly if crc & 1 else crc >> 1
            table.append(crc)
        _CRC32C_TABLE = table
    return _CRC32C_TABLE


def _native_crc32c():
    try:
        from dragonfly2_tpu.native import binding

        return binding.crc32c
    except Exception:
        return None


def _google_crc32c():
    """google-crc32c's C kernel, adapted to arbitrary buffers. Its argument
    converter only accepts read-only bytes-likes (bytes, not bytearray or
    memoryview), so non-bytes input pays one copy per slice — still ~GB/s
    where the pure-Python table is ~MB/s."""
    try:
        import google_crc32c

        if google_crc32c.implementation != "c":
            return None   # the package's own Python fallback is no faster
        google_crc32c.extend(0, b"probe")
    except Exception:
        return None

    def _impl(data, crc: int = 0) -> int:
        if not isinstance(data, bytes):
            data = bytes(data)
        return google_crc32c.extend(crc, data)

    return _impl


_crc32c_impl = None
_crc32c_backend_name = ""
_CRC_SLICE = 4 << 20


def _select_crc32c():
    global _crc32c_impl, _crc32c_backend_name
    impl = _native_crc32c()
    if impl is not None:
        _crc32c_backend_name = "native"
    else:
        impl = _google_crc32c()
        if impl is not None:
            _crc32c_backend_name = "google-crc32c"
        else:
            impl = _crc32c_py
            _crc32c_backend_name = "python"
    _crc32c_impl = impl
    return impl


def crc32c_backend() -> str:
    """Name of the selected CRC-32C backend (see module docstring for the
    preference order): ``native`` | ``google-crc32c`` | ``python``."""
    if _crc32c_impl is None:
        _select_crc32c()
    return _crc32c_backend_name


def crc32c(data, crc: int = 0) -> int:
    """CRC-32C over any bytes-like, buffer-sliced through the best
    available backend (module docstring: native SIMD > google-crc32c >
    Python table)."""
    impl = _crc32c_impl or _select_crc32c()
    n = data.nbytes if isinstance(data, memoryview) else len(data)
    if n <= _CRC_SLICE:
        return impl(data, crc)
    mv = data if isinstance(data, memoryview) else memoryview(data)
    for off in range(0, n, _CRC_SLICE):
        crc = impl(mv[off:off + _CRC_SLICE], crc)
    return crc


class _Crc32cHasher:
    """hashlib-like interface over crc32c."""

    name = ALGORITHM_CRC32C
    digest_size = 4

    def __init__(self):
        self._crc = 0

    def update(self, data: bytes) -> None:
        self._crc = crc32c(data, self._crc)

    def hexdigest(self) -> str:
        return f"{self._crc:08x}"

    def digest(self) -> bytes:
        return self._crc.to_bytes(4, "big")


def preferred_piece_algorithm() -> str:
    """Per-piece digest algorithm for newly produced pieces: crc32c
    whenever a C-speed backend exists — the native library (fused
    checksum+write, and cheap enough to re-verify on-device —
    ops/checksum.py) or google-crc32c (~11 GB/s vs md5's ~0.6) — else md5
    like the reference (local_storage.go WritePiece)."""
    if crc32c_backend() != "python":
        return ALGORITHM_CRC32C
    return ALGORITHM_MD5


def new_hasher(algorithm: str):
    if algorithm == ALGORITHM_CRC32C:
        return _Crc32cHasher()
    if algorithm in (ALGORITHM_MD5, ALGORITHM_SHA1, ALGORITHM_SHA256, ALGORITHM_SHA512):
        return hashlib.new(algorithm)
    raise InvalidDigestError(f"unsupported digest algorithm {algorithm!r}")


def hash_bytes(algorithm: str, data: bytes) -> Digest:
    h = new_hasher(algorithm)
    h.update(data)
    return Digest(algorithm, h.hexdigest())


def sha256_from_strings(*values: str) -> str:
    """SHA256 over concatenated strings (reference pkg/digest SHA256FromStrings,
    used by idgen task IDs — pkg/idgen/task_id.go:50,81,100)."""
    h = hashlib.sha256()
    for v in values:
        h.update(v.encode("utf-8"))
    return h.hexdigest()


def hash_file(algorithm: str, path: str, chunk_size: int = 4 * 1024 * 1024) -> Digest:
    h = new_hasher(algorithm)
    with open(path, "rb") as f:
        while True:
            chunk = f.read(chunk_size)
            if not chunk:
                break
            h.update(chunk)
    return Digest(algorithm, h.hexdigest())


class HashingReader:
    """Wraps a binary stream, hashing while reading
    (reference pkg/digest/digest_reader.go)."""

    def __init__(self, raw: BinaryIO, algorithm: str = ALGORITHM_MD5):
        self._raw = raw
        self._hasher = new_hasher(algorithm)
        self._algorithm = algorithm

    def read(self, n: int = -1) -> bytes:
        data = self._raw.read(n)
        if data:
            self._hasher.update(data)
        return data

    def digest(self) -> Digest:
        return Digest(self._algorithm, self._hasher.hexdigest())


def verify_chunks(algorithm: str, expected: Digest, chunks: Iterable[bytes]) -> bool:
    h = new_hasher(algorithm)
    for c in chunks:
        h.update(c)
    return h.hexdigest() == expected.encoded
