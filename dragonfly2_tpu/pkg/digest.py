"""Digest type, parser and hashing readers.

Reference: pkg/digest/digest.go:58-158 (algorithm:encoded string form,
parser, validation) and pkg/digest/digest_reader.go (readers that hash as
they stream). We additionally expose crc32c — used by piece verification on
the TPU-sidecar path — accelerated by the C++ native library when built
(dragonfly2_tpu/native), with a pure-Python table fallback.
"""

from __future__ import annotations

import hashlib
import re
from dataclasses import dataclass
from typing import BinaryIO, Iterable

ALGORITHM_MD5 = "md5"
ALGORITHM_SHA1 = "sha1"
ALGORITHM_SHA256 = "sha256"
ALGORITHM_SHA512 = "sha512"
ALGORITHM_CRC32C = "crc32c"

_ALGORITHMS = (ALGORITHM_MD5, ALGORITHM_SHA1, ALGORITHM_SHA256, ALGORITHM_SHA512, ALGORITHM_CRC32C)

_ENCODED_RE = {
    ALGORITHM_MD5: re.compile(r"^[a-f0-9]{32}$"),
    ALGORITHM_SHA1: re.compile(r"^[a-f0-9]{40}$"),
    ALGORITHM_SHA256: re.compile(r"^[a-f0-9]{64}$"),
    ALGORITHM_SHA512: re.compile(r"^[a-f0-9]{128}$"),
    ALGORITHM_CRC32C: re.compile(r"^[a-f0-9]{8}$"),
}


class InvalidDigestError(ValueError):
    pass


@dataclass(frozen=True)
class Digest:
    """A digest in ``algorithm:encoded`` string form (reference digest.go:58-76)."""

    algorithm: str
    encoded: str

    def __post_init__(self):
        if self.algorithm not in _ALGORITHMS:
            raise InvalidDigestError(f"unsupported digest algorithm {self.algorithm!r}")
        if not _ENCODED_RE[self.algorithm].match(self.encoded):
            raise InvalidDigestError(f"invalid {self.algorithm} encoded value {self.encoded!r}")

    def __str__(self) -> str:
        return f"{self.algorithm}:{self.encoded}"


def parse(value: str) -> Digest:
    """Parse ``algorithm:encoded`` (reference digest.go:120-158)."""
    algorithm, sep, encoded = value.partition(":")
    if not sep:
        raise InvalidDigestError(f"digest {value!r} missing ':' separator")
    return Digest(algorithm, encoded.lower())


def _crc32c_py(data: bytes, crc: int = 0) -> int:
    """Pure-python CRC-32C (Castagnoli), table-driven fallback."""
    table = _crc32c_table()
    crc ^= 0xFFFFFFFF
    for b in data:
        crc = table[(crc ^ b) & 0xFF] ^ (crc >> 8)
    return crc ^ 0xFFFFFFFF


_CRC32C_TABLE: list[int] | None = None


def _crc32c_table() -> list[int]:
    global _CRC32C_TABLE
    if _CRC32C_TABLE is None:
        poly = 0x82F63B78
        table = []
        for i in range(256):
            crc = i
            for _ in range(8):
                crc = (crc >> 1) ^ poly if crc & 1 else crc >> 1
            table.append(crc)
        _CRC32C_TABLE = table
    return _CRC32C_TABLE


def _native_crc32c():
    try:
        from dragonfly2_tpu.native import binding

        return binding.crc32c
    except Exception:
        return None


_crc32c_impl = None


def crc32c(data: bytes, crc: int = 0) -> int:
    """CRC-32C over ``data``; native C++ if available, else Python table."""
    global _crc32c_impl
    if _crc32c_impl is None:
        _crc32c_impl = _native_crc32c() or _crc32c_py
    return _crc32c_impl(data, crc)


class _Crc32cHasher:
    """hashlib-like interface over crc32c."""

    name = ALGORITHM_CRC32C
    digest_size = 4

    def __init__(self):
        self._crc = 0

    def update(self, data: bytes) -> None:
        self._crc = crc32c(data, self._crc)

    def hexdigest(self) -> str:
        return f"{self._crc:08x}"

    def digest(self) -> bytes:
        return self._crc.to_bytes(4, "big")


def preferred_piece_algorithm() -> str:
    """Per-piece digest algorithm for newly produced pieces: hardware crc32c
    via the native library when available (fused checksum+write, and cheap
    enough to re-verify on-device — ops/checksum.py), else md5 like the
    reference (local_storage.go WritePiece)."""
    return ALGORITHM_CRC32C if _native_crc32c() is not None else ALGORITHM_MD5


def new_hasher(algorithm: str):
    if algorithm == ALGORITHM_CRC32C:
        return _Crc32cHasher()
    if algorithm in (ALGORITHM_MD5, ALGORITHM_SHA1, ALGORITHM_SHA256, ALGORITHM_SHA512):
        return hashlib.new(algorithm)
    raise InvalidDigestError(f"unsupported digest algorithm {algorithm!r}")


def hash_bytes(algorithm: str, data: bytes) -> Digest:
    h = new_hasher(algorithm)
    h.update(data)
    return Digest(algorithm, h.hexdigest())


def sha256_from_strings(*values: str) -> str:
    """SHA256 over concatenated strings (reference pkg/digest SHA256FromStrings,
    used by idgen task IDs — pkg/idgen/task_id.go:50,81,100)."""
    h = hashlib.sha256()
    for v in values:
        h.update(v.encode("utf-8"))
    return h.hexdigest()


def hash_file(algorithm: str, path: str, chunk_size: int = 4 * 1024 * 1024) -> Digest:
    h = new_hasher(algorithm)
    with open(path, "rb") as f:
        while True:
            chunk = f.read(chunk_size)
            if not chunk:
                break
            h.update(chunk)
    return Digest(algorithm, h.hexdigest())


class HashingReader:
    """Wraps a binary stream, hashing while reading
    (reference pkg/digest/digest_reader.go)."""

    def __init__(self, raw: BinaryIO, algorithm: str = ALGORITHM_MD5):
        self._raw = raw
        self._hasher = new_hasher(algorithm)
        self._algorithm = algorithm

    def read(self, n: int = -1) -> bytes:
        data = self._raw.read(n)
        if data:
            self._hasher.update(data)
        return data

    def digest(self) -> Digest:
        return Digest(self._algorithm, self._hasher.hexdigest())


def verify_chunks(algorithm: str, expected: Digest, chunks: Iterable[bytes]) -> bool:
    h = new_hasher(algorithm)
    for c in chunks:
        h.update(c)
    return h.hexdigest() == expected.encoded
