"""Cluster control tower: manager-side fleet rollup, event journal, spool.

The scheduler-side telemetry layers (flight recorder, pod lens, fleet
observatory, runtime observatory) all stop at the scheduler boundary and
live in bounded in-memory rings. This module carries a condensed view of
each scheduler's fleet observatory across the keepalive wire and merges it
into one cluster-wide, per-scheduler-attributed picture on the manager:

  FrameBuilder     scheduler side — a bounded compact frame (time-series
                   rollup since the last ship, SLO burn rates, straggler /
                   quarantined host sets, decision-kind counts, resident
                   bytes), hard-capped in bytes with halving-until-fit
                   (the flight-digest discipline). Rides the
                   ``start_keepalive(payload=)`` hook like tenant_burn.
  ClusterSeries    manager side — folds frames into cluster totals with
                   per-scheduler attribution; /debug/cluster*.
  ClusterEventJournal
                   edge-triggered cluster events (keepalive lapse/return,
                   SLO breach, straggler flagged, quarantine storm,
                   admission 429 burst) in a bounded ring, the fleet
                   DecisionLog pattern; /debug/cluster/events.
  TelemetrySpool   compressed frames ring-buffered into the manager's
                   sqlite with a byte budget, so the cluster view and
                   ``?window=`` retrospection survive a manager restart.

A missing or malformed frame must never stall keepalives: every ingest
path is fail-open (the ``ingest_tenant_burn`` discipline), and a
scheduler on an older wire that ships no frames keeps full liveness
semantics — the cluster view marks it ``no_data`` rather than inventing
zeros. benchmarks/cluster_bench.py publishes the paired frame-build +
ingest overhead as BASELINE ``config15_cluster`` (<= 3% budget).
"""

from __future__ import annotations

import json
import time
import zlib
from collections import deque

from dragonfly2_tpu.pkg import dflog, metrics
from dragonfly2_tpu.pkg.fleet import COUNTERS

log = dflog.get("pkg.cluster")

# Hard byte cap on one encoded frame. Keepalives are small control-plane
# messages; the frame must stay a rounding error next to them even on a
# scheduler tracking thousands of hosts.
FRAME_MAX_BYTES = 8192

# Cluster event kinds (the journal rejects everything else so a typo'd
# emitter cannot grow an unbounded label set).
EVENT_KINDS = ("lapse", "return", "slo_breach", "straggler",
               "quarantine_storm", "admission_burst")

FRAME_COUNT = metrics.counter(
    "manager_fleet_frames_total",
    "Fleet telemetry frames arriving on scheduler keepalives, by result "
    "(ok / malformed / error)", ("result",))

SCHEDULERS_GAUGE = metrics.gauge(
    "manager_cluster_schedulers",
    "Schedulers known to the cluster control tower, by state (active / "
    "inactive / no_data — no_data = alive keepalive but no fleet frames, "
    "an older wire)", ("state",))

EVENT_COUNT = metrics.counter(
    "manager_cluster_events_total",
    "Edge-triggered cluster events recorded in the journal, by kind "
    "(lapse / return / slo_breach / straggler / quarantine_storm / "
    "admission_burst)", ("kind",))

SPOOL_GAUGE = metrics.gauge(
    "manager_spool_bytes",
    "Compressed bytes currently held by the durable telemetry spool "
    "(pruned oldest-first to its byte budget)")


def _enc_len(frame: dict) -> int:
    return len(json.dumps(frame, separators=(",", ":")))


# --------------------------------------------------------------------- #
# Scheduler side: the frame builder
# --------------------------------------------------------------------- #

class FrameBuilder:
    """Condenses one scheduler's fleet observatory into a bounded frame.

    ``build()`` is called from the keepalive payload provider at keepalive
    cadence; it reads only O(ring) accessors (``totals()`` /
    ``gauge_column()``) and per-kind decision counts — never the decision
    ring itself — so a frame costs microseconds, not a scan.
    """

    def __init__(self, fleet, *, slo=None, hostname: str = "",
                 quarantined=None, max_bytes: int = FRAME_MAX_BYTES,
                 clock=time.monotonic):
        self.fleet = fleet
        self.slo = slo
        self.hostname = hostname
        self._quarantined = quarantined   # () -> list[str] | None
        self.max_bytes = max_bytes
        self._clock = clock
        self._last_build = 0.0            # monotonic; 0 = never
        self._last_kind_counts: dict = {}
        # resident_bytes() deep-walks every bounded structure — two
        # orders of magnitude above the rest of a build. The structures
        # are preallocated/bounded, so the number moves slowly: refresh
        # at most every RESIDENT_REFRESH_S and ship the cached value.
        self._resident = -1
        self._resident_at = 0.0
        self.built_total = 0

    RESIDENT_REFRESH_S = 60.0

    def build(self) -> "dict | None":
        """One frame covering the window since the previous build (first
        frame: two buckets). Returns None when the observatory is off."""
        if self.fleet is None:
            return None
        series = self.fleet.series
        mono = self._clock()
        if self._last_build:
            window_s = mono - self._last_build
        else:
            window_s = series.bucket_s * 2
        # Clamp to the ring span — a scheduler that slept past its own
        # history can only report what the ring still holds.
        window_s = max(series.bucket_s, min(
            window_s, series.bucket_s * series.n_buckets))
        self._last_build = mono

        totals = series.totals(window_s, COUNTERS)
        counters = {k: (int(v) if v.is_integer() else v)
                    for k, v in totals.items() if v}
        gauges = series.gauges_last(window_s)   # {} when never sampled

        frame = {
            "v": 1,
            "host": self.hostname,
            "ts": round(time.time(), 3),
            "window_s": round(window_s, 3),
            "counters": counters,
            "gauges": gauges,
            "stragglers": sorted(self.fleet.scorecards._stragglers),
            "quarantined": sorted(self._quarantined() or ())
            if self._quarantined is not None else [],
            "decisions": self._decision_delta(),
            "resident_bytes": self._resident_bytes(mono),
        }
        if self.slo is not None:
            rep = self.slo.evaluate()
            frame["slo"] = {
                s["name"]: {
                    "state": s["state"],
                    "burn": max((w["burn_rate"] for w in s["windows"]),
                                default=0.0),
                } for s in rep["slos"]}
            frame["breached"] = rep["breached"]

        # Halving-until-fit (the flight-digest discipline): host sets are
        # the only unbounded-in-principle fields, so they pay first —
        # newest-sorted-first halves keep the frame representative.
        size = _enc_len(frame)
        while size > self.max_bytes and (
                frame["stragglers"] or frame["quarantined"]):
            frame["truncated"] = True
            if len(frame["stragglers"]) >= len(frame["quarantined"]):
                frame["stragglers"] = \
                    frame["stragglers"][:len(frame["stragglers"]) // 2]
            else:
                frame["quarantined"] = \
                    frame["quarantined"][:len(frame["quarantined"]) // 2]
            size = _enc_len(frame)
        if size > self.max_bytes and frame["decisions"]:
            frame["truncated"] = True
            frame["decisions"] = {}
            size = _enc_len(frame)
        frame["bytes"] = size
        self.built_total += 1
        return frame

    def _resident_bytes(self, mono: float) -> int:
        if self._resident < 0 or \
                mono - self._resident_at >= self.RESIDENT_REFRESH_S:
            self._resident = self.fleet.resident_bytes()
            self._resident_at = mono
        return self._resident

    def _decision_delta(self) -> dict:
        """Decision-kind counts since the previous frame — deltas of the
        DecisionLog's per-kind totals, so consecutive frames sum cleanly
        on the manager without double counting."""
        cur = dict(self.fleet.decisions.kind_counts)
        prev = self._last_kind_counts
        self._last_kind_counts = cur
        out = {}
        for kind, n in cur.items():
            d = n - prev.get(kind, 0)
            if d:
                out[kind] = d
        return out


# --------------------------------------------------------------------- #
# Manager side: event journal
# --------------------------------------------------------------------- #

class ClusterEventJournal:
    """Bounded ring of cluster events (one tuple per event, the fleet
    DecisionLog discipline). Query iterates newest-first."""

    __slots__ = ("cap", "_ring", "_n", "_children")

    def __init__(self, cap: int = 1024):
        self.cap = cap
        self._ring: list = [None] * cap
        self._n = 0
        self._children: dict = {}

    def record(self, kind: str, *, scheduler: str = "",
               subject: str = "", detail: str = "") -> None:
        if kind not in EVENT_KINDS:
            return
        self._ring[self._n % self.cap] = (
            time.time(), kind, scheduler, subject, detail)
        self._n += 1
        child = self._children.get(kind)
        if child is None:
            child = self._children[kind] = EVENT_COUNT.labels(kind)
        child.inc()
        log.info("cluster event", kind=kind, scheduler=scheduler,
                 subject=subject, detail=detail)

    @property
    def recorded_total(self) -> int:
        return self._n

    def query(self, *, kind: str = "", scheduler: str = "",
              limit: int = 256, since: float = 0.0,
              before: float = 0.0) -> dict:
        """Newest-first page; ``since``/``before`` are wall-clock bounds
        (half-open [since, before)) and ``since`` terminates the scan
        early — the ring is time-ordered."""
        out = []
        truncated = False
        i = self._n - 1
        oldest = max(0, self._n - self.cap)
        while i >= oldest:
            e = self._ring[i % self.cap]
            i -= 1
            if e is None:
                continue
            ts, k, sched, subject, detail = e
            if since and ts < since:
                break
            if before and ts >= before:
                continue
            if kind and k != kind:
                continue
            if scheduler and sched != scheduler:
                continue
            if len(out) >= limit:
                truncated = True
                break
            out.append({"ts": round(ts, 3), "kind": k,
                        "scheduler": sched, "subject": subject,
                        "detail": detail})
        return {"events": out, "recorded_total": self._n,
                "dropped": max(0, self._n - self.cap),
                "truncated": truncated}


class AdmissionBurstDetector:
    """Edge-triggers one ``admission_burst`` event when REST 429s exceed
    ``threshold`` within ``window_s``, and re-arms once the rate falls
    back under — a storm of push-backs becomes one journal line, not
    one per request."""

    def __init__(self, journal: ClusterEventJournal, *,
                 threshold: int = 10, window_s: float = 10.0,
                 clock=time.monotonic):
        self.journal = journal
        self.threshold = threshold
        self.window_s = window_s
        self._clock = clock
        self._hits: deque = deque()
        self._bursting = False

    def note_429(self, subject: str = "") -> None:
        now = self._clock()
        self._hits.append(now)
        cutoff = now - self.window_s
        while self._hits and self._hits[0] < cutoff:
            self._hits.popleft()
        if len(self._hits) >= self.threshold:
            if not self._bursting:
                self._bursting = True
                self.journal.record(
                    "admission_burst", subject=subject,
                    detail=f"{len(self._hits)} 429s in "
                           f"{self.window_s:.0f}s")
        elif self._bursting and len(self._hits) <= self.threshold // 2:
            self._bursting = False


# --------------------------------------------------------------------- #
# Manager side: durable telemetry spool
# --------------------------------------------------------------------- #

class TelemetrySpool:
    """Compressed frames ring-buffered into the manager's sqlite with a
    byte budget (the SnapshotStore discipline: same embedded backend,
    prune-oldest past the budget). ``load()`` replays the surviving
    window after a manager restart."""

    def __init__(self, db, *, max_bytes: int = 2 * 1024 * 1024):
        self.db = db                      # manager Database (execute())
        self.max_bytes = max_bytes
        self.db.execute(
            "CREATE TABLE IF NOT EXISTS cluster_frames ("
            "  id INTEGER PRIMARY KEY AUTOINCREMENT,"
            "  ts REAL NOT NULL,"
            "  hostname TEXT NOT NULL,"
            "  ip TEXT NOT NULL,"
            "  nbytes INTEGER NOT NULL,"
            "  frame BLOB NOT NULL)")
        row = self.db.execute(
            "SELECT COALESCE(SUM(nbytes), 0) AS b FROM cluster_frames")[0]
        self._bytes = int(row["b"])
        SPOOL_GAUGE.set(self._bytes)

    @property
    def bytes(self) -> int:
        return self._bytes

    def store(self, hostname: str, ip: str, frame: dict) -> None:
        blob = zlib.compress(
            json.dumps(frame, separators=(",", ":")).encode())
        self.db.execute(
            "INSERT INTO cluster_frames (ts, hostname, ip, nbytes, frame) "
            "VALUES (?, ?, ?, ?, ?)",
            (float(frame.get("ts", time.time())), hostname, ip,
             len(blob), blob))
        self._bytes += len(blob)
        while self._bytes > self.max_bytes:
            rows = self.db.execute(
                "SELECT id, nbytes FROM cluster_frames "
                "ORDER BY id LIMIT 64")
            if not rows:
                break
            drop, freed = [], 0
            for r in rows:
                drop.append(r["id"])
                freed += r["nbytes"]
                if self._bytes - freed <= self.max_bytes:
                    break
            qs = ",".join("?" * len(drop))
            self.db.execute(
                f"DELETE FROM cluster_frames WHERE id IN ({qs})", drop)
            self._bytes -= freed
        SPOOL_GAUGE.set(self._bytes)

    def load(self) -> list:
        """Oldest-first (ts, hostname, ip, frame) replay of every spooled
        frame; undecodable rows are skipped, not fatal."""
        out = []
        for r in self.db.execute(
                "SELECT ts, hostname, ip, frame FROM cluster_frames "
                "ORDER BY id"):
            try:
                frame = json.loads(zlib.decompress(r["frame"]))
            except Exception:
                continue
            out.append((r["ts"], r["hostname"], r["ip"], frame))
        return out

    def frame_count(self) -> int:
        row = self.db.execute(
            "SELECT COUNT(*) AS n FROM cluster_frames")[0]
        return int(row["n"])


# --------------------------------------------------------------------- #
# Manager side: the merged cluster series
# --------------------------------------------------------------------- #

class _SchedulerState:
    __slots__ = ("hostname", "ip", "frames", "state", "last_frame_ts",
                 "first_seen", "frames_total", "prev_stragglers",
                 "prev_breached", "prev_quarantined")

    def __init__(self, hostname: str, ip: str, cap: int):
        self.hostname = hostname
        self.ip = ip
        self.frames: deque = deque(maxlen=cap)
        self.state = "active"             # active | inactive | no_data
        self.last_frame_ts = 0.0
        self.first_seen = time.time()
        self.frames_total = 0
        self.prev_stragglers: set = set()
        self.prev_breached: set = set()
        self.prev_quarantined = 0

    @property
    def key(self) -> str:
        return f"{self.hostname}@{self.ip}" if self.ip else self.hostname


class ClusterSeries:
    """Folds per-scheduler fleet frames into a cluster-wide view with
    per-scheduler attribution, emitting edge-triggered journal events on
    the way (new straggler, new SLO breach, quarantine storm). Every
    ingest path is fail-open: a bad frame is counted and dropped, never
    raised into the keepalive stream."""

    def __init__(self, *, journal: "ClusterEventJournal | None" = None,
                 spool: "TelemetrySpool | None" = None,
                 frames_per_scheduler: int = 240,
                 quarantine_storm: int = 3):
        self.journal = journal or ClusterEventJournal()
        self.spool = spool
        self.frames_per_scheduler = frames_per_scheduler
        # A jump of this many quarantined hosts between consecutive
        # frames of one scheduler is a storm event.
        self.quarantine_storm = quarantine_storm
        self.admission = AdmissionBurstDetector(self.journal)
        self._scheds: dict = {}           # (hostname, ip) -> _SchedulerState
        self.restored_frames = 0
        self._frame_children = {
            r: FRAME_COUNT.labels(r) for r in ("ok", "malformed", "error")}
        self._state_children = {
            s: SCHEDULERS_GAUGE.labels(s)
            for s in ("active", "inactive", "no_data")}
        self._refresh_state_gauge()
        if self.spool is not None:
            self._restore()

    # -- ingest ------------------------------------------------------- #

    def ingest(self, hostname: str, ip: str, frame) -> int:
        """Fold one frame in; returns 1 on accept, 0 otherwise. Fail-open
        by construction — this runs inside the keepalive stream."""
        try:
            if not isinstance(frame, dict) or frame.get("v") != 1:
                self._frame_children["malformed"].inc()
                return 0
            st = self._sched(hostname, ip, state="active")
            if st.state != "active":
                self._set_state(st, "active")
            st.frames.append(frame)
            st.frames_total += 1
            st.last_frame_ts = float(frame.get("ts", time.time()))
            self._emit_edges(st, frame)
            if self.spool is not None:
                try:
                    self.spool.store(hostname, ip, frame)
                except Exception:
                    log.warning("telemetry spool write failed",
                                exc_info=True)
            self._frame_children["ok"].inc()
            return 1
        except Exception:
            self._frame_children["error"].inc()
            return 0

    def mark_seen(self, hostname: str, ip: str) -> None:
        """A keepalive arrived without a frame: full liveness, zero data.
        An already-reporting scheduler keeps its data; an old-wire one is
        surfaced as ``no_data`` instead of inventing zeros."""
        st = self._scheds.get((hostname, ip))
        if st is None:
            st = self._sched(hostname, ip, state="no_data")
        elif st.state == "inactive":
            self._set_state(
                st, "active" if st.frames_total else "no_data")

    def note_lapse(self, hostname: str, ip: str) -> None:
        """Keepalive liveness lapsed (expire_stale flipped the row)."""
        st = self._sched(hostname, ip, state="inactive")
        if st.state != "inactive":
            self._set_state(st, "inactive")
            self.journal.record("lapse", scheduler=st.key,
                                detail="keepalive lapsed")

    def note_return(self, hostname: str, ip: str) -> None:
        """A lapsed scheduler's keepalive came back."""
        st = self._scheds.get((hostname, ip))
        if st is not None and st.state == "inactive":
            self._set_state(
                st, "active" if st.frames_total else "no_data")
            self.journal.record("return", scheduler=st.key,
                                detail="keepalive returned")

    def note_admission_429(self, subject: str = "") -> None:
        self.admission.note_429(subject)

    # -- internals ---------------------------------------------------- #

    def _sched(self, hostname: str, ip: str,
               *, state: str) -> _SchedulerState:
        st = self._scheds.get((hostname, ip))
        if st is None:
            st = _SchedulerState(hostname, ip, self.frames_per_scheduler)
            st.state = state
            self._scheds[(hostname, ip)] = st
            self._refresh_state_gauge()
        return st

    def _set_state(self, st: _SchedulerState, state: str) -> None:
        st.state = state
        self._refresh_state_gauge()

    def _refresh_state_gauge(self) -> None:
        counts = {"active": 0, "inactive": 0, "no_data": 0}
        for st in self._scheds.values():
            counts[st.state] = counts.get(st.state, 0) + 1
        for state, child in self._state_children.items():
            child.set(counts[state])

    def _emit_edges(self, st: _SchedulerState, frame: dict) -> None:
        stragglers = set(frame.get("stragglers") or ())
        for host in sorted(stragglers - st.prev_stragglers):
            self.journal.record("straggler", scheduler=st.key,
                                subject=host,
                                detail="flagged by fleet scorecard")
        st.prev_stragglers = stragglers
        breached = set(frame.get("breached") or ())
        for name in sorted(breached - st.prev_breached):
            slo = (frame.get("slo") or {}).get(name) or {}
            self.journal.record(
                "slo_breach", scheduler=st.key, subject=name,
                detail=f"burn={slo.get('burn', 0.0):.2f}")
        st.prev_breached = breached
        nq = len(frame.get("quarantined") or ())
        if nq - st.prev_quarantined >= self.quarantine_storm:
            self.journal.record(
                "quarantine_storm", scheduler=st.key,
                detail=f"{st.prev_quarantined} -> {nq} quarantined "
                       f"hosts in one frame")
        st.prev_quarantined = nq

    def _restore(self) -> None:
        """Replay the spooled window (oldest-first) without re-triggering
        edge events — restored history is context, not news."""
        try:
            rows = self.spool.load()
        except Exception:
            log.warning("telemetry spool restore failed", exc_info=True)
            return
        for ts, hostname, ip, frame in rows:
            if not isinstance(frame, dict) or frame.get("v") != 1:
                continue
            st = self._sched(hostname, ip, state="active")
            st.frames.append(frame)
            st.frames_total += 1
            st.last_frame_ts = max(st.last_frame_ts,
                                   float(frame.get("ts", ts)))
            st.prev_stragglers = set(frame.get("stragglers") or ())
            st.prev_breached = set(frame.get("breached") or ())
            st.prev_quarantined = len(frame.get("quarantined") or ())
            self.restored_frames += 1
        if self.restored_frames:
            log.info("telemetry spool restored",
                     frames=self.restored_frames,
                     schedulers=len(self._scheds))

    # -- reports ------------------------------------------------------ #

    def _frames_in(self, st: _SchedulerState, since: float) -> list:
        return [f for f in st.frames
                if float(f.get("ts", 0.0)) >= since]

    def report(self, window_s: float = 600.0) -> dict:
        """The merged cluster view: totals summed over every scheduler's
        frames in the window, latest gauges summed across schedulers,
        and straggler/quarantine/breach attribution back to the owning
        scheduler."""
        now = time.time()
        since = now - window_s
        totals: dict = {}
        gauges: dict = {}
        decisions: dict = {}
        stragglers: dict = {}
        quarantined: dict = {}
        breached: dict = {}
        schedulers = []
        for st in sorted(self._scheds.values(), key=lambda s: s.key):
            frames = self._frames_in(st, since)
            last = frames[-1] if frames else None
            for f in frames:
                for k, v in (f.get("counters") or {}).items():
                    totals[k] = totals.get(k, 0) + v
                for k, v in (f.get("decisions") or {}).items():
                    decisions[k] = decisions.get(k, 0) + v
            if last is not None:
                for k, v in (last.get("gauges") or {}).items():
                    gauges[k] = gauges.get(k, 0) + v
                for host in last.get("stragglers") or ():
                    stragglers[host] = st.key
                for host in last.get("quarantined") or ():
                    quarantined[host] = st.key
                for name in last.get("breached") or ():
                    breached.setdefault(name, []).append(st.key)
            schedulers.append(self._sched_summary(st, frames, now))
        return {
            "now": round(now, 3),
            "window_s": window_s,
            "schedulers": schedulers,
            "totals": totals,
            "gauges": gauges,
            "decisions": decisions,
            "stragglers": stragglers,
            "quarantined": quarantined,
            "breached": breached,
            "events": {"recorded_total": self.journal.recorded_total,
                       "dropped": max(0, self.journal.recorded_total
                                      - self.journal.cap)},
            "restored_frames": self.restored_frames,
            "spool": ({"bytes": self.spool.bytes,
                       "max_bytes": self.spool.max_bytes}
                      if self.spool is not None else None),
        }

    def _sched_summary(self, st: _SchedulerState, frames: list,
                       now: float) -> dict:
        last = frames[-1] if frames else None
        out = {
            "scheduler": st.key,
            "hostname": st.hostname,
            "ip": st.ip,
            "state": st.state if st.frames_total or
            st.state == "inactive" else "no_data",
            "frames": len(frames),
            "frames_total": st.frames_total,
            "last_frame_age_s": (round(now - st.last_frame_ts, 1)
                                 if st.last_frame_ts else None),
        }
        if last is not None:
            out.update({
                "stragglers": list(last.get("stragglers") or ()),
                "quarantined": list(last.get("quarantined") or ()),
                "breached": list(last.get("breached") or ()),
                "gauges": dict(last.get("gauges") or {}),
                "resident_bytes": last.get("resident_bytes"),
                "frame_bytes": last.get("bytes"),
            })
        return out

    def schedulers_report(self, window_s: float = 600.0) -> dict:
        now = time.time()
        since = now - window_s
        return {
            "now": round(now, 3),
            "window_s": window_s,
            "schedulers": [
                self._sched_summary(st, self._frames_in(st, since), now)
                for st in sorted(self._scheds.values(),
                                 key=lambda s: s.key)],
        }

    def slo_report(self, window_s: float = 600.0) -> dict:
        """Latest per-scheduler SLO condensate + the cluster-wide union
        of breached names."""
        now = time.time()
        since = now - window_s
        per = {}
        breached: set = set()
        for st in sorted(self._scheds.values(), key=lambda s: s.key):
            frames = self._frames_in(st, since)
            last = next((f for f in reversed(frames)
                         if "slo" in f), None)
            if last is None:
                per[st.key] = {"state": "no_data", "slos": {}}
                continue
            per[st.key] = {"state": "breach" if last.get("breached")
                           else "ok", "slos": last.get("slo") or {}}
            breached.update(last.get("breached") or ())
        return {"now": round(now, 3), "window_s": window_s,
                "schedulers": per, "breached": sorted(breached)}


# --------------------------------------------------------------------- #
# The one text renderer (``?format=text`` and ``dfget --cluster``)
# --------------------------------------------------------------------- #

def render_cluster(report: dict) -> str:
    """Render a ClusterSeries.report() as the operator-facing text view —
    the SAME renderer behind ``GET /debug/cluster?format=text`` and
    ``dfget --explain --cluster``."""
    lines = []
    n = len(report.get("schedulers") or ())
    lines.append(f"cluster view · {n} scheduler(s) · window "
                 f"{report.get('window_s', 0):.0f}s")
    totals = report.get("totals") or {}
    if totals:
        keys = ("pieces_landed", "handouts", "back_source", "quarantines",
                "registers", "announces")
        parts = [f"{k}={int(totals[k])}" for k in keys if totals.get(k)]
        extra = sum(v for k, v in totals.items()
                    if k.startswith("failed_"))
        if extra:
            parts.append(f"failed={int(extra)}")
        if parts:
            lines.append("  totals: " + " ".join(parts))
    gauges = report.get("gauges") or {}
    if gauges:
        parts = [f"{k}={int(v)}" for k, v in sorted(gauges.items()) if v]
        if parts:
            lines.append("  gauges: " + " ".join(parts))
    for s in report.get("schedulers") or ():
        age = s.get("last_frame_age_s")
        lines.append(
            f"  scheduler {s['scheduler']:<24} {s['state']:<9} "
            f"frames={s.get('frames', 0)}"
            + (f" last={age:.0f}s ago" if age is not None else ""))
        for label in ("stragglers", "quarantined", "breached"):
            vals = s.get(label) or ()
            if vals:
                lines.append(f"    {label}: " + ", ".join(vals))
    stragglers = report.get("stragglers") or {}
    if stragglers:
        lines.append("  stragglers (host -> scheduler):")
        for host, sched in sorted(stragglers.items()):
            lines.append(f"    {host} -> {sched}")
    breached = report.get("breached") or {}
    if breached:
        lines.append("  slo breaches:")
        for name, scheds in sorted(breached.items()):
            lines.append(f"    {name}: " + ", ".join(scheds))
    ev = report.get("events") or {}
    if ev:
        lines.append(f"  events: recorded={ev.get('recorded_total', 0)} "
                     f"dropped={ev.get('dropped', 0)}")
    if report.get("restored_frames"):
        lines.append(f"  restored from spool: "
                     f"{report['restored_frames']} frame(s)")
    spool = report.get("spool")
    if spool:
        lines.append(f"  spool: {spool['bytes']}/{spool['max_bytes']} "
                     f"bytes")
    return "\n".join(lines) + "\n"
