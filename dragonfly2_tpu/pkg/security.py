"""TLS credentials for drpc and the HTTP piece/upload surfaces.

Reference: pkg/rpc/credential.go — mTLS gRPC transport credentials loading
cert/key/CA per binary, and certify-issued upload-server certs
(client/daemon/upload/upload_manager.go WithTLS). stdlib ssl here: a
server context (optionally requiring client certs = mTLS) and a client
context (optionally presenting a cert, verifying the fabric CA).
"""

from __future__ import annotations

import ssl


def server_ssl_context(cert_file: str, key_file: str, *, ca_file: str = "",
                       require_client_cert: bool = False) -> ssl.SSLContext:
    ctx = ssl.SSLContext(ssl.PROTOCOL_TLS_SERVER)
    ctx.load_cert_chain(cert_file, key_file)
    if ca_file:
        ctx.load_verify_locations(ca_file)
    if require_client_cert:
        ctx.verify_mode = ssl.CERT_REQUIRED
    return ctx


def client_ssl_context(*, cert_file: str = "", key_file: str = "",
                       ca_file: str = "",
                       verify: bool = True) -> ssl.SSLContext:
    ctx = ssl.SSLContext(ssl.PROTOCOL_TLS_CLIENT)
    if ca_file:
        ctx.load_verify_locations(ca_file)
        ctx.check_hostname = False       # fabric certs are per-host, not DNS
    elif not verify:
        ctx.check_hostname = False
        ctx.verify_mode = ssl.CERT_NONE
    else:
        # No explicit CA but verification on: anchor to the system store
        # (a bare PROTOCOL_TLS_CLIENT context trusts NOTHING and would fail
        # every handshake).
        ctx.load_default_certs()
    if cert_file and key_file:
        ctx.load_cert_chain(cert_file, key_file)
    return ctx
