"""Pod lens: cross-host merged broadcast timelines with clock alignment.

The flight recorder (pkg/flight) answers "where did the wall time go" for
one task on ONE daemon; the scheduler's PodAggregator sums coarse
per-piece timings per host. Neither can draw the picture an operator
actually needs when a 1024-host broadcast drags: every host's phase
timeline on ONE wall-aligned axis, with the slowest host and its
dominant phase named. This module is that merge:

  * ``ClockEstimator`` — per-host clock offset from announce-path
    round-trip samples. The daemon stamps ``t0``/``t1`` (its anchored
    monotonic wall clock, pkg/flight.anchored_wall — NTP steps cannot
    skew a sample) around an announce whose response carried the
    scheduler's own ``sched_wall`` echo; the classic NTP midpoint gives
    ``offset = (t0 + t1) / 2 - echo`` with the guaranteed error bound
    ``|true - est| <= rtt / 2``. The estimator keeps the best (min
    uncertainty) recent sample per host and CARRIES the bound instead of
    pretending alignment is exact — the merged timeline prints it.

  * ``PodLens`` — bounded per-task store of the flight digests daemons
    ship on task completion/failure (pkg/flight.digest), merged by
    ``timeline()`` into one wall-aligned pod report: per-host phase
    segments shifted into the scheduler's clock domain, slowest host,
    pod-dominant phase, and the worst per-host alignment error bound.
    ``render_timeline`` draws the per-host phase-colored lag waterfall
    (``/debug/pod/<task_id>/timeline?format=text``, ``dfget --pod``).

Bounded like everything else in the observability stack: digests are
byte-capped at the source, the per-task index is LRU-capped, and the
estimator keeps O(1) samples per host with an LRU host cap.
"""

from __future__ import annotations

import time
from collections import OrderedDict

import msgpack

from dragonfly2_tpu.pkg import dflog
from dragonfly2_tpu.pkg.flight import PHASES, digest_piece_rows

log = dflog.get("podlens")

# Worst-case relative drift between two anchored monotonic clocks, used
# to age a sample's error bound (crystal oscillators drift ~10-100 ppm;
# 200 keeps the bound honest on throttled VMs).
DRIFT_PPM = 200.0
# Floor on any reported alignment bound: scheduling jitter between "stamp
# taken" and "message on the wire" is real even on loopback.
MIN_ERR_S = 0.002
# Offset assumed for a host with no samples at all (bound, not estimate).
UNALIGNED_ERR_S = 1.0


class ClockEstimator:
    """Per-host offset (host_wall - sched_wall) with carried uncertainty.

    ``add_sample`` is O(1); hosts are LRU-capped. The estimate picks the
    sample with the smallest AGED bound (rtt/2 + age * drift): a tight
    old sample eventually loses to a looser fresh one, so a rebooted
    host's stale offset cannot linger."""

    def __init__(self, *, max_hosts: int = 4096, keep: int = 4,
                 clock=time.monotonic):
        self.max_hosts = max_hosts
        self.keep = keep
        self._clock = clock
        # host -> list of [offset, rtt/2, taken_at] (newest last)
        self._hosts: "OrderedDict[str, list]" = OrderedDict()

    def add_sample(self, host_id: str, t0: float, t1: float,
                   echo: float) -> bool:
        """One round trip: host stamped ``t0`` at send and ``t1`` at
        response receipt (its anchored wall clock); the response carried
        the scheduler's ``echo`` wall stamp. Rejects malformed samples
        (negative rtt, missing echo) instead of poisoning the estimate."""
        rtt = t1 - t0
        if rtt < 0 or echo <= 0 or t0 <= 0:
            return False
        samples = self._hosts.get(host_id)
        if samples is None:
            while len(self._hosts) >= self.max_hosts:
                self._hosts.popitem(last=False)
            samples = self._hosts[host_id] = []
        else:
            self._hosts.move_to_end(host_id)
        samples.append([(t0 + t1) / 2.0 - echo, rtt / 2.0, self._clock()])
        del samples[:-self.keep]
        return True

    def estimate(self, host_id: str) -> "tuple[float, float, int]":
        """(offset_s, err_bound_s, n_samples). Unknown hosts report
        offset 0 with the UNALIGNED bound — the merge stays usable, the
        printed bound stays honest."""
        samples = self._hosts.get(host_id)
        if not samples:
            return 0.0, UNALIGNED_ERR_S, 0
        now = self._clock()
        best = min(samples,
                   key=lambda s: s[1] + max(0.0, now - s[2])
                   * DRIFT_PPM * 1e-6)
        err = best[1] + max(0.0, now - best[2]) * DRIFT_PPM * 1e-6
        return best[0], max(MIN_ERR_S, err), len(samples)

    def hosts_tracked(self) -> int:
        return len(self._hosts)


def completion_stats(d: dict) -> "tuple[float, float, float]":
    """(makespan_s, ttfb_s, stall_frac) of one shipped digest — the SLO
    engine's per-completion SLIs. TTFB = earliest first-byte (or landed)
    mark; -1 when the digest carries no piece rows. Reads the compact
    piece arrays in place (this runs once per task completion on the
    scheduler's ingest path — no row dicts)."""
    wall = float(d.get("wall_s") or 0.0)
    phases = d.get("phases") or {}
    stall_frac = (phases.get("stall", 0.0) / wall) if wall > 0 else 0.0
    ttfb = -1.0
    for row in d.get("pieces") or ():
        # Row layout: DIGEST_PIECE_FIELDS — t_first_byte at 3, t_landed
        # at 4.
        try:
            t = row[3] if row[3] >= 0 else row[4]
        except (TypeError, IndexError):
            continue
        if t >= 0 and (ttfb < 0 or t < ttfb):
            ttfb = t
    return wall, ttfb, stall_frac


class PodLens:
    """Bounded store of shipped flight digests + the clock estimator,
    merged on demand into the cross-host timeline.

    Retention is a REDUCTION, not the raw digest: the merge needs the
    phase totals, the merged phase segments and the counts — not the
    per-piece waterfall or the named events (those stay on the host at
    ``/debug/flight`` and come back whole via an on-demand
    ``Daemon.FlightReport`` pull). The reduction is stored as one
    msgpack bytes object per host: a live dict per digest would hand
    every cyclic-GC pass the whole store to rescan, and podlens_bench
    caught exactly that as a systematic scheduler CPU tax. Ingest cost
    is ~10 us/task (config10_podlens pins it); reads (timelines, rare)
    decode on demand."""

    # Digest keys the merge consumes — everything else is dropped at
    # ingest (the reduction that keeps the store and the GC honest).
    _KEEP = ("v", "task_id", "state", "note", "start_wall", "wall_s",
             "phases", "other_s", "dominant_phase", "segments",
             "pieces_total", "pieces_truncated", "events_total",
             "events_dropped")
    _MAX_SEGMENTS = 48

    def __init__(self, *, max_tasks: int = 256,
                 clock_estimator: "ClockEstimator | None" = None):
        self.max_tasks = max_tasks
        self.clock = clock_estimator or ClockEstimator()
        # task_id -> {host_id: (peer_id, msgpack bytes of the reduction)}
        self._tasks: "OrderedDict[str, dict]" = OrderedDict()

    def note_flight(self, task_id: str, host_id: str, d: dict,
                    peer_id: str = "") -> None:
        """Ingest one shipped digest (terminal announce message or an
        on-demand ``Daemon.FlightReport`` pull). Clock samples ride the
        digest; they feed the estimator here."""
        if not isinstance(d, dict):
            return
        for sample in d.get("clock") or []:
            try:
                t0, t1, echo = sample
                self.clock.add_sample(host_id, float(t0), float(t1),
                                      float(echo))
            except (TypeError, ValueError):
                continue
        entry = self._tasks.get(task_id)
        if entry is None:
            while len(self._tasks) >= self.max_tasks:
                self._tasks.popitem(last=False)
            entry = self._tasks[task_id] = {}
        keep = {k: d[k] for k in self._KEEP if k in d}
        keep["pieces_total"] = d.get("pieces_total",
                                     len(d.get("pieces") or ()))
        segs = keep.get("segments")
        if segs and len(segs) > self._MAX_SEGMENTS:
            keep["segments"] = segs[:self._MAX_SEGMENTS]
        try:
            raw = msgpack.packb(keep)
        except (TypeError, ValueError):
            return                      # unserializable digest: drop
        entry[host_id] = (peer_id, raw)

    def digests_for(self, task_id: str) -> dict:
        """Decoded shipped digest reductions ({host_id: dict})."""
        out = {}
        for host_id, (peer_id, raw) in (self._tasks.get(task_id)
                                        or {}).items():
            d = msgpack.unpackb(raw)
            if peer_id:
                d["peer_id"] = peer_id
            out[host_id] = d
        return out

    def shipped_hosts(self, task_id: str) -> set:
        """Hosts whose digest already arrived (no decode — the pull-
        budget check on the timeline path)."""
        return set(self._tasks.get(task_id) or ())

    def tasks(self) -> list:
        return [{"task_id": tid, "hosts": len(hosts)}
                for tid, hosts in self._tasks.items()]

    def timeline(self, task_id: str,
                 extra: "dict | None" = None) -> "dict | None":
        """The merged pod timeline: every host's digest aligned into the
        scheduler's wall domain (host_wall - offset). ``extra`` holds
        digests pulled on demand for hosts that never shipped one (they
        merge but are not retained). None when no digest is known."""
        digests = self.digests_for(task_id)
        for host_id, d in (extra or {}).items():
            if isinstance(d, dict):
                digests.setdefault(host_id, d)
        if not digests:
            return None
        hosts = []
        totals = {ph: 0.0 for ph in PHASES}
        err_max = 0.0
        t0_pod = None
        end_pod = 0.0
        for host_id, d in digests.items():
            offset, err, n_samples = self.clock.estimate(host_id)
            start = float(d.get("start_wall") or 0.0) - offset
            wall = float(d.get("wall_s") or 0.0)
            phases = {ph: float((d.get("phases") or {}).get(ph, 0.0))
                      for ph in PHASES}
            for ph, v in phases.items():
                totals[ph] += v
            err_max = max(err_max, err)
            if t0_pod is None or start < t0_pod:
                t0_pod = start
            end_pod = max(end_pod, start + wall)
            hosts.append({
                "host": host_id,
                "peer_id": d.get("peer_id", ""),
                "state": d.get("state", ""),
                "start_wall": round(start, 6),
                "wall_s": round(wall, 6),
                "phases": {ph: round(v, 6) for ph, v in phases.items()},
                "other_s": d.get("other_s", 0.0),
                "dominant_phase": d.get("dominant_phase", ""),
                "segments": d.get("segments") or [],
                "pieces": d.get("pieces_total",
                                len(d.get("pieces") or ())),
                "events_dropped": d.get("events_dropped", 0),
                "clock_offset_s": round(offset, 6),
                "align_err_s": round(err, 6),
                "clock_samples": n_samples,
            })
        t0_pod = t0_pod or 0.0
        for h in hosts:
            h["t_start"] = round(h["start_wall"] - t0_pod, 6)
        # Slowest = the host whose own task wall was longest (alignment
        # error cannot flip it, unlike last-finisher ordering would).
        hosts.sort(key=lambda h: -h["wall_s"])
        slowest = hosts[0]["host"] if hosts and hosts[0]["wall_s"] > 0 \
            else ""
        dominant = max(PHASES, key=lambda p: totals[p]) \
            if any(v > 0 for v in totals.values()) else ""
        return {
            "task_id": task_id,
            "hosts": hosts,
            "hosts_total": len(hosts),
            "t0_wall": round(t0_pod, 6),
            "span_s": round(max(0.0, end_pod - t0_pod), 6),
            "slowest_host": slowest,
            "dominant_phase": dominant,
            "phase_totals": {ph: round(v, 6) for ph, v in totals.items()},
            "align_err_max_s": round(err_max, 6),
        }

    def resident_bytes(self) -> int:
        from dragonfly2_tpu.pkg.fleet import _deep_bytes

        return _deep_bytes(self._tasks) + _deep_bytes(self.clock._hosts)


# --------------------------------------------------------------------- #
# Text rendering: the per-host phase-colored lag waterfall
# --------------------------------------------------------------------- #

PHASE_CHARS = {"sched_wait": ".", "dcn": "=", "ici": "~", "verify": "v",
               "store": "s", "stall": "!", "origin": "o"}


def render_timeline(report: dict, width: int = 48) -> str:
    """One wall-aligned bar per host, phase-colored; the slowest host is
    starred and the alignment error bound is printed so nobody reads
    sub-bound lead/lag differences as real. The SAME renderer backs
    ``/debug/pod/<task_id>/timeline?format=text`` and ``dfget --pod``."""
    span = report["span_s"] or 1e-9
    lines = [
        f"pod {report['task_id'][:40]} hosts={report['hosts_total']} "
        f"span={report['span_s']:.3f}s "
        f"slowest={report['slowest_host'] or '-'} "
        f"dominant={report['dominant_phase'] or '-'} "
        f"align_err<={report['align_err_max_s'] * 1000:.1f}ms",
        "legend: " + " ".join(f"{c}={ph}"
                              for ph, c in PHASE_CHARS.items()),
    ]
    for h in report["hosts"]:
        bar = [" "] * width
        base = h["t_start"]
        for seg in h["segments"]:
            try:
                s, e, ph = seg
            except (TypeError, ValueError):
                continue
            c = PHASE_CHARS.get(ph, "?")
            lo = int(width * min(max(base + s, 0.0), span) / span)
            hi = int(width * min(max(base + e, 0.0), span) / span)
            for i in range(lo, max(hi, lo + 1)):
                if i < width:
                    bar[i] = c
        mark = "*" if h["host"] == report["slowest_host"] else " "
        lines.append(
            f" {mark}{h['host'][:28]:<28} |{''.join(bar)}| "
            f"+{h['t_start']:6.3f}s wall={h['wall_s']:7.3f}s "
            f"{h['dominant_phase'] or '-':<10} "
            f"off={h['clock_offset_s'] * 1000:+7.1f}ms "
            f"±{h['align_err_s'] * 1000:.1f}ms")
    return "\n".join(lines)
