"""Certificate authority for TLS-intercepting proxy and fabric mTLS.

Reference: client/daemon/proxy/proxy.go:471 handleHTTPS — the proxy
hijacks CONNECT tunnels by terminating TLS with a leaf certificate forged
on the fly for the requested host, signed by a configured CA the cluster's
clients trust. Here the CA can be loaded from PEM files or self-generated
(the reference leans on an operator-supplied cert; a generated CA plus a
trust-bundle export covers the TPU-pod deployment where we control every
client).

Leaf certs are minted per hostname and cached; each carries the hostname
as both CN and SAN (DNS or IP as appropriate) so stock TLS clients accept
it once the CA is trusted.
"""

from __future__ import annotations

import datetime
import ipaddress
import os
import ssl
import threading

from cryptography import x509
from cryptography.hazmat.primitives import hashes, serialization
from cryptography.hazmat.primitives.asymmetric import rsa
from cryptography.x509.oid import NameOID

_ONE_DAY = datetime.timedelta(days=1)


def _new_key() -> rsa.RSAPrivateKey:
    return rsa.generate_private_key(public_exponent=65537, key_size=2048)


def _pem_key(key) -> bytes:
    return key.private_bytes(
        serialization.Encoding.PEM,
        serialization.PrivateFormat.TraditionalOpenSSL,
        serialization.NoEncryption())


def _pem_cert(cert: x509.Certificate) -> bytes:
    return cert.public_bytes(serialization.Encoding.PEM)


class CertAuthority:
    """A CA that forges leaf certificates for arbitrary hosts."""

    def __init__(self, ca_cert_pem: bytes, ca_key_pem: bytes):
        self.ca_cert_pem = ca_cert_pem
        self.ca_key_pem = ca_key_pem
        self.ca_cert = x509.load_pem_x509_certificate(ca_cert_pem)
        self.ca_key = serialization.load_pem_private_key(ca_key_pem, None)
        self._contexts: dict[str, ssl.SSLContext] = {}
        self._lock = threading.Lock()
        # One leaf key shared across forged certs: keygen is the expensive
        # part and the key is as trusted as the in-memory CA key anyway.
        self._leaf_key = _new_key()

    # -- construction ------------------------------------------------------

    @classmethod
    def generate(cls, common_name: str = "dragonfly2-tpu-proxy-ca",
                 valid_days: int = 3650) -> "CertAuthority":
        key = _new_key()
        name = x509.Name([
            x509.NameAttribute(NameOID.COMMON_NAME, common_name),
            x509.NameAttribute(NameOID.ORGANIZATION_NAME, "dragonfly2-tpu"),
        ])
        now = datetime.datetime.now(datetime.timezone.utc)
        cert = (x509.CertificateBuilder()
                .subject_name(name).issuer_name(name)
                .public_key(key.public_key())
                .serial_number(x509.random_serial_number())
                .not_valid_before(now - _ONE_DAY)
                .not_valid_after(now + datetime.timedelta(days=valid_days))
                .add_extension(x509.BasicConstraints(ca=True, path_length=0),
                               critical=True)
                .add_extension(x509.KeyUsage(
                    digital_signature=True, key_cert_sign=True, crl_sign=True,
                    content_commitment=False, key_encipherment=False,
                    data_encipherment=False, key_agreement=False,
                    encipher_only=False, decipher_only=False), critical=True)
                .sign(key, hashes.SHA256()))
        return cls(_pem_cert(cert), _pem_key(key))

    @classmethod
    def load(cls, cert_path: str, key_path: str) -> "CertAuthority":
        with open(cert_path, "rb") as f:
            cert_pem = f.read()
        with open(key_path, "rb") as f:
            key_pem = f.read()
        return cls(cert_pem, key_pem)

    @classmethod
    def load_or_generate(cls, cert_path: str = "", key_path: str = "",
                         persist_dir: str = "") -> "CertAuthority":
        """Operator-supplied CA when paths are given; otherwise generate,
        persisting into ``persist_dir`` so restarts keep the same root of
        trust (clients only need to install the CA once)."""
        if cert_path and key_path:
            return cls.load(cert_path, key_path)
        if persist_dir:
            cert_p = os.path.join(persist_dir, "proxy-ca.crt")
            key_p = os.path.join(persist_dir, "proxy-ca.key")
            if os.path.exists(cert_p) and os.path.exists(key_p):
                return cls.load(cert_p, key_p)
            ca = cls.generate()
            os.makedirs(persist_dir, exist_ok=True)
            with open(cert_p, "wb") as f:
                f.write(ca.ca_cert_pem)
            fd = os.open(key_p, os.O_WRONLY | os.O_CREAT | os.O_TRUNC, 0o600)
            with os.fdopen(fd, "wb") as f:
                f.write(ca.ca_key_pem)
            return ca
        return cls.generate()

    # -- leaf forging ------------------------------------------------------

    def forge_leaf(self, hostname: str) -> tuple[bytes, bytes]:
        """Mint (cert_pem, key_pem) for ``hostname``, CA-signed."""
        try:
            san: x509.GeneralName = x509.IPAddress(
                ipaddress.ip_address(hostname))
        except ValueError:
            san = x509.DNSName(hostname)
        now = datetime.datetime.now(datetime.timezone.utc)
        cert = (x509.CertificateBuilder()
                .subject_name(x509.Name([
                    x509.NameAttribute(NameOID.COMMON_NAME, hostname[:64])]))
                .issuer_name(self.ca_cert.subject)
                .public_key(self._leaf_key.public_key())
                .serial_number(x509.random_serial_number())
                .not_valid_before(now - _ONE_DAY)
                .not_valid_after(now + datetime.timedelta(days=397))
                .add_extension(x509.SubjectAlternativeName([san]),
                               critical=False)
                .add_extension(x509.ExtendedKeyUsage(
                    [x509.oid.ExtendedKeyUsageOID.SERVER_AUTH]),
                    critical=False)
                .sign(self.ca_key, hashes.SHA256()))
        return _pem_cert(cert), _pem_key(self._leaf_key)

    def server_context(self, hostname: str) -> ssl.SSLContext:
        """Server-side SSLContext presenting a forged cert for ``hostname``
        (chained with the CA cert). Cached per host."""
        with self._lock:
            ctx = self._contexts.get(hostname)
        if ctx is not None:
            return ctx
        ctx = self.fresh_server_context(hostname)
        with self._lock:
            self._contexts[hostname] = ctx
        return ctx

    def fresh_server_context(self, hostname: str) -> ssl.SSLContext:
        """Uncached variant for callers that mutate the context (e.g. a
        per-connection sni_callback) — the cached ones are shared."""
        cert_pem, key_pem = self.forge_leaf(hostname)
        ctx = ssl.SSLContext(ssl.PROTOCOL_TLS_SERVER)
        # Serve leaf + CA chain so clients can build the path even when
        # only the root is in their trust store via a bundle file.
        import tempfile

        with tempfile.NamedTemporaryFile(suffix=".pem") as cf, \
                tempfile.NamedTemporaryFile(suffix=".pem") as kf:
            cf.write(cert_pem + self.ca_cert_pem)
            cf.flush()
            kf.write(key_pem)
            kf.flush()
            ctx.load_cert_chain(cf.name, kf.name)
        return ctx

    def trust_context(self) -> ssl.SSLContext:
        """Client-side context trusting (only) this CA — what cluster
        clients install to talk through the intercepting proxy."""
        ctx = ssl.SSLContext(ssl.PROTOCOL_TLS_CLIENT)
        ctx.load_verify_locations(cadata=self.ca_cert_pem.decode())
        return ctx
