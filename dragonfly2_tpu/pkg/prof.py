"""Runtime observatory: continuous in-process profiling for every role.

The flight recorder (pkg/flight), fleet observatory (pkg/fleet) and pod
lens (pkg/podlens) are all event/task-centric; nothing watches the
RUNTIME itself — yet the scheduler's one real CPU regression so far
(cyclic GC rescanning live digest dicts) was only caught by accident in
a bench. This module is the missing process-level layer, the Python
analog of the reference's per-binary pprof endpoints
(cmd/dependency/dependency.go --pprof-port): always on, bounded, and
cheap enough to leave armed in production (prof_bench publishes the
paired cost as ``config12_prof``; budget <= 3%).

Three instruments, one ``RuntimeObservatory``:

  * ``StackSampler`` — a named daemon thread (``df-prof-sampler``) walks
    ``sys._current_frames()`` at a configurable hz and folds each
    thread's stack into a bounded call-tree trie keyed by code object.
    The flight-ring discipline applies: the walk buffer is preallocated,
    trie nodes are interned (a sample through an existing path allocates
    nothing), and the node budget is a hard cap with an eviction/
    truncation counter — a pathological stack explosion degrades to a
    counter, never to unbounded memory. Attribution is per THREAD NAME,
    which is why every long-lived thread in this tree carries a ``df-``
    prefix (tier-1 guard in tests/test_prof.py): dispatcher, upload,
    io-ring, chunker and sampler work separate cleanly in one glance.
  * ``LoopLagProbe`` — a scheduled heartbeat per asyncio loop; the delta
    between the intended and actual wake is the loop's lag. Samples land
    in a preallocated ring + bounded histogram; ticks above ``slow_s``
    are stamped into every RUNNING task flight as typed events
    (EV_LOOP_LAG), so ``dfget --explain``'s stall phase can say *the
    loop was wedged*, not just *nothing happened*. The ring also backs
    the ``loop_lag`` SLO (pkg/slo kind="probe"): wedged wall-seconds
    over observed wall-seconds.
  * ``GCObservatory`` — ``gc.callbacks`` pause histograms per
    generation + collection counters; pauses above ``gc_slow_s`` stamp
    EV_GC_PAUSE the same way. ``/proc/self`` gauges (RSS, open fds,
    threads, ctx switches) refresh on snapshot, not continuously.

Served by pkg/metrics_server on daemon AND scheduler:
  GET /debug/prof                   JSON top-N self-time per thread
  GET /debug/prof/flame?format=folded   flamegraph-ready folded stacks
  GET /debug/prof/runtime           loop lag + GC + /proc gauges

The observatory is a process singleton (``install()``/``release()``
refcounted): a test process embedding a daemon and a scheduler must not
run two sampler threads or double-book GC pauses.
"""

from __future__ import annotations

import asyncio
import gc
import os
import threading
import time
import sys
from dataclasses import dataclass

from dragonfly2_tpu.pkg import dflog, metrics

log = dflog.get("prof")

SAMPLES_TOTAL = metrics.counter(
    "runtime_profiler_samples_total",
    "Sampling passes the stack profiler completed (one pass folds every "
    "live thread's stack into the bounded trie)")

TRUNCATED_TOTAL = metrics.counter(
    "runtime_profiler_truncated_total",
    "Stack folds cut short by the trie node cap — the bounded-memory "
    "degradation counter (raise max_nodes if this moves)")

LAG_SECONDS = metrics.histogram(
    "runtime_loop_lag_seconds",
    "Asyncio event-loop heartbeat lag (actual wake minus intended wake); "
    "the loop-wedge detector behind the loop_lag SLO",
    buckets=(0.001, 0.005, 0.01, 0.025, 0.05, 0.1, 0.25, 0.5, 1.0, 2.5,
             5.0))

SLOW_TICKS_TOTAL = metrics.counter(
    "runtime_loop_slow_ticks_total",
    "Heartbeat ticks whose lag crossed the slow-tick threshold (each one "
    "is also stamped into every running task flight as a typed event)")

GC_PAUSE_SECONDS = metrics.histogram(
    "runtime_gc_pause_seconds",
    "Cyclic-GC pause per collection, by generation (gc.callbacks "
    "start/stop delta)",
    ("generation",),
    buckets=(0.0005, 0.001, 0.005, 0.01, 0.025, 0.05, 0.1, 0.25, 0.5,
             1.0))

GC_COLLECTIONS_TOTAL = metrics.counter(
    "runtime_gc_collections_total",
    "Cyclic-GC collections observed by generation",
    ("generation",))

RSS_BYTES = metrics.gauge(
    "runtime_rss_bytes",
    "Resident set size from /proc/self/statm (refreshed on scrape)")

OPEN_FDS = metrics.gauge(
    "runtime_open_fds",
    "Open file descriptors from /proc/self/fd (refreshed on scrape)")

THREADS_GAUGE = metrics.gauge(
    "runtime_threads",
    "Live threads in this process (refreshed on scrape)")

CTX_SWITCHES = metrics.gauge(
    "runtime_ctx_switches",
    "Context switches from /proc/self/status by kind "
    "(voluntary/involuntary; cumulative counters mirrored as gauges)",
    ("kind",))


@dataclass
class ProfConfig:
    """Runtime-observatory knobs, shared by daemon and scheduler config
    (``prof:`` block). Always on by default — the bench-published budget
    is what makes that safe; ``enabled=False`` removes every hook."""

    enabled: bool = True
    hz: float = 19.0              # sampler passes per second
    max_nodes: int = 8192         # trie node hard cap (then truncation)
    max_depth: int = 48           # frames folded per stack
    lag_interval_s: float = 0.25  # heartbeat period per probed loop
    lag_slow_s: float = 0.25      # slow-tick threshold -> flight events
    gc_slow_s: float = 0.05       # GC pause threshold -> flight events
    lag_ring: int = 4096          # lag samples retained for the SLO probe


# Internal fixed bucket edges for the JSON-served lag/GC histograms
# (preallocated count arrays; the Prometheus families use their own).
_LAG_EDGES = (0.001, 0.005, 0.01, 0.025, 0.05, 0.1, 0.25, 0.5, 1.0, 2.5,
              5.0)


def proc_stats() -> dict:
    """Best-effort /proc/self gauges; zeros off-Linux. Cheap enough to
    call per scrape (two small reads + one dirlist)."""
    out = {"rss_bytes": 0, "open_fds": 0, "threads": threading.active_count(),
           "voluntary_ctx_switches": 0, "involuntary_ctx_switches": 0}
    try:
        with open("/proc/self/statm") as f:
            out["rss_bytes"] = int(f.read().split()[1]) * os.sysconf(
                "SC_PAGE_SIZE")
    except (OSError, IndexError, ValueError):
        pass
    try:
        out["open_fds"] = len(os.listdir("/proc/self/fd"))
    except OSError:
        pass
    try:
        with open("/proc/self/status") as f:
            for line in f:
                if line.startswith("voluntary_ctxt_switches:"):
                    out["voluntary_ctx_switches"] = int(line.split()[1])
                elif line.startswith("nonvoluntary_ctxt_switches:"):
                    out["involuntary_ctx_switches"] = int(line.split()[1])
    except (OSError, IndexError, ValueError):
        pass
    return out


# --------------------------------------------------------------------- #
# (a) Sampling stack profiler
# --------------------------------------------------------------------- #

class StackSampler:
    """Folded-stack trie fed by a sampling daemon thread.

    Trie nodes are ``[self_count, {code: child}]`` keyed by code object —
    interning by identity means a steady-state sample allocates nothing
    in OUR structures (``sys._current_frames`` itself builds one dict per
    pass; that is the floor). Node creation stops at ``max_nodes``; the
    overflow shows up in ``truncated`` instead of memory."""

    def __init__(self, hz: float = 19.0, max_nodes: int = 8192,
                 max_depth: int = 48):
        self.hz = max(0.5, float(hz))
        self.max_nodes = max_nodes
        self.max_depth = max_depth
        self.samples = 0
        self.truncated = 0
        self._roots: "dict[str, list]" = {}     # thread name -> node
        self._nodes = 0
        self._labels: dict = {}                 # code -> "file:func"
        self._stackbuf: list = [None] * max_depth
        self._names: "dict[int, str]" = {}      # ident -> thread name
        self._names_refreshed = 0.0
        self._lock = threading.Lock()
        self._stop = threading.Event()
        self._thread: "threading.Thread | None" = None

    # -- lifecycle ---------------------------------------------------------

    def start(self) -> None:
        if self._thread is not None:
            return
        self._stop.clear()
        self._thread = threading.Thread(
            target=self._run, daemon=True, name="df-prof-sampler")
        self._thread.start()

    def stop(self) -> None:
        t = self._thread
        if t is None:
            return
        self._stop.set()
        t.join(timeout=2.0)
        self._thread = None

    def _run(self) -> None:
        interval = 1.0 / self.hz
        while not self._stop.wait(interval):
            with self._lock:
                self._sample_once()
            SAMPLES_TOTAL.inc()

    # -- the sampling pass -------------------------------------------------

    def _thread_name(self, ident: int, now: float) -> str:
        name = self._names.get(ident)
        if name is None or now - self._names_refreshed > 1.0:
            self._names = {t.ident: t.name for t in threading.enumerate()}
            self._names_refreshed = now
            name = self._names.get(ident)
        return name or f"tid-{ident}"

    def _sample_once(self) -> None:
        me = threading.get_ident()
        now = time.monotonic()
        buf = self._stackbuf
        for ident, frame in sys._current_frames().items():
            if ident == me:
                continue
            n = 0
            while frame is not None and n < self.max_depth:
                buf[n] = frame.f_code
                n += 1
                frame = frame.f_back
            name = self._thread_name(ident, now)
            node = self._roots.get(name)
            if node is None:
                node = self._roots[name] = [0, {}]
            truncated = False
            for i in range(n - 1, -1, -1):      # outermost first
                children = node[1]
                child = children.get(buf[i])
                if child is None:
                    if self._nodes >= self.max_nodes:
                        truncated = True
                        break
                    child = children[buf[i]] = [0, {}]
                    self._nodes += 1
                node = child
            node[0] += 1
            if truncated:
                self.truncated += 1
                TRUNCATED_TOTAL.inc()
        self.samples += 1

    @property
    def nodes(self) -> int:
        return self._nodes

    # -- rendering ---------------------------------------------------------

    def _label(self, code) -> str:
        label = self._labels.get(code)
        if label is None:
            label = self._labels[code] = (
                f"{os.path.basename(code.co_filename)}:{code.co_name}")
        return label

    def folded(self, max_lines: int = 4096) -> str:
        """Flamegraph-ready folded stacks: ``thread;frame;frame count``
        per line, leaf self-counts only (standard collapse format)."""
        lines: list = []
        with self._lock:
            for tname, root in sorted(self._roots.items()):
                stack = [tname]
                self._fold(root, stack, lines, max_lines)
        return "\n".join(lines) + ("\n" if lines else "")

    def _fold(self, node: list, stack: list, out: list,
              max_lines: int) -> None:
        if len(out) >= max_lines:
            return
        if node[0] > 0:
            out.append(f"{';'.join(stack)} {node[0]}")
        for code, child in node[1].items():
            stack.append(self._label(code))
            self._fold(child, stack, out, max_lines)
            stack.pop()

    def report(self, topn: int = 20) -> dict:
        """Top-N self-time frames per thread plus sampler state — the
        ``/debug/prof`` JSON body."""
        threads: dict = {}
        with self._lock:
            for tname, root in self._roots.items():
                per_frame: "dict[str, int]" = {}
                total = self._self_counts(root, per_frame)
                top = sorted(per_frame.items(), key=lambda kv: -kv[1])[:topn]
                threads[tname] = {
                    "samples": total,
                    "top_self": [
                        {"frame": frame, "self": count,
                         "frac": round(count / total, 4) if total else 0.0}
                        for frame, count in top],
                }
            return {
                "hz": self.hz,
                "samples": self.samples,
                "nodes": self._nodes,
                "max_nodes": self.max_nodes,
                "truncated": self.truncated,
                "threads": threads,
            }

    def _self_counts(self, node: list, acc: dict) -> int:
        total = node[0]
        for code, child in node[1].items():
            if child[0] > 0:
                label = self._label(code)
                acc[label] = acc.get(label, 0) + child[0]
            total += self._self_counts(child, acc)
        return total

    def top_frames(self, n: int = 5) -> list:
        """Flat process-wide top self-time frames (bench fallback
        snapshots want one list, not a per-thread tree)."""
        acc: "dict[str, int]" = {}
        with self._lock:
            for root in self._roots.values():
                self._self_counts(root, acc)
        top = sorted(acc.items(), key=lambda kv: -kv[1])[:n]
        return [{"frame": f, "self": c} for f, c in top]


# --------------------------------------------------------------------- #
# (b) Event-loop lag probe
# --------------------------------------------------------------------- #

class LoopLagProbe:
    """One heartbeat task per probed loop. A wedge of W seconds surfaces
    as ONE tick with ~W lag (the heartbeat self-reschedules), so the SLO
    probe counts wedged WALL TIME, not tick counts — immune to dilution
    by the healthy ticks around a stall."""

    def __init__(self, obs: "RuntimeObservatory", name: str,
                 interval_s: float = 0.25, slow_s: float = 0.25,
                 ring: int = 4096):
        self.obs = obs
        self.name = name
        self.interval_s = interval_s
        self.slow_s = slow_s
        self._ring: list = [None] * ring        # (mono_t, lag_s)
        self._cap = ring
        self._n = 0
        self.started_mono = time.monotonic()
        self.max_lag_s = 0.0
        self.slow_ticks = 0
        self._buckets = [0] * (len(_LAG_EDGES) + 1)
        self._task: "asyncio.Task | None" = None

    def arm(self) -> "LoopLagProbe":
        """Create the heartbeat on the RUNNING loop (call from it)."""
        loop = asyncio.get_running_loop()
        self.started_mono = time.monotonic()
        self._task = loop.create_task(self._beat(loop))
        try:
            self._task.set_name(f"df-prof-loop-{self.name}")
        except AttributeError:
            pass
        return self

    def disarm(self) -> None:
        if self._task is not None:
            self._task.cancel()
            self._task = None

    async def _beat(self, loop) -> None:
        interval = self.interval_s
        while True:
            t0 = loop.time()
            await asyncio.sleep(interval)
            lag = max(0.0, loop.time() - t0 - interval)
            self.note_lag(lag)

    def note_lag(self, lag: float) -> None:
        """One heartbeat observation (the async beat calls this; tests
        and the DES sim may feed synthetic ticks)."""
        self._ring[self._n % self._cap] = (time.monotonic(), lag)
        self._n += 1
        i = 0
        for edge in _LAG_EDGES:
            if lag <= edge:
                break
            i += 1
        self._buckets[i] += 1
        LAG_SECONDS.observe(lag)
        if lag > self.max_lag_s:
            self.max_lag_s = lag
        if lag >= self.slow_s:
            self.slow_ticks += 1
            SLOW_TICKS_TOTAL.inc()
            self.obs._stamp_flights_loop_lag(lag)

    # -- SLO feed ----------------------------------------------------------

    def wedged_seconds(self, window: float, threshold: float,
                       now: "float | None" = None) -> "tuple[float, float]":
        """(wedged, observed) wall-seconds over the trailing window: the
        pkg/slo kind="probe" good/bad fraction. Each retained tick whose
        lag crossed ``threshold`` contributes its full lag — the wall
        time the loop was not serving."""
        if now is None:
            now = time.monotonic()
        cutoff = now - window
        bad = 0.0
        oldest_seen = now
        newest = self._n - 1
        oldest = max(0, self._n - self._cap)
        i = newest
        while i >= oldest:
            row = self._ring[i % self._cap]
            i -= 1
            if row is None or row[0] < cutoff:
                break
            oldest_seen = row[0]
            if row[1] >= threshold:
                bad += row[1]
        observed = min(window, now - max(self.started_mono, cutoff))
        # A ring that wrapped inside the window shrinks what we can vouch
        # for to the retained span.
        if self._n > self._cap:
            observed = min(observed, now - oldest_seen)
        observed = max(0.0, observed)
        return min(bad, observed), observed

    def summary(self) -> dict:
        return {
            "name": self.name,
            "interval_s": self.interval_s,
            "slow_s": self.slow_s,
            "ticks": self._n,
            "max_lag_s": round(self.max_lag_s, 6),
            "slow_ticks": self.slow_ticks,
            "histogram": {
                "edges_s": list(_LAG_EDGES),
                "counts": list(self._buckets),
            },
        }


# --------------------------------------------------------------------- #
# (c) GC observatory
# --------------------------------------------------------------------- #

class GCObservatory:
    """gc.callbacks pause clock. Collections are not reentrant, so one
    start stamp per observatory suffices; the callback runs on whatever
    thread triggered the collection — everything it touches is a scalar
    store or a bounded bucket increment."""

    _GENS = ("0", "1", "2")

    def __init__(self, obs: "RuntimeObservatory", slow_s: float = 0.05):
        self.obs = obs
        self.slow_s = slow_s
        self.collections = [0, 0, 0]
        self.collected = 0
        self.uncollectable = 0
        self.max_pause_s = 0.0
        self.slow_pauses = 0
        self._pause_sum = [0.0, 0.0, 0.0]
        self._start_pc = -1.0
        self._armed = False
        self._pause_children = [GC_PAUSE_SECONDS.labels(g)
                                for g in self._GENS]
        self._count_children = [GC_COLLECTIONS_TOTAL.labels(g)
                                for g in self._GENS]

    def arm(self) -> None:
        if not self._armed:
            gc.callbacks.append(self._cb)
            self._armed = True

    def disarm(self) -> None:
        if self._armed:
            try:
                gc.callbacks.remove(self._cb)
            except ValueError:
                pass
            self._armed = False

    def _cb(self, phase: str, info: dict) -> None:
        if phase == "start":
            self._start_pc = time.perf_counter()
            return
        if self._start_pc < 0:
            return
        pause = time.perf_counter() - self._start_pc
        self._start_pc = -1.0
        gen = min(2, max(0, int(info.get("generation", 0))))
        self.collections[gen] += 1
        self._pause_sum[gen] += pause
        self.collected += int(info.get("collected", 0))
        self.uncollectable += int(info.get("uncollectable", 0))
        if pause > self.max_pause_s:
            self.max_pause_s = pause
        self._pause_children[gen].observe(pause)
        self._count_children[gen].inc()
        if pause >= self.slow_s:
            self.slow_pauses += 1
            self.obs._stamp_flights_gc(pause)

    def summary(self) -> dict:
        return {
            "collections": list(self.collections),
            "pause_sum_s": [round(v, 6) for v in self._pause_sum],
            "max_pause_s": round(self.max_pause_s, 6),
            "slow_pauses": self.slow_pauses,
            "slow_s": self.slow_s,
            "collected": self.collected,
            "uncollectable": self.uncollectable,
            "tracked": gc.get_count(),
        }


# --------------------------------------------------------------------- #
# The umbrella + process singleton
# --------------------------------------------------------------------- #

class RuntimeObservatory:
    """Sampler + per-loop lag probes + GC observatory behind one handle.
    ``recorder`` (a pkg/flight.FlightRecorder) is where slow ticks and
    slow GC pauses land as typed events; roles without a recorder
    (scheduler) just skip the stamping."""

    def __init__(self, cfg: "ProfConfig | None" = None, recorder=None):
        self.cfg = cfg or ProfConfig()
        self.recorder = recorder
        self.sampler = StackSampler(self.cfg.hz, self.cfg.max_nodes,
                                    self.cfg.max_depth)
        self.gc = GCObservatory(self, self.cfg.gc_slow_s)
        self.probes: "dict[str, LoopLagProbe]" = {}
        self.started_wall = time.time()

    # -- lifecycle ---------------------------------------------------------

    def start(self) -> None:
        self.sampler.start()
        self.gc.arm()

    def stop(self) -> None:
        for probe in self.probes.values():
            probe.disarm()
        self.probes.clear()
        self.gc.disarm()
        self.sampler.stop()

    def arm_loop(self, name: str = "main") -> LoopLagProbe:
        """Attach a lag probe to the RUNNING loop (call from it). One
        probe per name; re-arming a name replaces the old probe."""
        old = self.probes.get(name)
        if old is not None:
            old.disarm()
        probe = LoopLagProbe(
            self, name, self.cfg.lag_interval_s, self.cfg.lag_slow_s,
            self.cfg.lag_ring)
        self.probes[name] = probe
        return probe.arm()

    # -- flight stamping ---------------------------------------------------

    def _stamp_flights_loop_lag(self, lag: float) -> None:
        rec = self.recorder
        if rec is not None:
            from dragonfly2_tpu.pkg import flight as flightlib

            rec.stamp_running(flightlib.EV_LOOP_LAG, lag, "loop_lag")

    def _stamp_flights_gc(self, pause: float) -> None:
        rec = self.recorder
        if rec is not None:
            from dragonfly2_tpu.pkg import flight as flightlib

            rec.stamp_running(flightlib.EV_GC_PAUSE, pause, "gc_pause")

    # -- SLO feed ----------------------------------------------------------

    def slo_probes(self) -> dict:
        """pkg/slo kind="probe" callables, keyed by spec field."""
        return {"loop_lag": self._loop_lag_counts}

    def _loop_lag_counts(self, window: float,
                         threshold: float) -> "tuple[float, float]":
        bad = total = 0.0
        for probe in self.probes.values():
            b, t = probe.wedged_seconds(window, threshold)
            bad += b
            total += t
        return bad, total

    # -- reports -----------------------------------------------------------

    def runtime_report(self) -> dict:
        """/debug/prof/runtime: loop lag + GC + /proc gauges (and the
        Prometheus runtime_* gauges refresh here too — scrape-time, not
        continuous)."""
        proc = proc_stats()
        RSS_BYTES.set(proc["rss_bytes"])
        OPEN_FDS.set(proc["open_fds"])
        THREADS_GAUGE.set(proc["threads"])
        CTX_SWITCHES.labels("voluntary").set(
            proc["voluntary_ctx_switches"])
        CTX_SWITCHES.labels("involuntary").set(
            proc["involuntary_ctx_switches"])
        return {
            "loops": [p.summary() for p in self.probes.values()],
            "gc": self.gc.summary(),
            "proc": proc,
            "uptime_s": round(time.time() - self.started_wall, 1),
        }

    def profile_report(self, topn: int = 20) -> dict:
        return self.sampler.report(topn)

    def folded(self, max_lines: int = 4096) -> str:
        return self.sampler.folded(max_lines)

    def postmortem(self, topn: int = 10) -> dict:
        """Pruned snapshot for flight post-mortem bundles: what the
        PROCESS was doing when the task died — top frames per thread,
        loop-lag and GC summaries, proc gauges."""
        prof = self.sampler.report(topn)
        return {
            "prof": {
                "samples": prof["samples"],
                "truncated": prof["truncated"],
                "threads": {
                    name: t["top_self"][:topn]
                    for name, t in prof["threads"].items() if t["top_self"]
                },
            },
            "loops": [p.summary() for p in self.probes.values()],
            "gc": self.gc.summary(),
            "proc": proc_stats(),
        }


_OBS: "RuntimeObservatory | None" = None
_REFS = 0
_OBS_LOCK = threading.Lock()


def install(cfg: "ProfConfig | None" = None,
            recorder=None) -> RuntimeObservatory:
    """Get-or-create the process observatory (refcounted — pair every
    install with a release). The first caller's config wins; a recorder
    attaches whenever one is offered and none is set."""
    global _OBS, _REFS
    with _OBS_LOCK:
        if _OBS is None:
            _OBS = RuntimeObservatory(cfg)
            _OBS.start()
        if recorder is not None and _OBS.recorder is None:
            _OBS.recorder = recorder
        _REFS += 1
        return _OBS


def release(obs: RuntimeObservatory) -> None:
    global _OBS, _REFS
    with _OBS_LOCK:
        if obs is not _OBS:
            obs.stop()      # a privately-constructed observatory
            return
        _REFS -= 1
        if _REFS <= 0:
            _OBS, _REFS = None, 0
            obs.stop()


def observatory() -> "RuntimeObservatory | None":
    return _OBS


def fallback_snapshot(top: int = 5) -> dict:
    """Runtime snapshot for bench.py's structured device fallback: where
    the probe attempt spent its wall time (sampler top frames), RSS, and
    loop lag if a probe is armed. Works unarmed (frames empty)."""
    obs = _OBS
    proc = proc_stats()
    out = {
        "rss_mb": round(proc["rss_bytes"] / 1e6, 1),
        "open_fds": proc["open_fds"],
        "threads": proc["threads"],
        "samples": 0,
        "top_self": [],
        "max_loop_lag_ms": None,
        "gc_collections": None,
    }
    if obs is not None:
        out["samples"] = obs.sampler.samples
        out["top_self"] = obs.sampler.top_frames(top)
        out["gc_collections"] = sum(obs.gc.collections)
        if obs.probes:
            out["max_loop_lag_ms"] = round(
                max(p.max_lag_s for p in obs.probes.values()) * 1000, 2)
    return out
