"""Generic dynamic-config pull cache.

Reference: internal/dynconfig/dynconfig.go — periodic refresh (:63), on-disk
cache file surviving manager outages (:86), observer notification on change.
Specialised by scheduler/dynconfig.py and daemon/dynconfig.py exactly like
the reference's scheduler/config/dynconfig.go and
client/config/dynconfig_manager.go.
"""

from __future__ import annotations

import asyncio
import json
import os
from typing import Any, Awaitable, Callable

from dragonfly2_tpu.pkg import dflog

log = dflog.get("dynconfig")

Fetcher = Callable[[], Awaitable[dict[str, Any]]]
Observer = Callable[[dict[str, Any]], None]

DEFAULT_REFRESH_INTERVAL = 10.0  # reference default 10s (dynconfig.go)


class Dynconfig:
    def __init__(self, name: str, fetch: Fetcher, *,
                 refresh_interval: float = DEFAULT_REFRESH_INTERVAL,
                 cache_dir: str = ""):
        self.name = name
        self._fetch = fetch
        self.refresh_interval = refresh_interval
        self._cache_file = (os.path.join(cache_dir, f"dynconfig-{name}.json")
                            if cache_dir else "")
        self._data: dict[str, Any] | None = None
        self._observers: list[Observer] = []
        self._task: asyncio.Task | None = None

    def register(self, observer: Observer) -> None:
        """Observer fires on every successful refresh that changed the data
        (reference dynconfig.go Register/Notify)."""
        self._observers.append(observer)

    def cached(self) -> dict[str, Any]:
        """Non-blocking view of the last-fetched data ({} before the first
        refresh). Falls back to the on-disk cache file so consumers see
        data immediately after a restart."""
        if self._data is None and self._cache_file and os.path.exists(self._cache_file):
            try:
                with open(self._cache_file) as f:
                    self._data = json.load(f)
            except (OSError, ValueError):
                pass
        return self._data or {}

    async def get(self) -> dict[str, Any]:
        if self._data is None:
            await self.refresh()
        return self._data or {}

    async def refresh(self) -> bool:
        """Pull once. On failure fall back to the on-disk cache; returns
        True if data is available afterwards."""
        try:
            data = await self._fetch()
        except Exception as e:
            log.warning("dynconfig fetch failed", name=self.name, error=str(e))
            if self._data is None and self._cache_file and os.path.exists(self._cache_file):
                try:
                    with open(self._cache_file) as f:
                        self._data = json.load(f)
                    log.info("dynconfig loaded from cache file", name=self.name)
                except Exception:
                    pass
            return self._data is not None
        changed = data != self._data
        self._data = data
        if self._cache_file:
            try:
                os.makedirs(os.path.dirname(self._cache_file), exist_ok=True)
                tmp = self._cache_file + ".tmp"
                with open(tmp, "w") as f:
                    json.dump(data, f)
                os.replace(tmp, self._cache_file)
            except OSError as e:
                log.warning("dynconfig cache write failed", error=str(e))
        if changed:
            for obs in self._observers:
                try:
                    obs(data)
                except Exception as e:
                    log.warning("dynconfig observer failed", error=str(e))
        return True

    def serve(self) -> None:
        if self._task is None or self._task.done():
            self._task = asyncio.create_task(self._loop())

    async def _loop(self) -> None:
        while True:
            try:
                await self.refresh()
            except asyncio.CancelledError:
                raise
            except Exception as e:  # fetcher bugs must not kill the loop
                log.warning("dynconfig refresh error", name=self.name, error=str(e))
            await asyncio.sleep(self.refresh_interval)

    def stop(self) -> None:
        if self._task is not None:
            self._task.cancel()
            self._task = None
