"""Reusable piece-buffer pool for the zero-copy receive AND serve paths.

Piece bodies used to materialize as throwaway ``bytes`` at every hop
(``resp.read()``, ``bytes(buf[:piece_size])``, ``b"".join``) — at 4-32 MiB
a piece, that is allocator churn plus a full memory copy per hop on the
daemon's one hot core. The pool hands out ``memoryview`` windows over
recycled bytearrays instead; receive loops fill them in place, the store
writes straight from them, serve paths preadv into them, and release()
parks the backing buffer for the next piece.

Ownership rules (documented in docs/ZERO_COPY.md):
  - acquire() transfers ownership to the caller; exactly one release()
    returns it. Double-release is refused (the buffer is already free).
  - A released view must not be read again — the next acquire() will
    overwrite its bytes.
  - Consumers that must RETAIN piece bytes past the call that handed them
    over (device sinks, caches) must copy (``bytes(view)``); everything on
    the receive→verify→store→serve path only borrows.

Every pool is observable: acquire/release counts and retained bytes feed
the shared Prometheus registry (``bufpool_acquires_total{pool=...}``,
``bufpool_retained_bytes{pool=...}``) so any binary's metrics endpoint
(pkg/metrics_server) exposes read-path buffer behavior, and the
acquire/release balance is assertable in leak-guard tests
(``outstanding`` in stats()).
"""

from __future__ import annotations

import threading

from dragonfly2_tpu.pkg import metrics

_MB = 1 << 20

BUFPOOL_ACQUIRES = metrics.counter(
    "bufpool_acquires_total",
    "Buffer-pool acquires (pooled-hit vs fresh allocation)",
    ("pool", "source"))
BUFPOOL_RELEASES = metrics.counter(
    "bufpool_releases_total",
    "Buffer-pool releases (retained for reuse vs dropped over cap)",
    ("pool", "outcome"))
BUFPOOL_RETAINED = metrics.gauge(
    "bufpool_retained_bytes",
    "Bytes currently parked in the buffer-pool free list", ("pool",))


class BufferPool:
    """Free-list of bytearrays, bounded by total retained bytes. Thread-safe
    (release happens on worker threads after off-loop store writes/reads)."""

    def __init__(self, max_retained_bytes: int = 64 * _MB,
                 name: str = "default"):
        self.name = name
        self._free: list[bytearray] = []
        self._retained = 0
        self._max_retained = max_retained_bytes
        self._mu = threading.Lock()
        self._acquires = 0
        self._releases = 0
        # Labeled children resolved once: .labels() is a dict lookup plus
        # tuple hash per call — measurable at per-piece frequency.
        self._m_pooled = BUFPOOL_ACQUIRES.labels(name, "pooled")
        self._m_fresh = BUFPOOL_ACQUIRES.labels(name, "fresh")
        self._m_retained_rel = BUFPOOL_RELEASES.labels(name, "retained")
        self._m_dropped_rel = BUFPOOL_RELEASES.labels(name, "dropped")
        self._m_retained_bytes = BUFPOOL_RETAINED.labels(name)

    def acquire(self, size: int) -> memoryview:
        """A writable ``memoryview`` of exactly ``size`` bytes over a pooled
        (or fresh) bytearray."""
        size = max(size, 1)
        with self._mu:
            self._acquires += 1
            # First fit that's large enough; the fleet of piece buffers in
            # one daemon is near-uniform in size, so this is ~always hit #0.
            for i, ba in enumerate(self._free):
                if len(ba) >= size:
                    self._free.pop(i)
                    self._retained -= len(ba)
                    self._m_pooled.inc()
                    self._m_retained_bytes.set(self._retained)
                    return memoryview(ba)[:size]
        self._m_fresh.inc()
        return memoryview(bytearray(size))

    def release(self, view: "memoryview | bytearray | bytes | None") -> None:
        """Return a buffer obtained from acquire(). Tolerant of plain bytes
        (non-pooled fallback paths): those are simply dropped."""
        if isinstance(view, memoryview):
            obj = view.obj
            view.release()
        else:
            obj = view
        if not isinstance(obj, bytearray):
            return
        with self._mu:
            self._releases += 1
            if self._retained + len(obj) <= self._max_retained:
                self._free.append(obj)
                self._retained += len(obj)
                self._m_retained_rel.inc()
                self._m_retained_bytes.set(self._retained)
            else:
                self._m_dropped_rel.inc()

    def stats(self) -> dict:
        with self._mu:
            return {"free_buffers": len(self._free),
                    "retained_bytes": self._retained,
                    "acquires": self._acquires,
                    "releases": self._releases,
                    # Views handed out and not yet returned. Paths that
                    # legitimately drop views (the pool only loses reuse)
                    # keep this >0; leak-guard tests snapshot before/after
                    # a balanced path and assert the DELTA is zero.
                    "outstanding": self._acquires - self._releases}
