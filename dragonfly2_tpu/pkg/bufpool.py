"""Reusable piece-buffer pool for the zero-copy receive path.

Piece bodies used to materialize as throwaway ``bytes`` at every hop
(``resp.read()``, ``bytes(buf[:piece_size])``, ``b"".join``) — at 4-32 MiB
a piece, that is allocator churn plus a full memory copy per hop on the
daemon's one hot core. The pool hands out ``memoryview`` windows over
recycled bytearrays instead; receive loops fill them in place, the store
writes straight from them, and release() parks the backing buffer for the
next piece.

Ownership rules (documented in docs/ZERO_COPY.md):
  - acquire() transfers ownership to the caller; exactly one release()
    returns it. Double-release is refused (the buffer is already free).
  - A released view must not be read again — the next acquire() will
    overwrite its bytes.
  - Consumers that must RETAIN piece bytes past the call that handed them
    over (device sinks, caches) must copy (``bytes(view)``); everything on
    the receive→verify→store path only borrows.
"""

from __future__ import annotations

import threading

_MB = 1 << 20


class BufferPool:
    """Free-list of bytearrays, bounded by total retained bytes. Thread-safe
    (release happens on worker threads after off-loop store writes)."""

    def __init__(self, max_retained_bytes: int = 64 * _MB):
        self._free: list[bytearray] = []
        self._retained = 0
        self._max_retained = max_retained_bytes
        self._mu = threading.Lock()

    def acquire(self, size: int) -> memoryview:
        """A writable ``memoryview`` of exactly ``size`` bytes over a pooled
        (or fresh) bytearray."""
        size = max(size, 1)
        with self._mu:
            # First fit that's large enough; the fleet of piece buffers in
            # one daemon is near-uniform in size, so this is ~always hit #0.
            for i, ba in enumerate(self._free):
                if len(ba) >= size:
                    self._free.pop(i)
                    self._retained -= len(ba)
                    return memoryview(ba)[:size]
        return memoryview(bytearray(size))

    def release(self, view: "memoryview | bytearray | bytes | None") -> None:
        """Return a buffer obtained from acquire(). Tolerant of plain bytes
        (non-pooled fallback paths): those are simply dropped."""
        if isinstance(view, memoryview):
            obj = view.obj
            view.release()
        else:
            obj = view
        if not isinstance(obj, bytearray):
            return
        with self._mu:
            if self._retained + len(obj) <= self._max_retained:
                self._free.append(obj)
                self._retained += len(obj)

    def stats(self) -> dict:
        with self._mu:
            return {"free_buffers": len(self._free),
                    "retained_bytes": self._retained}
