"""TTL cache and periodic GC runner.

Reference: pkg/cache/cache.go (TTL cache with expiry janitor) and
pkg/gc/gc.go:28-77 + task.go (named periodic GC tasks used by both the
scheduler and the daemon).
"""

from __future__ import annotations

import asyncio
import threading
import time
from dataclasses import dataclass
from typing import Any, Awaitable, Callable

NO_EXPIRATION = -1.0


class TTLCache:
    """Thread-safe TTL cache (reference pkg/cache/cache.go)."""

    def __init__(self, default_ttl: float = NO_EXPIRATION):
        self._default_ttl = default_ttl
        self._items: dict[str, tuple[Any, float]] = {}
        self._mu = threading.Lock()

    def set(self, key: str, value: Any, ttl: float | None = None) -> None:
        ttl = self._default_ttl if ttl is None else ttl
        expires = NO_EXPIRATION if ttl == NO_EXPIRATION else time.monotonic() + ttl
        with self._mu:
            self._items[key] = (value, expires)

    def get(self, key: str) -> tuple[Any, bool]:
        with self._mu:
            item = self._items.get(key)
            if item is None:
                return None, False
            value, expires = item
            if expires != NO_EXPIRATION and time.monotonic() > expires:
                del self._items[key]
                return None, False
            return value, True

    def delete(self, key: str) -> None:
        with self._mu:
            self._items.pop(key, None)

    def keys(self) -> list[str]:
        with self._mu:
            now = time.monotonic()
            return [k for k, (_, exp) in self._items.items() if exp == NO_EXPIRATION or exp >= now]

    def purge_expired(self) -> int:
        with self._mu:
            now = time.monotonic()
            dead = [k for k, (_, exp) in self._items.items() if exp != NO_EXPIRATION and exp < now]
            for k in dead:
                del self._items[k]
            return len(dead)

    def __len__(self) -> int:
        return len(self.keys())


@dataclass
class GCTask:
    """One named periodic GC job (reference pkg/gc/task.go)."""

    id: str
    interval: float
    timeout: float
    runner: Callable[[], Awaitable[None]] | Callable[[], None]


class GC:
    """Named periodic GC driver (reference pkg/gc/gc.go:28,63-77). Runs each
    registered task on its own interval inside the host event loop."""

    def __init__(self, logger=None):
        self._tasks: dict[str, GCTask] = {}
        self._handles: list[asyncio.Task] = []
        self._log = logger
        self._running = False

    def add(self, task: GCTask) -> None:
        if task.id in self._tasks:
            raise ValueError(f"gc task {task.id} exists")
        self._tasks[task.id] = task

    async def _loop(self, task: GCTask) -> None:
        while True:
            await asyncio.sleep(task.interval)
            try:
                result = task.runner()
                if asyncio.iscoroutine(result):
                    await asyncio.wait_for(result, timeout=task.timeout)
            except asyncio.CancelledError:
                raise
            except Exception as e:  # GC must never kill the server
                if self._log:
                    self._log.error(f"gc task {task.id} failed", error=str(e))

    async def run(self, task_id: str) -> None:
        """Run one task immediately (reference gc.go Run)."""
        task = self._tasks[task_id]
        result = task.runner()
        if asyncio.iscoroutine(result):
            await result

    def serve(self) -> None:
        if self._running:
            return
        self._running = True
        for task in self._tasks.values():
            self._handles.append(asyncio.get_running_loop().create_task(self._loop(task)))

    def stop(self) -> None:
        for h in self._handles:
            h.cancel()
        self._handles.clear()
        self._running = False
