"""Async token-bucket rate limiting.

Reference: golang.org/x/time/rate as used by the piece manager
(client/daemon/peer/piece_manager.go waitLimit), the upload manager
(upload/upload_manager.go:79 WithLimiter) and the traffic shaper
(traffic_shaper.go). Limits are bytes/second with a burst bucket.
"""

from __future__ import annotations

import asyncio
import time

INF = float("inf")


class Limiter:
    """Token bucket. ``limit`` tokens/second, bucket size ``burst``.

    asyncio-native: waiters sleep exactly until their reservation matures,
    which keeps a single-core daemon responsive under load.
    """

    def __init__(self, limit: float = INF, burst: int | None = None):
        self._limit = limit
        if burst is None:
            burst = int(limit) if limit != INF else 1 << 62
        self._burst = max(1, burst)
        self._tokens = float(self._burst)
        self._last = time.monotonic()
        self._lock = asyncio.Lock()
        self._resume: asyncio.Event | None = None  # waiters parked on limit<=0

    @property
    def limit(self) -> float:
        return self._limit

    def set_limit(self, limit: float, burst: int | None = None) -> None:
        """Dynamic re-allocation (traffic shaper re-tunes per-task limits)."""
        self._advance()
        self._limit = limit
        if burst is not None:
            self._burst = max(1, burst)
        elif limit != INF:
            self._burst = max(int(limit), 1)
        self._tokens = min(self._tokens, float(self._burst))
        if limit > 0 and self._resume is not None:
            self._resume.set()  # wake waiters parked by a zero limit
            self._resume = None

    def _advance(self) -> None:
        now = time.monotonic()
        if self._limit != INF:
            self._tokens = min(float(self._burst), self._tokens + (now - self._last) * self._limit)
        else:
            self._tokens = float(self._burst)
        self._last = now

    def allow(self, n: int = 1) -> bool:
        self._advance()
        if self._tokens >= n:
            self._tokens -= n
            return True
        return False

    def can_allow(self, n: int = 1) -> bool:
        """Non-mutating: would allow(n) succeed right now? Lets callers
        check SEVERAL buckets before debiting any (all-or-nothing takes
        across clusters must not drain earlier buckets on a later deny)."""
        self._advance()
        return self._tokens >= n

    async def wait(self, n: int = 1) -> float:
        """Block until ``n`` tokens are available; returns seconds waited."""
        if self._limit == INF:
            return 0.0
        while self._limit <= 0:
            # Limit 0 pauses the transfer; a later set_limit(>0) resumes it
            # (the traffic shaper uses this to pause/resume tasks).
            if self._resume is None:
                self._resume = asyncio.Event()
            await self._resume.wait()
        if n > self._burst:
            # A single request larger than the bucket: pay for it across
            # multiple bucket fills rather than deadlocking. Non-virtual
            # call — subclasses that override wait() for accounting (the
            # traffic shaper's window counter) must see ONE request, not
            # request + its chunks.
            waited = 0.0
            remaining = n
            while remaining > 0:
                chunk = min(remaining, self._burst)
                waited += await Limiter.wait(self, chunk)
                remaining -= chunk
            return waited
        start = time.monotonic()
        async with self._lock:  # lock held through the sleep → FIFO fairness
            self._advance()
            if self._tokens >= n:
                self._tokens -= n
                return 0.0
            deficit = n - self._tokens
            delay = deficit / self._limit
            self._tokens -= n  # reserve (goes negative; matures over time)
            try:
                await asyncio.sleep(delay)
            except asyncio.CancelledError:
                # Cancelled waiters must not consume budget (x/time/rate
                # returns the reservation on ctx cancel).
                self._tokens += n
                raise
        return time.monotonic() - start
