"""asyncio helpers.

``spawn`` is the fire-and-forget task launcher: the event loop keeps only
weak references to tasks, so a bare ``ensure_future`` can be garbage
collected mid-flight; spawned tasks are held strongly until done (the same
bug class the reference avoids with Go's structured goroutine ownership).
"""

from __future__ import annotations

import asyncio
from typing import Coroutine

_BACKGROUND: set[asyncio.Task] = set()


def spawn(coro: Coroutine) -> asyncio.Task:
    task = asyncio.ensure_future(coro)
    _BACKGROUND.add(task)
    task.add_done_callback(_BACKGROUND.discard)
    return task
