"""Retry helper with exponential backoff (reference: pkg/retry/retry.go)."""

from __future__ import annotations

import asyncio
import random
from typing import Awaitable, Callable, TypeVar

T = TypeVar("T")


async def run(
    fn: Callable[[], Awaitable[T]],
    *,
    init_backoff: float = 0.2,
    max_backoff: float = 5.0,
    max_attempts: int = 5,
    cancel: asyncio.Event | None = None,
    retryable: Callable[[Exception], bool] | None = None,
) -> T:
    """Run ``fn`` until success, with jittered exponential backoff.

    Raises the last error after ``max_attempts``. ``retryable`` can mark
    errors as terminal (returns False → raise immediately).
    """
    backoff = init_backoff
    last: Exception | None = None
    for attempt in range(max_attempts):
        if cancel is not None and cancel.is_set():
            raise asyncio.CancelledError()
        try:
            return await fn()
        except asyncio.CancelledError:
            raise
        except Exception as e:
            last = e
            if retryable is not None and not retryable(e):
                raise
            if attempt == max_attempts - 1:
                break
            await asyncio.sleep(backoff * (0.5 + random.random()))
            backoff = min(backoff * 2, max_backoff)
    assert last is not None
    raise last
