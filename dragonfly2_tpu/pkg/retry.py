"""The single retry/backoff policy for every reconnect/refetch loop.

Reference: pkg/retry/retry.go (capped exponential backoff used by
scheduler reconnects and back-to-source pulls) plus the "exponential
backoff and full jitter" discipline. Before this module each loop rolled
its own: eager reconnect-on-next-use in rpc/client, fixed raw retries in
the source clients. Everything now shares one policy object:

  * capped exponential delay: ``min(cap, base * multiplier**attempt)``
  * full jitter by default: the actual sleep is uniform in [0, delay], so
    a thousand daemons whose scheduler just died don't reconnect in
    lockstep waves
  * a progress watchdog (``watch_idle``) that bounds the gap BETWEEN
    chunks — the slow-loris defense an overall timeout can't express
    without also capping legitimate large transfers.

Used by rpc/client (reconnect pacing), the peer conductor (announce-stream
recovery budget), piece_downloader (chunk-gap watchdog), and
piece_manager's origin retry (temporary-only, so a permanent 403/404
never burns the back-to-source budget).
"""

from __future__ import annotations

import asyncio
import random
from dataclasses import dataclass
from typing import AsyncIterator, Awaitable, Callable, TypeVar

T = TypeVar("T")


class ProgressTimeout(asyncio.TimeoutError):
    """No forward progress (no chunk/no byte) within the idle budget."""


@dataclass(frozen=True)
class BackoffPolicy:
    """Capped exponential backoff with full jitter.

    ``delay(attempt)`` for attempt = 0, 1, 2, ... — attempt 0 is the delay
    BEFORE the first retry (the first try itself is free).
    """

    base: float = 0.1
    cap: float = 5.0
    multiplier: float = 2.0
    jitter: bool = True

    def raw_delay(self, attempt: int) -> float:
        """The jitterless ceiling for ``attempt`` (tests pin this)."""
        if attempt < 0:
            return 0.0
        return min(self.cap, self.base * self.multiplier ** attempt)

    def delay(self, attempt: int,
              rng: Callable[[], float] = random.random) -> float:
        raw = self.raw_delay(attempt)
        if not self.jitter:
            return raw
        # Full jitter: uniform in [0, raw]. rng is injectable so seeded
        # tests stay deterministic.
        return raw * rng()


# Shared defaults, tuned per call family:
#   RECONNECT — rpc client to a flapping scheduler: fast first retry,
#     bounded so a unary call's own timeout still dominates.
#   ANNOUNCE — conductor announce-stream recovery: a little slower; the
#     piece workers keep downloading while it runs.
#   SOURCE — origin refetch: origins rate-limit; back off harder.
RECONNECT = BackoffPolicy(base=0.05, cap=2.0)
ANNOUNCE = BackoffPolicy(base=0.1, cap=3.0)
SOURCE = BackoffPolicy(base=0.2, cap=10.0)


async def run(
    fn: Callable[[], Awaitable[T]],
    *,
    policy: BackoffPolicy | None = None,
    max_attempts: int = 5,
    cancel: asyncio.Event | None = None,
    retryable: Callable[[BaseException], bool] | None = None,
    rng: Callable[[], float] = random.random,
    init_backoff: float | None = None,
    max_backoff: float | None = None,
) -> T:
    """Run ``fn`` until success with the shared backoff policy.

    Raises the last error after ``max_attempts``. ``retryable`` can mark
    errors as terminal (returns False → raise immediately). The legacy
    ``init_backoff``/``max_backoff`` kwargs build an equivalent policy.
    """
    if policy is None:
        policy = BackoffPolicy(base=init_backoff if init_backoff is not None
                               else 0.2,
                               cap=max_backoff if max_backoff is not None
                               else 5.0)
    last: BaseException | None = None
    for attempt in range(max_attempts):
        if cancel is not None and cancel.is_set():
            raise asyncio.CancelledError()
        try:
            return await fn()
        except asyncio.CancelledError:
            raise
        except Exception as e:
            last = e
            if retryable is not None and not retryable(e):
                raise
            if attempt == max_attempts - 1:
                break
            await asyncio.sleep(policy.delay(attempt, rng))
    assert last is not None
    raise last


async def watch_idle(chunks: AsyncIterator[bytes], idle_timeout: float,
                     what: str = "stream") -> AsyncIterator[bytes]:
    """Per-chunk progress watchdog: yield from ``chunks`` but raise
    ``ProgressTimeout`` when the gap between consecutive chunks exceeds
    ``idle_timeout``. An overall deadline cannot distinguish a healthy
    10 GiB transfer from a slow-loris parent trickling one byte a minute;
    a chunk-gap bound can. ``idle_timeout <= 0`` disables the watchdog."""
    if idle_timeout <= 0:
        async for chunk in chunks:
            yield chunk
        return
    it = chunks.__aiter__()
    while True:
        try:
            chunk = await asyncio.wait_for(it.__anext__(), idle_timeout)
        except StopAsyncIteration:
            return
        except asyncio.TimeoutError:
            raise ProgressTimeout(
                f"{what}: no data for {idle_timeout:.1f}s (stalled)")
        yield chunk
