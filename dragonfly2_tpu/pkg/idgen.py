"""Task / peer / host ID generation.

Reference: pkg/idgen/task_id.go:36-101, peer_id.go:24-39, host_id.go:24-29.
Task IDs are content addresses: sha256 over the filtered URL plus
distinguishing metadata, so identical content maps to one task cluster-wide.
"""

from __future__ import annotations

import os
import uuid
from urllib.parse import parse_qsl, urlencode, urlsplit, urlunsplit

from dragonfly2_tpu.pkg import digest as pkgdigest

FILTERED_QUERY_PARAMS_SEPARATOR = "&"


def filter_query_params(url: str, filtered: list[str] | None) -> str:
    """Remove named query params and sort the rest for a canonical URL
    (reference pkg/net/url FilterQueryParams used by task_id.go:59,95)."""
    if not filtered:
        filtered = []
    try:
        parts = urlsplit(url)
        pairs = parse_qsl(parts.query, keep_blank_values=True)
        kept = [(k, v) for k, v in pairs if k not in set(filtered)]
        # Canonical ordering so param order never changes the task ID.
        kept.sort()
        return urlunsplit((parts.scheme, parts.netloc, parts.path, urlencode(kept), ""))
    except ValueError:
        return ""


def parse_filtered_query_params(raw: str | None) -> list[str]:
    """Split '&'-separated filter string (reference task_id.go:85-91)."""
    if not raw or not raw.strip():
        return []
    return raw.split(FILTERED_QUERY_PARAMS_SEPARATOR)


def task_id_v1(
    url: str,
    *,
    digest: str = "",
    tag: str = "",
    application: str = "",
    filters: str = "",
    range_header: str = "",
    ignore_range: bool = False,
) -> str:
    """v1 task ID (reference task_id.go:46-82): sha256 over filtered URL +
    digest + range + tag + application (present fields only)."""
    u = filter_query_params(url, parse_filtered_query_params(filters))
    data = [u]
    if digest:
        data.append(digest)
    if not ignore_range and range_header:
        data.append(range_header)
    if tag:
        data.append(tag)
    if application:
        data.append(application)
    return pkgdigest.sha256_from_strings(*data)


def parent_task_id_v1(url: str, **kwargs) -> str:
    """Task ID ignoring the range — used to look up whole-file parents for
    ranged requests (reference task_id.go:40-44)."""
    kwargs["ignore_range"] = True
    return task_id_v1(url, **kwargs)


def task_id_v2(url: str, tag: str = "", application: str = "", filtered_query_params: list[str] | None = None) -> str:
    """v2 task ID (reference task_id.go:94-101)."""
    u = filter_query_params(url, filtered_query_params or [])
    return pkgdigest.sha256_from_strings(u, tag, application)


def persistent_cache_task_id(content_digest: str, tag: str = "", application: str = "") -> str:
    """Persistent-cache tasks are addressed by content digest, not URL."""
    return pkgdigest.sha256_from_strings(content_digest, tag, application)


def peer_id_v1(ip: str) -> str:
    """``ip-pid-uuid`` (reference peer_id.go:27-29)."""
    return f"{ip}-{os.getpid()}-{uuid.uuid4()}"


def seed_peer_id_v1(ip: str) -> str:
    """Seed-peer IDs carry a ``_Seed`` suffix (reference peer_id.go:32-34);
    the scheduler uses this marker to identify seed-originated peers."""
    return f"{peer_id_v1(ip)}_Seed"


def peer_id_v2() -> str:
    return str(uuid.uuid4())


def is_seed_peer_id(peer_id: str) -> bool:
    return peer_id.endswith("_Seed")


def host_id(hostname: str, port: int | None = None) -> str:
    """Host ID (reference host_id.go:24-29): hostname, or hostname-port for
    multi-daemon hosts."""
    if port is None:
        return hostname
    return f"{hostname}-{port}"
