"""Minimal finite-state machine.

Reference: the looplab/fsm library driving Peer and Task lifecycles
(scheduler/resource/standard/peer.go:222-243, task.go:197-219). Events name
transitions; callbacks fire after a successful transition.
"""

from __future__ import annotations

import threading
from dataclasses import dataclass
from typing import Callable


class TransitionError(Exception):
    def __init__(self, event: str, state: str):
        super().__init__(f"event {event!r} inappropriate in current state {state!r}")
        self.event = event
        self.state = state


@dataclass(frozen=True)
class EventDesc:
    name: str
    src: tuple[str, ...]
    dst: str


class FSM:
    def __init__(
        self,
        initial: str,
        events: list[EventDesc],
        callbacks: dict[str, Callable[[str, str, str], None]] | None = None,
    ):
        self._state = initial
        self._events: dict[str, EventDesc] = {e.name: e for e in events}
        self._callbacks = callbacks or {}
        self._mu = threading.RLock()

    @property
    def current(self) -> str:
        with self._mu:
            return self._state

    def is_state(self, *states: str) -> bool:
        with self._mu:
            return self._state in states

    def can(self, event: str) -> bool:
        with self._mu:
            desc = self._events.get(event)
            return desc is not None and self._state in desc.src

    def restore(self, state: str) -> None:
        """Set the state directly, bypassing transitions — ONLY for
        rebuilding an FSM from a durable snapshot (scheduler HA restore),
        where the recorded state was reached through real transitions in
        a previous process. No callbacks fire."""
        with self._mu:
            self._state = state

    def event(self, name: str) -> None:
        with self._mu:
            desc = self._events.get(name)
            if desc is None or self._state not in desc.src:
                raise TransitionError(name, self._state)
            src = self._state
            self._state = desc.dst
            cb = self._callbacks.get(name)
        if cb is not None:
            cb(name, src, desc.dst)
