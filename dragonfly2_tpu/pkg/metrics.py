"""Prometheus metrics helpers.

Reference: every binary serves Prometheus (scheduler/metrics/metrics.go,
client/daemon/metrics/metrics.go, manager/metrics). We wrap
prometheus_client so subsystems can declare metrics without worrying about
duplicate registration in tests.
"""

from __future__ import annotations

import threading

from prometheus_client import (
    CollectorRegistry,
    Counter,
    Gauge,
    Histogram,
    generate_latest,
    CONTENT_TYPE_LATEST,
)

_NAMESPACE = "dragonfly_tpu"
_lock = threading.Lock()
_registry = CollectorRegistry()
_metrics: dict[str, object] = {}


def _get_or_create(kind: type, name: str, factory):
    """Metric names are unique per registry regardless of kind; a name reused
    with a different kind is a programming error surfaced eagerly."""
    with _lock:
        existing = _metrics.get(name)
        if existing is not None:
            if not isinstance(existing, kind):
                raise ValueError(
                    f"metric {name!r} already registered as {type(existing).__name__}, not {kind.__name__}"
                )
            return existing
        m = factory()
        _metrics[name] = m
        return m


def counter(name: str, doc: str, labels: tuple[str, ...] = ()) -> "Counter":
    return _get_or_create(
        Counter, name, lambda: Counter(name, doc, labels, namespace=_NAMESPACE, registry=_registry)
    )


def gauge(name: str, doc: str, labels: tuple[str, ...] = ()) -> "Gauge":
    return _get_or_create(
        Gauge, name, lambda: Gauge(name, doc, labels, namespace=_NAMESPACE, registry=_registry)
    )


def histogram(name: str, doc: str, labels: tuple[str, ...] = (), buckets=None) -> "Histogram":
    def factory():
        kwargs = {"namespace": _NAMESPACE, "registry": _registry}
        if buckets is not None:
            kwargs["buckets"] = buckets
        return Histogram(name, doc, labels, **kwargs)

    return _get_or_create(Histogram, name, factory)


def parse_labeled_samples(text: str, full_name: str,
                          label: str) -> dict[str, int]:
    """Parse one labeled metric's samples out of an exposition-format page:
    ``{label_value: int(sample)}``. The single parser for every scraper in
    benches/tests — exposition parsing is just fragile enough that two
    private copies WILL diverge on the first metric rename."""
    out: dict[str, int] = {}
    for line in text.splitlines():
        if line.startswith("#") or "{" not in line:
            continue
        name, rest = line.split("{", 1)
        if name != full_name or "}" not in rest:
            continue
        labels_part, _, value = rest.rpartition("}")
        for kv in labels_part.split(","):
            k, _, v = kv.partition("=")
            if k.strip() == label:
                key = v.strip().strip('"')
                out[key] = out.get(key, 0) + int(float(value))
    return out


def families() -> list[dict]:
    """Every registered metric family: ``{name, kind, doc, labels}``
    (name WITHOUT the namespace prefix — what callers registered). The
    metrics-name lint test walks this to enforce the
    ``{component}_{noun}[_{unit}][_total]`` convention and the
    docs/OBSERVABILITY.md documentation requirement."""
    kind_names = {Counter: "counter", Gauge: "gauge",
                  Histogram: "histogram"}
    with _lock:
        return [
            {
                "name": name,
                "kind": kind_names.get(type(m), type(m).__name__.lower()),
                "doc": m._documentation,
                "labels": tuple(m._labelnames),
            }
            for name, m in sorted(_metrics.items())
        ]


def render(accept: str = "", registry=None) -> tuple[bytes, str]:
    """Render the registry for an HTTP /metrics endpoint.

    Content-negotiated: an ``Accept`` header asking for
    ``application/openmetrics-text`` gets the OpenMetrics exposition
    (``# HELP``/``# TYPE``/``# EOF``, strict label escaping); everything
    else gets the classic Prometheus text format. Both come from
    prometheus_client's exposition writers — the OpenMetrics round-trip
    test (tests/test_metrics_lint.py) parses our output with the strict
    parser and cross-checks ``families()``. ``registry`` overrides the
    process registry (tests probe escaping without polluting it)."""
    reg = registry if registry is not None else _registry
    if "application/openmetrics-text" in (accept or ""):
        from prometheus_client.openmetrics.exposition import (
            CONTENT_TYPE_LATEST as OPENMETRICS_CONTENT_TYPE,
            generate_latest as openmetrics_latest,
        )

        return openmetrics_latest(reg), OPENMETRICS_CONTENT_TYPE
    return generate_latest(reg), CONTENT_TYPE_LATEST
