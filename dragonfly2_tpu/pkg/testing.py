"""Shared in-process test/bench fixtures.

Shipping these in the package (not under tests/) lets benches and
examples reuse them without cross-importing test modules — and keeps ONE
copy of the ranged-origin HTTP handler, whose 206/Content-Range
semantics have already needed coordinated fixes across private copies
twice (served-vs-requested byte counting, clamped Content-Range ends).
"""

from __future__ import annotations


async def start_range_origin(content: bytes):
    """An aiohttp origin serving ``content`` with single-range 206
    support and served-byte accounting. Returns ``(runner, url, stats)``
    — ``await runner.cleanup()`` when done; ``stats["bytes"]`` counts
    bytes actually served (ranges clamped to the content)."""
    from aiohttp import web

    from dragonfly2_tpu.pkg.piece import Range

    stats = {"bytes": 0, "streams": 0}

    async def blob(request):
        stats["streams"] += 1
        hdr = request.headers.get("Range")
        if hdr:
            r = Range.parse_http(hdr, len(content))
            data = content[r.start:r.start + r.length]
            stats["bytes"] += len(data)
            return web.Response(status=206, body=data, headers={
                "Content-Range":
                    f"bytes {r.start}-{r.start + len(data) - 1}"
                    f"/{len(content)}",
                "Accept-Ranges": "bytes"})
        stats["bytes"] += len(content)
        return web.Response(body=content,
                            headers={"Accept-Ranges": "bytes"})

    app = web.Application()
    app.router.add_get("/content", blob)
    runner = web.AppRunner(app, access_log=None)
    await runner.setup()
    site = web.TCPSite(runner, "127.0.0.1", 0)
    await site.start()
    port = site._server.sockets[0].getsockname()[1]
    return runner, f"http://127.0.0.1:{port}/content", stats
