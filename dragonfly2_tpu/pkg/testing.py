"""Shared in-process test/bench fixtures.

Shipping these in the package (not under tests/) lets benches and
examples reuse them without cross-importing test modules — and keeps ONE
copy of the ranged-origin HTTP handler, whose 206/Content-Range
semantics have already needed coordinated fixes across private copies
twice (served-vs-requested byte counting, clamped Content-Range ends).
"""

from __future__ import annotations


async def start_range_origin(content: bytes):
    """An aiohttp origin serving ``content`` with single-range 206
    support and served-byte accounting. Returns ``(runner, url, stats)``
    — ``await runner.cleanup()`` when done; ``stats["bytes"]`` counts
    bytes actually served (ranges clamped to the content)."""
    from aiohttp import web

    from dragonfly2_tpu.pkg.piece import Range

    stats = {"bytes": 0, "streams": 0}

    async def blob(request):
        stats["streams"] += 1
        hdr = request.headers.get("Range")
        if hdr:
            r = Range.parse_http(hdr, len(content))
            data = content[r.start:r.start + r.length]
            stats["bytes"] += len(data)
            return web.Response(status=206, body=data, headers={
                "Content-Range":
                    f"bytes {r.start}-{r.start + len(data) - 1}"
                    f"/{len(content)}",
                "Accept-Ranges": "bytes"})
        stats["bytes"] += len(content)
        return web.Response(body=content,
                            headers={"Accept-Ranges": "bytes"})

    app = web.Application()
    app.router.add_get("/content", blob)
    runner = web.AppRunner(app, access_log=None)
    await runner.setup()
    site = web.TCPSite(runner, "127.0.0.1", 0)
    await site.start()
    port = site._server.sockets[0].getsockname()[1]
    return runner, f"http://127.0.0.1:{port}/content", stats


class GatewayFixture:
    """In-process daemon data plane: an FS-backed object-storage gateway
    on a REAL TaskManager, so gateway GETs / ranged-task reads genuinely
    ride the P2P task machinery. The one fixture for every test/bench
    that needs a live gateway without spawning a daemon process."""

    def __init__(self, svc, port: int, tm, storage, backend, sinks=None):
        self.svc = svc
        self.port = port
        self.tm = tm
        self.storage = storage
        self.backend = backend
        self.sinks = sinks

    @property
    def endpoint(self) -> str:
        return f"http://127.0.0.1:{self.port}"

    def object_url(self, bucket: str, key: str) -> str:
        """The backend origin URL a gateway GET resolves for bucket/key —
        what DaemonRangeFetcher and task-identity assertions need."""
        return self.backend.object_url(bucket, key)

    async def aclose(self) -> None:
        await self.svc.close()
        if self.sinks is not None:
            self.sinks.close()
        self.storage.close()


async def start_gateway_fixture(workdir, *, device_sinks: bool = False,
                                concurrency: int = 2,
                                **svc_kwargs) -> GatewayFixture:
    """Serve an ObjectStorageService on 127.0.0.1:<ephemeral> backed by
    ``workdir/buckets`` (FS backend) and a piece store in ``workdir/p2p``.
    ``device_sinks`` attaches a DeviceSinkManager (prefetch --device=tpu
    paths). Callers ``await fixture.aclose()`` when done."""
    import os

    from dragonfly2_tpu.daemon.objectstorage import ObjectStorageService
    from dragonfly2_tpu.daemon.peer.piece_manager import (
        PieceManager,
        PieceManagerOption,
    )
    from dragonfly2_tpu.daemon.peer.task_manager import TaskManager
    from dragonfly2_tpu.daemon.transport import P2PTransport
    from dragonfly2_tpu.pkg.objectstorage.fs import FSObjectStorage
    from dragonfly2_tpu.storage import StorageManager, StorageOption

    workdir = str(workdir)
    backend = FSObjectStorage(root=os.path.join(workdir, "buckets"))
    storage = StorageManager(
        StorageOption(data_dir=os.path.join(workdir, "p2p")))
    sinks = None
    if device_sinks:
        from dragonfly2_tpu.daemon.peer.device_sink import DeviceSinkManager

        sinks = DeviceSinkManager()
    tm = TaskManager(storage,
                     PieceManager(PieceManagerOption(concurrency=concurrency)),
                     device_sinks=sinks)
    svc = ObjectStorageService(backend, P2PTransport(tm), **svc_kwargs)
    port = await svc.serve("127.0.0.1", 0)
    return GatewayFixture(svc, port, tm, storage, backend, sinks)
