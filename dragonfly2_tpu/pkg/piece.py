"""Piece-size math and piece records.

Reference: internal/util/util.go:22-49 (size scaling law) and the piece
metadata carried in commonv1.PieceInfo / commonv2.Piece. Pieces are the unit
of transfer, verification, scheduling and HBM landing.
"""

from __future__ import annotations

import math
from dataclasses import dataclass, field

_MB = 1024 * 1024
DEFAULT_PIECE_SIZE = 4 * _MB
PIECE_SIZE_LIMIT = 32 * _MB

# Content up to this size keeps the 4 MiB floor; above it the piece size
# scales to hold the piece count near _TARGET_PIECES.
_SCALE_START = 128 * _MB
_TARGET_PIECES = 32


def compute_piece_size(length: int) -> int:
    """Piece size scaling. Deliberately steeper than the reference curve
    (util.go:33-44: 4 MiB → 15 MiB above 200 MiB of content): every piece
    costs a fixed slice of Python control plane on both ends of the hop —
    dispatch, report, metadata — so a task aims for ~32 pieces once content
    outgrows 128 MiB (256 MiB → 8 MiB pieces, 1 GiB → 32 MiB), capped at
    32 MiB (the non-native pull path buffers whole pieces in memory;
    piece_parallelism × cap bounds that transient). 32 pieces still
    saturate the multi-parent pipeline (piece parallelism is 4-8 per
    peer); what the extra pieces bought the reference's Go runtime, they
    cost this one."""
    if length <= 0 or length <= _SCALE_START:
        return DEFAULT_PIECE_SIZE
    target = length // _TARGET_PIECES
    size = ((target + _MB - 1) // _MB) * _MB  # 1 MiB multiple (sink alignment)
    return min(max(size, DEFAULT_PIECE_SIZE), PIECE_SIZE_LIMIT)


def compute_piece_count(length: int, piece_size: int) -> int:
    """ceil(length / piece_size) (reference util.go:47-49)."""
    return math.ceil(length / piece_size)


def piece_offset(piece_num: int, piece_size: int) -> int:
    return piece_num * piece_size


def piece_length(piece_num: int, piece_size: int, content_length: int) -> int:
    """Length of piece ``piece_num`` given total content length."""
    start = piece_num * piece_size
    if content_length < 0:
        return piece_size
    return max(0, min(piece_size, content_length - start))


@dataclass
class PieceInfo:
    """Metadata for one piece (reference commonv1.PieceInfo)."""

    piece_num: int
    range_start: int
    range_size: int
    digest: str = ""            # "md5:..." / "crc32c:..." string form
    download_cost_ms: int = 0   # observed cost, feeds bad-node detection
    dst_peer_id: str = ""       # which parent served it

    def to_wire(self) -> dict:
        return {
            "piece_num": self.piece_num,
            "range_start": self.range_start,
            "range_size": self.range_size,
            "digest": self.digest,
            "download_cost_ms": self.download_cost_ms,
            "dst_peer_id": self.dst_peer_id,
        }

    @classmethod
    def from_wire(cls, d: dict) -> "PieceInfo":
        return cls(
            piece_num=d["piece_num"],
            range_start=d["range_start"],
            range_size=d["range_size"],
            digest=d.get("digest", ""),
            download_cost_ms=d.get("download_cost_ms", 0),
            dst_peer_id=d.get("dst_peer_id", ""),
        )


@dataclass
class Range:
    """HTTP byte range [start, start+length). Parsed from ``bytes=a-b``."""

    start: int
    length: int

    @staticmethod
    def normalize_header(value: str) -> str:
        """Canonical ``bytes=a-b`` form, validated. This string is TASK
        IDENTITY (task_id_v1 hashes it verbatim), so every producer of a
        ranged task — preheat jobs, client device pulls, dfget — must
        normalize through this one function or warmed ranges stop
        deduping with client pulls. Raises ValueError on malformed or
        suffix spans (suffix needs a content length no producer has)."""
        if not value:
            return ""
        v = value if value.strip().startswith("bytes=") else f"bytes={value}"
        r = Range.parse_http(v)
        # Re-emit, never echo: ' 0 - 5' and '007-100' must hash like
        # their canonical forms or equal ranges get distinct task ids.
        return r.to_http()

    @classmethod
    def parse_http(cls, header: str, content_length: int = -1) -> "Range | None":
        """Parse single-range ``bytes=a-b`` / ``bytes=a-`` / ``bytes=-n``."""
        if not header:
            return None
        value = header.strip()
        if value.startswith("bytes="):
            value = value[len("bytes="):]
        if "," in value:
            raise ValueError("multi-range not supported")
        start_s, sep, end_s = value.partition("-")
        if not sep:
            raise ValueError(f"invalid range {header!r}")
        if not start_s:  # suffix range: last N bytes
            if content_length < 0:
                raise ValueError("suffix range needs content length")
            n = int(end_s)
            return cls(max(0, content_length - n), min(n, content_length))
        start = int(start_s)
        if not end_s:
            if content_length < 0:
                return cls(start, -1)
            return cls(start, max(0, content_length - start))
        end = int(end_s)
        if end < start:
            raise ValueError(f"inverted range {header!r}")
        return cls(start, end - start + 1)

    def to_http(self) -> str:
        if self.length < 0:
            return f"bytes={self.start}-"
        return f"bytes={self.start}-{self.start + self.length - 1}"


class SizeScope:
    """Task size classes driving registration shortcuts
    (reference scheduler/resource/standard/task.go:468-490)."""

    NORMAL = "normal"   # > piece size: full piece machinery
    SMALL = "small"     # one piece: single-piece shortcut
    TINY = "tiny"       # <= 128 bytes: inlined in scheduler response
    EMPTY = "empty"     # zero bytes
    UNKNOW = "unknow"   # unknown content length

    TINY_FILE_SIZE = 128

    @classmethod
    def of(cls, content_length: int, piece_size: int, total_piece_count: int | None = None) -> str:
        if content_length < 0:
            return cls.UNKNOW
        if content_length == 0:
            return cls.EMPTY
        if content_length <= cls.TINY_FILE_SIZE:
            return cls.TINY
        if total_piece_count is None:
            total_piece_count = compute_piece_count(content_length, piece_size)
        if total_piece_count == 1:
            return cls.SMALL
        return cls.NORMAL


@dataclass
class PieceBitmap:
    """Tracks which pieces are present; persisted with task metadata
    (reference client/daemon/storage metadata piece map)."""

    total: int = -1  # -1 while content length unknown
    _bits: set[int] = field(default_factory=set)

    def mark(self, piece_num: int) -> None:
        self._bits.add(piece_num)

    def has(self, piece_num: int) -> bool:
        return piece_num in self._bits

    def count(self) -> int:
        return len(self._bits)

    def complete(self) -> bool:
        return self.total >= 0 and len(self._bits) >= self.total

    def missing(self) -> list[int]:
        if self.total < 0:
            return []
        return [i for i in range(self.total) if i not in self._bits]

    def to_wire(self) -> dict:
        return {"total": self.total, "bits": sorted(self._bits)}

    @classmethod
    def from_wire(cls, d: dict) -> "PieceBitmap":
        bm = cls(total=d.get("total", -1))
        bm._bits = set(d.get("bits", []))
        return bm
