"""Chaos fabric: deterministic, seeded fault injection for the data plane.

The P2P fabric's failure story (parent death mid-piece, corrupt bodies,
slow-loris stalls, scheduler crashes, origin 5xx bursts) was grown
piecemeal and exercised by hope. This module injects those faults FROM A
SEEDED SCHEDULE at the three choke points every byte and control message
already flows through:

  rpc.connect     rpc/client.Client._ensure_conn        refuse | stall
  rpc.recv        rpc/framing.FrameReader.read          drop | stall
  rpc.send        rpc/framing.FrameWriter.write         drop | stall
  piece.request   daemon/peer/piece_downloader GET      refuse | http5xx | stall
  piece.body      piece body stream                     truncate | corrupt | drop | stall
  source.request  source client download/probe          refuse | http5xx | stall
  source.body     origin body stream                    truncate | corrupt | drop | stall
  sched.announce  scheduler/service announce loop       drop | stall

``rpc.recv`` drop against the scheduler connection IS the
scheduler-member-crash simulation from the daemon's point of view: the
read loop dies, every pending call and stream fails, and the announce
recovery path has to do its job. ``sched.announce`` is the SERVER-side
twin — armed inside a scheduler process it severs (or stalls) announce
streams at the service loop, killing the stream for every daemon at
once without killing the process: the shard-failover drill
(tests/test_scheduler_ha.py) and the crash-recovery e2e both ride it.

Determinism: the decision for the n-th invocation of a given
``(site, key)`` is a pure function of ``(seed, site, key, n, rule)`` —
independent of event-loop interleaving across keys — so one seed
reproduces the identical fault schedule run after run, and a failing
schedule can be replayed.

Inert by default, zero hot-path overhead: the hooked modules hold a
module-level ``_chaos = None`` that only ``enable()`` ever assigns; the
hot path pays one ``is not None`` check and never imports this module
(tests/test_chaos.py pins both properties).
"""

from __future__ import annotations

import asyncio
import json
import os
import random
from dataclasses import dataclass, field
from typing import AsyncIterator

from dragonfly2_tpu.pkg import dflog, metrics

log = dflog.get("chaos")

FAULT_COUNT = metrics.counter(
    "chaos_faults_injected_total",
    "Faults injected by the chaos fabric", ("site", "kind"))

# site prefix -> fault kinds it knows how to express
KINDS = ("refuse", "drop", "truncate", "corrupt", "stall", "http5xx")

ENV_VAR = "DF_CHAOS"


@dataclass(frozen=True)
class Rule:
    """One fault rule. Matches by exact ``site``; ``key_substr`` narrows to
    invocations whose key contains it (e.g. one parent's ip:port)."""

    site: str
    kind: str
    rate: float = 0.0          # per-invocation probability (seeded stream)
    at: tuple = ()             # explicit 1-based invocation indices that fire
    key_substr: str = ""
    max_fires: int = -1        # -1 = unlimited
    stall_s: float = 0.5       # sleep for kind == "stall"
    status: int = 503          # response status for kind == "http5xx"

    def __post_init__(self):
        if self.kind not in KINDS:
            raise ValueError(f"unknown chaos fault kind {self.kind!r}")


@dataclass(frozen=True)
class Fault:
    """A decision to inject, handed back to the choke point."""

    site: str
    kind: str
    stall_s: float = 0.5
    status: int = 503


@dataclass
class ChaosFabric:
    """The seeded schedule + injection helpers.

    ``decide(site, key)`` advances the (site, key) invocation counter and
    returns the Fault to inject (or None). All the async helpers below
    translate a Fault into the native failure shape of their call site.
    """

    seed: int = 0
    rules: list = field(default_factory=list)

    def __post_init__(self):
        self._counts: dict[tuple[str, str], int] = {}
        self._fires: dict[int, int] = {}       # rule index -> times fired
        self.injected: list[tuple[str, str, int, str]] = []  # (site,key,n,kind)

    # -- schedule ----------------------------------------------------------

    @staticmethod
    def _draw(seed: int, site: str, key: str, n: int, rule_idx: int) -> float:
        # A fresh Random per decision keyed on the full coordinates: the
        # n-th decision for (site, key) is interleaving-independent.
        return random.Random(f"{seed}|{site}|{key}|{n}|{rule_idx}").random()

    def decide(self, site: str, key: str = "") -> Fault | None:
        n = self._counts.get((site, key), 0) + 1
        self._counts[(site, key)] = n
        for idx, rule in enumerate(self.rules):
            if rule.site != site:
                continue
            if rule.key_substr and rule.key_substr not in key:
                continue
            if rule.max_fires >= 0 and self._fires.get(idx, 0) >= rule.max_fires:
                continue
            hit = (n in rule.at) if rule.at else (
                rule.rate > 0.0
                and self._draw(self.seed, site, key, n, idx) < rule.rate)
            if not hit:
                continue
            self._fires[idx] = self._fires.get(idx, 0) + 1
            self.injected.append((site, key, n, rule.kind))
            FAULT_COUNT.labels(site, rule.kind).inc()
            log.info("chaos fault", site=site, key=key[:64], n=n,
                     kind=rule.kind)
            return Fault(site, rule.kind, rule.stall_s, rule.status)
        return None

    def targets(self, site_prefix: str) -> bool:
        """Does any rule touch sites under ``site_prefix``? The native
        piece path asks this once per task to route bytes through the
        (hookable) Python path while chaos aims at it."""
        return any(r.site.startswith(site_prefix) for r in self.rules)

    def injected_by_kind(self) -> dict[str, int]:
        out: dict[str, int] = {}
        for _site, _key, _n, kind in self.injected:
            out[kind] = out.get(kind, 0) + 1
        return out

    # -- injection helpers (async; called only when a hook is armed) -------

    async def on_connect(self, site: str, key: str, exc_factory) -> None:
        """Connect-shaped choke point: refuse/drop raise ``exc_factory(msg)``,
        stall sleeps then proceeds."""
        fault = self.decide(site, key)
        if fault is None:
            return
        if fault.kind == "stall":
            await asyncio.sleep(fault.stall_s)
            return
        if fault.kind == "http5xx":
            raise exc_factory(f"chaos: injected {fault.status} at {site}")
        raise exc_factory(f"chaos: injected {fault.kind} at {site}")

    async def on_frame(self, site: str, key: str) -> str | None:
        """Frame-level choke point (rpc.recv / rpc.send): returns "drop"
        when the connection should be considered lost, None to proceed.
        Stall sleeps inline (the frame still goes through afterwards)."""
        fault = self.decide(site, key)
        if fault is None:
            return None
        if fault.kind == "stall":
            await asyncio.sleep(fault.stall_s)
            return None
        return "drop"

    def on_request(self, site: str, key: str) -> Fault | None:
        """Request-shaped choke point (piece/source HTTP request): the
        caller maps the Fault into its own coded error / status. Stall is
        returned too — the caller sleeps where it can hold its timeout
        accounting together."""
        return self.decide(site, key)

    async def wrap_body(self, site: str, key: str,
                        chunks: AsyncIterator[bytes]) -> AsyncIterator[bytes]:
        """Body-stream choke point. One decision per stream, drawn at the
        first chunk so empty streams don't consume schedule entries:

          truncate  yield half of the first chunk, then end (clean EOF —
                    the length/digest checks must catch it)
          corrupt   flip one bit in the first chunk (crc32c must trip)
          drop      yield the first chunk, then die mid-stream
          stall     sleep before the first chunk (progress watchdogs trip)
        """
        fault: Fault | None = None
        first = True
        async for chunk in chunks:
            if first:
                first = False
                fault = self.decide(site, key)
                if fault is not None:
                    if fault.kind == "stall":
                        await asyncio.sleep(fault.stall_s)
                    elif fault.kind == "truncate":
                        if len(chunk) > 1:
                            yield bytes(chunk)[: max(1, len(chunk) // 2)]
                        return
                    elif fault.kind == "corrupt":
                        b = bytearray(chunk)
                        b[len(b) // 2] ^= 0x01
                        yield bytes(b)
                        fault = None   # rest of the stream flows clean
                        continue
                    elif fault.kind == "drop":
                        yield chunk
                        raise ConnectionResetError(
                            f"chaos: injected drop at {site}")
            yield chunk

    def wrap_source(self, client):
        """Proxy a source ResourceClient so origin requests/bodies pass
        through the source.* sites. Proxies are cached per client so the
        registry hands out stable objects."""
        cache = getattr(self, "_source_proxies", None)
        if cache is None:
            cache = self._source_proxies = {}
        proxy = cache.get(id(client))
        if proxy is None:
            proxy = _ChaosSourceClient(self, client)
            cache[id(client)] = proxy
        return proxy


class _ChaosSourceClient:
    """Source-client proxy: injects at source.request / source.body and
    delegates everything else untouched."""

    def __init__(self, fabric: ChaosFabric, inner):
        self._fabric = fabric
        self._inner = inner

    def __getattr__(self, name):
        return getattr(self._inner, name)

    def native_fetch_plan(self, request):
        # The native origin path bypasses Python byte handling entirely;
        # while chaos aims at the source sites, route through the hookable
        # aiohttp path instead.
        if self._fabric.targets("source"):
            return None
        plan_fn = getattr(self._inner, "native_fetch_plan", None)
        return plan_fn(request) if plan_fn is not None else None

    async def download(self, request):
        from dragonfly2_tpu.pkg.errors import Code, SourceError

        fault = self._fabric.on_request("source.request", request.url)
        if fault is not None:
            if fault.kind == "stall":
                await asyncio.sleep(fault.stall_s)
            elif fault.kind == "http5xx":
                raise SourceError(
                    f"chaos: origin {fault.status}: {request.url}",
                    Code.BackToSourceAborted, temporary=True)
            else:
                raise SourceError(
                    f"chaos: origin connect refused: {request.url}",
                    Code.BackToSourceAborted, temporary=True)
        resp = await self._inner.download(request)
        wrapped = self._fabric.wrap_body("source.body", request.url,
                                         resp.body)

        async def body():
            # Injected drops surface as the coded temporary SourceError
            # the real clients raise for a mid-stream connection loss.
            try:
                async for chunk in wrapped:
                    yield chunk
            except ConnectionResetError as e:
                raise SourceError(f"chaos: origin read {request.url}: {e}",
                                  Code.BackToSourceAborted, temporary=True)

        resp.body = body()
        return resp


# --------------------------------------------------------------------- #
# Arming / disarming the hooks
# --------------------------------------------------------------------- #

_enabled: ChaosFabric | None = None


def _hooked_modules():
    # Imported HERE, not by the hot modules: with chaos off they never
    # see this module at all.
    from dragonfly2_tpu.daemon.peer import piece_downloader
    from dragonfly2_tpu.rpc import client as rpc_client
    from dragonfly2_tpu.rpc import framing as rpc_framing
    from dragonfly2_tpu.scheduler import service as scheduler_service
    from dragonfly2_tpu.source import client as source_client

    return (rpc_client, rpc_framing, piece_downloader, source_client,
            scheduler_service)


def enable(fabric: ChaosFabric) -> ChaosFabric:
    """Arm the fabric at every choke point (process-wide)."""
    global _enabled
    _enabled = fabric
    for mod in _hooked_modules():
        mod._chaos = fabric
    log.info("chaos fabric ENABLED", seed=fabric.seed,
             rules=len(fabric.rules))
    return fabric


def disable() -> None:
    global _enabled
    _enabled = None
    for mod in _hooked_modules():
        mod._chaos = None


def enabled() -> ChaosFabric | None:
    return _enabled


def parse_spec(spec: "str | dict") -> ChaosFabric:
    """Build a fabric from a JSON spec (or an already-parsed dict):

        {"seed": 7, "rules": [
            {"site": "piece.body", "kind": "corrupt", "rate": 0.25},
            {"site": "rpc.recv", "kind": "drop", "at": [3]}]}
    """
    if isinstance(spec, str):
        spec = json.loads(spec)
    rules = [Rule(site=r["site"], kind=r["kind"],
                  rate=float(r.get("rate", 0.0)),
                  at=tuple(r.get("at") or ()),
                  key_substr=r.get("key_substr", ""),
                  max_fires=int(r.get("max_fires", -1)),
                  stall_s=float(r.get("stall_s", 0.5)),
                  status=int(r.get("status", 503)))
             for r in spec.get("rules") or []]
    return ChaosFabric(seed=int(spec.get("seed", 0)), rules=rules)


def maybe_enable_from_env() -> ChaosFabric | None:
    """Arm from ``DF_CHAOS`` (inline JSON, or ``@/path/to/spec.json``).
    Unset/empty → no-op. Called by daemon/scheduler bootstrap so real-
    process runs (benches, e2e) can inject without code changes."""
    raw = os.environ.get(ENV_VAR, "").strip()
    if not raw:
        return None
    if raw.startswith("@"):
        with open(raw[1:]) as f:
            raw = f.read()
    return enable(parse_spec(raw))
