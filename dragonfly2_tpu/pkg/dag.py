"""Generic DAG used for the per-task peer tree.

Reference: pkg/graph/dag/dag.go + vertex.go — a lock-guarded DAG with
random-vertex sampling; the scheduler stores each task's peers as vertices
and parent→child download edges (scheduler/resource/standard/task.go:154-155).
"""

from __future__ import annotations

import random
import threading
from typing import Generic, Iterator, TypeVar

T = TypeVar("T")


class DAGError(Exception):
    pass


class CycleError(DAGError):
    pass


class VertexNotFoundError(DAGError):
    pass


class Vertex(Generic[T]):
    def __init__(self, vid: str, value: T):
        self.id = vid
        self.value = value
        self.parents: dict[str, "Vertex[T]"] = {}
        self.children: dict[str, "Vertex[T]"] = {}

    def in_degree(self) -> int:
        return len(self.parents)

    def out_degree(self) -> int:
        return len(self.children)


class DAG(Generic[T]):
    """Thread-safe DAG. Edges are parent → child."""

    def __init__(self):
        self._v: dict[str, Vertex[T]] = {}
        # Sampling index: id list with swap-remove + position map, so
        # random_vertices costs O(sample), never O(vertices). The
        # candidate sampler runs on every schedule attempt and a pod
        # task holds tens of thousands of peer vertices — materializing
        # the key list per call is an O(n^2) storm tax.
        self._order: list[str] = []
        self._pos: dict[str, int] = {}
        self._mu = threading.RLock()

    def add_vertex(self, vid: str, value: T) -> None:
        with self._mu:
            if vid in self._v:
                raise DAGError(f"vertex {vid} exists")
            self._v[vid] = Vertex(vid, value)
            self._pos[vid] = len(self._order)
            self._order.append(vid)

    def delete_vertex(self, vid: str) -> None:
        with self._mu:
            v = self._v.pop(vid, None)
            if v is None:
                return
            i = self._pos.pop(vid)
            last = self._order.pop()
            if last != vid:
                self._order[i] = last
                self._pos[last] = i
            for p in v.parents.values():
                p.children.pop(vid, None)
            for c in v.children.values():
                c.parents.pop(vid, None)

    def get_vertex(self, vid: str) -> Vertex[T]:
        with self._mu:
            v = self._v.get(vid)
            if v is None:
                raise VertexNotFoundError(vid)
            return v

    def has_vertex(self, vid: str) -> bool:
        with self._mu:
            return vid in self._v

    def vertex_count(self) -> int:
        with self._mu:
            return len(self._v)

    def vertex_ids(self) -> list[str]:
        with self._mu:
            return list(self._v.keys())

    def add_edge(self, from_id: str, to_id: str) -> None:
        with self._mu:
            if from_id == to_id:
                raise CycleError("self edge")
            src = self._v.get(from_id)
            dst = self._v.get(to_id)
            if src is None or dst is None:
                raise VertexNotFoundError(from_id if src is None else to_id)
            if to_id in src.children:
                raise DAGError(f"edge {from_id}->{to_id} exists")
            if self._reachable(dst, src):
                raise CycleError(f"edge {from_id}->{to_id} creates a cycle")
            src.children[to_id] = dst
            dst.parents[from_id] = src

    def delete_edge(self, from_id: str, to_id: str) -> None:
        with self._mu:
            src = self._v.get(from_id)
            dst = self._v.get(to_id)
            if src is None or dst is None:
                return
            src.children.pop(to_id, None)
            dst.parents.pop(from_id, None)

    def delete_vertex_in_edges(self, vid: str) -> None:
        """Drop all parent edges of a vertex (peer reschedule: detach from
        its current parents — reference task.go DeletePeerInEdges)."""
        with self._mu:
            v = self._v.get(vid)
            if v is None:
                raise VertexNotFoundError(vid)
            for p in list(v.parents.values()):
                p.children.pop(vid, None)
            v.parents.clear()

    def delete_vertex_out_edges(self, vid: str) -> None:
        with self._mu:
            v = self._v.get(vid)
            if v is None:
                raise VertexNotFoundError(vid)
            for c in list(v.children.values()):
                c.parents.pop(vid, None)
            v.children.clear()

    def can_add_edge(self, from_id: str, to_id: str) -> bool:
        with self._mu:
            src = self._v.get(from_id)
            dst = self._v.get(to_id)
            if src is None or dst is None or from_id == to_id:
                return False
            if to_id in src.children:
                return False
            return not self._reachable(dst, src)

    def _reachable(self, start: Vertex[T], target: Vertex[T]) -> bool:
        """DFS: can we reach ``target`` from ``start`` following children."""
        stack = [start]
        seen: set[str] = set()
        while stack:
            v = stack.pop()
            if v.id == target.id:
                return True
            if v.id in seen:
                continue
            seen.add(v.id)
            stack.extend(v.children.values())
        return False

    def random_vertices(self, n: int) -> list[Vertex[T]]:
        """Random sample of vertices (reference dag.go random-sampling API —
        used by FilterParentLimit candidate sampling)."""
        with self._mu:
            m = len(self._order)
            if n >= m:
                sample = list(self._order)
            else:
                sample = [self._order[i]
                          for i in random.sample(range(m), n)]
            return [self._v[i] for i in sample]

    def find_value(self, pred) -> "T | None":
        """First vertex value matching ``pred``, scanning insertion order
        under the lock with early exit. Availability probes hit on the
        OLDEST vertices (where finished peers live), so this is O(1) in
        practice where ``values()`` would materialize every vertex per
        call; callers must not mutate the DAG from ``pred``."""
        with self._mu:
            for v in self._v.values():
                if pred(v.value):
                    return v.value
            return None

    def values(self) -> Iterator[T]:
        with self._mu:
            vs = list(self._v.values())
        for v in vs:
            yield v.value
