"""Well-known directories (reference: pkg/dfpath).

Default layout under a single root (overridable for tests):
  <root>/data      piece stores
  <root>/cache     dynconfig cache files
  <root>/logs      rotating logs
  <root>/run       unix sockets, pid files
  <root>/plugins   plugins
"""

from __future__ import annotations

import os
from dataclasses import dataclass, field


def default_root() -> str:
    return os.environ.get("DF_HOME", os.path.expanduser("~/.dragonfly2-tpu"))


@dataclass
class Dfpath:
    root: str = field(default_factory=default_root)

    @property
    def data_dir(self) -> str:
        return os.path.join(self.root, "data")

    @property
    def cache_dir(self) -> str:
        return os.path.join(self.root, "cache")

    @property
    def log_dir(self) -> str:
        return os.path.join(self.root, "logs")

    @property
    def run_dir(self) -> str:
        return os.path.join(self.root, "run")

    @property
    def plugins_dir(self) -> str:
        return os.path.join(self.root, "plugins")

    @property
    def daemon_sock(self) -> str:
        return os.path.join(self.run_dir, "dfdaemon.sock")

    @property
    def daemon_lock(self) -> str:
        return os.path.join(self.run_dir, "dfdaemon.lock")

    def ensure(self) -> "Dfpath":
        for d in (self.data_dir, self.cache_dir, self.log_dir, self.run_dir, self.plugins_dir):
            os.makedirs(d, exist_ok=True)
        return self
