"""Standalone metrics + debug HTTP endpoint for any binary.

Reference: Prometheus served per binary (scheduler/scheduler.go:219,
manager/metrics, client daemon metrics) and the --pprof-port runtime
dashboards (cmd/dependency/dependency.go:95-114). The /debug surface is
the Python analog of pprof: live thread stacks and asyncio task dumps.

Routes: GET /metrics (Prometheus text), GET /healthy,
        GET /debug/stacks (all thread stacks), GET /debug/tasks (asyncio).
"""

from __future__ import annotations

import asyncio
import io
import sys
import traceback

from aiohttp import web

from dragonfly2_tpu.pkg import dflog, metrics

log = dflog.get("metrics_server")


def _thread_stacks() -> str:
    out = io.StringIO()
    for thread_id, frame in sys._current_frames().items():
        out.write(f"--- thread {thread_id} ---\n")
        traceback.print_stack(frame, file=out)
        out.write("\n")
    return out.getvalue()


def _task_dump() -> str:
    out = io.StringIO()
    try:
        tasks = asyncio.all_tasks()
    except RuntimeError:
        return "no running loop\n"
    for task in tasks:
        out.write(f"--- {task.get_name()} "
                  f"{'cancelled' if task.cancelled() else 'pending'} ---\n")
        task.print_stack(file=out)
        out.write("\n")
    return out.getvalue()


class MetricsServer:
    def __init__(self):
        self._runner: web.AppRunner | None = None
        self._port = 0

    async def serve(self, host: str, port: int) -> int:
        app = web.Application()
        app.router.add_get("/metrics", self._metrics)
        app.router.add_get("/healthy", self._healthy)
        app.router.add_get("/debug/stacks", self._stacks)
        app.router.add_get("/debug/tasks", self._tasks)
        self._runner = web.AppRunner(app, access_log=None)
        await self._runner.setup()
        site = web.TCPSite(self._runner, host, port)
        await site.start()
        self._port = site._server.sockets[0].getsockname()[1]
        log.info("metrics server up", port=self._port)
        return self._port

    @property
    def port(self) -> int:
        return self._port

    async def close(self) -> None:
        if self._runner is not None:
            await self._runner.cleanup()

    async def _metrics(self, request: web.Request) -> web.Response:
        body, content_type = metrics.render()
        # content_type carries params (version/charset); aiohttp's
        # content_type kwarg rejects those — set the raw header.
        return web.Response(body=body, headers={"Content-Type": content_type})

    async def _healthy(self, request: web.Request) -> web.Response:
        return web.json_response({"ok": True})

    async def _stacks(self, request: web.Request) -> web.Response:
        return web.Response(text=_thread_stacks())

    async def _tasks(self, request: web.Request) -> web.Response:
        return web.Response(text=_task_dump())
