"""Standalone metrics + debug HTTP endpoint for any binary.

Reference: Prometheus served per binary (scheduler/scheduler.go:219,
manager/metrics, client daemon metrics) and the --pprof-port runtime
dashboards (cmd/dependency/dependency.go:95-114). The /debug surface is
the Python analog of pprof: live thread stacks and asyncio task dumps.

Routes: GET /metrics (Prometheus text; OpenMetrics via Accept),
        GET /healthy,
        GET /debug/stacks (all thread stacks), GET /debug/tasks (asyncio),
        GET /debug/profile?seconds=N (cProfile sample, pprof's CPU
        profile analog), GET /debug/heap?topn=N (tracemalloc snapshot,
        pprof's heap profile analog; first call arms tracing),
        GET /debug/flight (flight-recorder task index),
        GET /debug/flight/{task_id}[?format=text] (critical-path autopsy:
        phase breakdown + per-piece waterfall, JSON or rendered text),
        GET /debug/pod/{task_id} (scheduler-side per-host straggler
        attribution from piece-report timings),
        GET /debug/pod/{task_id}/timeline[?format=text] (pod lens: the
        merged cross-host broadcast timeline, clock-aligned, slowest
        host + dominant phase named, alignment error bound printed),
        GET /debug/slo (the continuous SLO / burn-rate engine's state),
        GET /debug/prof (runtime observatory: top-N self-time per thread
        from the always-on sampling profiler),
        GET /debug/prof/flame?format=folded (flamegraph-ready folded
        stacks from the same trie),
        GET /debug/prof/runtime (event-loop lag histograms, GC pauses,
        /proc gauges),
        GET /debug/fleet[?window=seconds] (cluster health time-series),
        GET /debug/fleet/hosts (cross-task host scorecards + straggler
        flags), GET /debug/fleet/decisions?host=|task=|kind=|n=|since=|
        before= (the scheduling decision audit log, hard-capped with a
        truncated marker), GET /debug/fleet/info (scheduler uptime /
        build / config snapshot). All fleet routes are backed by the
        bounded pkg/fleet observatory the scheduler passes in.
        GET /debug/cluster[?window=][&format=text] (manager: the merged
        cluster control-tower view — every scheduler's keepalive fleet
        frames folded with per-scheduler attribution),
        GET /debug/cluster/schedulers (per-scheduler state: active /
        inactive / no_data, frames, latest sets),
        GET /debug/cluster/slo (per-scheduler SLO condensate + breached
        union), GET /debug/cluster/events?kind=|scheduler=|n=|since=|
        before= (the edge-triggered cluster event journal). All cluster
        routes are backed by the bounded pkg/cluster series the manager
        passes in.

The route table is a class attribute (``ROUTES``) so tooling and the
docs lint (tests/test_metrics_lint.py) can introspect every registered
``/debug/*`` route without serving.
"""

from __future__ import annotations

import asyncio
import io
import sys
import traceback

from aiohttp import web

from dragonfly2_tpu.pkg import dflog, flight as flightlib, metrics

log = dflog.get("metrics_server")


def _thread_stacks() -> str:
    out = io.StringIO()
    for thread_id, frame in sys._current_frames().items():
        out.write(f"--- thread {thread_id} ---\n")
        traceback.print_stack(frame, file=out)
        out.write("\n")
    return out.getvalue()


def _task_dump() -> str:
    out = io.StringIO()
    try:
        tasks = asyncio.all_tasks()
    except RuntimeError:
        return "no running loop\n"
    for task in tasks:
        out.write(f"--- {task.get_name()} "
                  f"{'cancelled' if task.cancelled() else 'pending'} ---\n")
        task.print_stack(file=out)
        out.write("\n")
    return out.getvalue()


class MetricsServer:
    # The single source of truth for the HTTP surface: (path, handler
    # attribute name). serve() registers exactly this; debug_routes()
    # exposes it so the docs lint can demand every /debug route be
    # documented without hand-listing paths anywhere.
    ROUTES = (
        ("/metrics", "_metrics"),
        ("/healthy", "_healthy"),
        ("/debug/stacks", "_stacks"),
        ("/debug/tasks", "_tasks"),
        ("/debug/profile", "_profile"),
        ("/debug/heap", "_heap"),
        ("/debug/flight", "_flight_index"),
        ("/debug/flight/{task_id}", "_flight_task"),
        ("/debug/pod/{task_id}", "_pod_task"),
        ("/debug/pod/{task_id}/timeline", "_pod_timeline"),
        ("/debug/slo", "_slo"),
        ("/debug/prof", "_prof"),
        ("/debug/prof/flame", "_prof_flame"),
        ("/debug/prof/runtime", "_prof_runtime"),
        ("/debug/fleet", "_fleet_snapshot"),
        ("/debug/fleet/hosts", "_fleet_hosts"),
        ("/debug/fleet/decisions", "_fleet_decisions"),
        ("/debug/fleet/info", "_fleet_info"),
        ("/debug/cluster", "_cluster_view"),
        ("/debug/cluster/schedulers", "_cluster_schedulers"),
        ("/debug/cluster/slo", "_cluster_slo"),
        ("/debug/cluster/events", "_cluster_events"),
    )

    def __init__(self, *, flight: "flightlib.FlightRecorder | None" = None,
                 pod_flight: "flightlib.PodAggregator | None" = None,
                 fleet=None, slo=None, pod_timeline=None, prof=None,
                 cluster=None):
        # Optional providers: the daemon passes its flight recorder, the
        # scheduler its pod aggregator + fleet observatory + SLO engine
        # + pod-timeline assembler (an async callable task_id -> report,
        # so the on-demand FlightReport pulls stay in the scheduler);
        # the manager its cluster control tower (pkg/cluster) behind the
        # /debug/cluster* family; ALL pass the runtime observatory
        # (pkg/prof) behind /debug/prof*; endpoints 404 without one.
        self._flight = flight
        self._pod_flight = pod_flight
        self._fleet = fleet
        self._slo_engine = slo
        self._pod_timeline_provider = pod_timeline
        self._prof_obs = prof
        self._cluster = cluster
        self._runner: web.AppRunner | None = None
        self._port = 0
        self._profiling = False

    @classmethod
    def debug_routes(cls) -> list:
        """Every registered /debug route pattern — what the docs lint
        walks so no endpoint ships undocumented."""
        return [path for path, _name in cls.ROUTES
                if path.startswith("/debug/")]

    async def serve(self, host: str, port: int) -> int:
        app = web.Application()
        for path, name in self.ROUTES:
            app.router.add_get(path, getattr(self, name))
        self._runner = web.AppRunner(app, access_log=None)
        await self._runner.setup()
        site = web.TCPSite(self._runner, host, port)
        await site.start()
        self._port = site._server.sockets[0].getsockname()[1]
        log.info("metrics server up", port=self._port)
        return self._port

    @property
    def port(self) -> int:
        return self._port

    async def close(self) -> None:
        if self._runner is not None:
            await self._runner.cleanup()

    async def _metrics(self, request: web.Request) -> web.Response:
        # Content-negotiated: an OpenMetrics Accept header gets the
        # strict exposition (scrapers that parse strictly — and our own
        # round-trip test — use it).
        body, content_type = metrics.render(request.headers.get("Accept",
                                                                ""))
        # content_type carries params (version/charset); aiohttp's
        # content_type kwarg rejects those — set the raw header.
        return web.Response(body=body, headers={"Content-Type": content_type})

    async def _healthy(self, request: web.Request) -> web.Response:
        return web.json_response({"ok": True})

    async def _stacks(self, request: web.Request) -> web.Response:
        return web.Response(text=_thread_stacks())

    async def _tasks(self, request: web.Request) -> web.Response:
        return web.Response(text=_task_dump())

    async def _profile(self, request: web.Request) -> web.Response:
        """CPU profile of the event-loop thread for ?seconds=N (default 5,
        cap 60): cProfile runs while the loop keeps serving, then pstats
        text comes back — the pprof /debug/pprof/profile analog."""
        import cProfile
        import pstats

        try:
            seconds = min(max(float(request.query.get("seconds", "5")), 0.1),
                          60.0)
        except ValueError:
            return web.Response(text="bad seconds value\n", status=400)
        if self._profiling:
            return web.Response(text="a profile is already running\n",
                                status=409)
        self._profiling = True
        prof = cProfile.Profile()
        try:
            try:
                prof.enable()
            except ValueError as e:  # another profiler is active
                return web.Response(text=f"{e}\n", status=409)
            try:
                await asyncio.sleep(seconds)
            finally:
                prof.disable()
        finally:
            self._profiling = False
        out = io.StringIO()
        stats = pstats.Stats(prof, stream=out)
        stats.sort_stats("cumulative").print_stats(60)
        return web.Response(text=out.getvalue())

    async def _flight_index(self, request: web.Request) -> web.Response:
        if self._flight is None:
            raise web.HTTPNotFound(text="no flight recorder on this binary\n")
        return web.json_response({"tasks": self._flight.summary()})

    async def _flight_task(self, request: web.Request) -> web.Response:
        """The black-box autopsy: phase breakdown folding the task's event
        ring (sums to wall time) + the per-piece waterfall. ``?format=text``
        renders the same waterfall ``dfget --explain`` prints."""
        if self._flight is None:
            raise web.HTTPNotFound(text="no flight recorder on this binary\n")
        task_id = request.match_info["task_id"]
        tf = self._flight.get(task_id)
        if tf is None:
            raise web.HTTPNotFound(text=f"no flight data for {task_id}\n")
        report = flightlib.analyze(tf)
        if request.query.get("format") == "text":
            return web.Response(text=flightlib.render_waterfall(report) + "\n")
        return web.json_response(report)

    async def _pod_task(self, request: web.Request) -> web.Response:
        """Pod-level straggler attribution (scheduler binary): slowest
        host, dominant phase, quarantine correlation."""
        if self._pod_flight is None:
            raise web.HTTPNotFound(text="no pod aggregator on this binary\n")
        task_id = request.match_info["task_id"]
        report = self._pod_flight.report(task_id)
        if report is None:
            raise web.HTTPNotFound(text=f"no pod data for {task_id}\n")
        return web.json_response(report)

    async def _pod_timeline(self, request: web.Request) -> web.Response:
        """Pod lens (scheduler binary): the merged cross-host broadcast
        timeline — every host's shipped flight digest aligned onto one
        wall axis by the announce-path clock estimator, slowest host and
        dominant phase named, alignment error bound carried.
        ``?format=text`` renders the per-host phase-colored lag
        waterfall (the same renderer ``dfget --pod`` prints)."""
        if self._pod_timeline_provider is None:
            raise web.HTTPNotFound(
                text="no pod lens on this binary (scheduler-only)\n")
        task_id = request.match_info["task_id"]
        report = await self._pod_timeline_provider(task_id)
        if report is None:
            raise web.HTTPNotFound(
                text=f"no shipped flight digests for {task_id}\n")
        if request.query.get("format") == "text":
            from dragonfly2_tpu.pkg import podlens

            return web.Response(text=podlens.render_timeline(report) + "\n")
        return web.json_response(report)

    async def _slo(self, request: web.Request) -> web.Response:
        """The continuous SLO / burn-rate engine: the scheduler serves
        the full spec set; a daemon serves its runtime-only engine
        (loop_lag) when the observatory is armed."""
        if self._slo_engine is None:
            raise web.HTTPNotFound(
                text="no SLO engine on this binary\n")
        return web.json_response(self._slo_engine.report())

    def _need_prof(self):
        if self._prof_obs is None:
            raise web.HTTPNotFound(
                text="no runtime observatory on this binary "
                     "(prof.enabled=false?)\n")
        return self._prof_obs

    async def _prof(self, request: web.Request) -> web.Response:
        """Runtime observatory (pkg/prof): the always-on sampling
        profiler's top-N self-time frames per thread. ``?topn=`` bounds
        the per-thread list (default 20, cap 200)."""
        obs = self._need_prof()
        try:
            topn = min(max(int(request.query.get("topn", "20")), 1), 200)
        except ValueError:
            return web.Response(text="bad topn value\n", status=400)
        return web.json_response(obs.profile_report(topn))

    async def _prof_flame(self, request: web.Request) -> web.Response:
        """Flamegraph-ready folded stacks (``thread;frame;frame count``
        per line) from the sampler's bounded trie — pipe straight into
        flamegraph.pl / speedscope. ``format=folded`` is the only
        format."""
        obs = self._need_prof()
        if request.query.get("format", "folded") != "folded":
            return web.Response(text="only format=folded is supported\n",
                                status=400)
        return web.Response(text=obs.folded())

    async def _prof_runtime(self, request: web.Request) -> web.Response:
        """Loop-lag histograms per probed loop, GC pause/collection
        summary, and /proc/self gauges (RSS, fds, threads, ctx
        switches) — refreshed at scrape time."""
        return web.json_response(self._need_prof().runtime_report())

    def _need_fleet(self):
        if self._fleet is None:
            raise web.HTTPNotFound(text="no fleet observatory on this "
                                        "binary (scheduler-only)\n")
        return self._fleet

    async def _fleet_snapshot(self, request: web.Request) -> web.Response:
        """Cluster health time-series: counters/gauges over the trailing
        ``?window=`` seconds (default 600, clamped to the ring)."""
        fleet = self._need_fleet()
        try:
            window = max(1.0, float(request.query.get("window", "600")))
        except ValueError:
            return web.Response(text="bad window value\n", status=400)
        return web.json_response(fleet.snapshot(window))

    async def _fleet_hosts(self, request: web.Request) -> web.Response:
        """Cross-task host scorecards: serve/download EWMAs, decayed
        failure counts, upload load, straggler flags with robust z."""
        fleet = self._need_fleet()
        try:
            limit = min(max(int(request.query.get("n", "256")), 1), 4096)
        except ValueError:
            return web.Response(text="bad n value\n", status=400)
        return web.json_response(fleet.hosts_report(limit))

    async def _fleet_decisions(self, request: web.Request) -> web.Response:
        """The scheduling decision audit log, newest first, filterable by
        ?host= / ?task= / ?kind= (handout, quarantine, back_source,
        stripe_handout, stripe_reshuffle, straggler_filter,
        schedule_failed, admission, throttle — the QoS kinds carry the
        TENANT as subject) and bounded in time by ?since=/?before= (wall
        seconds, half-open [since, before)). ?n= caps the page (hard cap
        4096); a page that hit the cap with more matching entries behind
        it carries ``truncated: true`` — page back with
        ``before=<oldest ts>``."""
        fleet = self._need_fleet()
        try:
            limit = min(max(int(request.query.get("n", "256")), 1), 4096)
            since = float(request.query.get("since", "0") or 0)
            before = float(request.query.get("before", "0") or 0)
        except ValueError:
            return web.Response(text="bad n/since/before value\n",
                                status=400)
        return web.json_response(fleet.decisions.query(
            host=request.query.get("host", ""),
            task=request.query.get("task", ""),
            kind=request.query.get("kind", ""),
            limit=limit, since=since, before=before))

    async def _fleet_info(self, request: web.Request) -> web.Response:
        """Scheduler identity card: uptime, build, config snapshot, and
        the observatory's own bounds + resident bytes."""
        return web.json_response(self._need_fleet().info())

    def _need_cluster(self):
        if self._cluster is None:
            raise web.HTTPNotFound(text="no cluster control tower on this "
                                        "binary (manager-only)\n")
        return self._cluster

    async def _cluster_view(self, request: web.Request) -> web.Response:
        """The merged cluster view (manager binary): every scheduler's
        keepalive fleet frames folded into cluster totals with
        per-scheduler straggler/quarantine/breach attribution over the
        trailing ``?window=`` seconds (default 600). ``?format=text``
        renders the same view ``dfget --explain --cluster`` prints."""
        cluster = self._need_cluster()
        try:
            window = max(1.0, float(request.query.get("window", "600")))
        except ValueError:
            return web.Response(text="bad window value\n", status=400)
        report = cluster.report(window)
        if request.query.get("format") == "text":
            from dragonfly2_tpu.pkg.cluster import render_cluster

            return web.Response(text=render_cluster(report))
        return web.json_response(report)

    async def _cluster_schedulers(self, request: web.Request) -> web.Response:
        """Per-scheduler detail: state (active / inactive / no_data —
        no_data = alive keepalive, no fleet frames), frame counts and
        age, latest straggler/quarantine sets and gauges."""
        cluster = self._need_cluster()
        try:
            window = max(1.0, float(request.query.get("window", "600")))
        except ValueError:
            return web.Response(text="bad window value\n", status=400)
        return web.json_response(cluster.schedulers_report(window))

    async def _cluster_slo(self, request: web.Request) -> web.Response:
        """Per-scheduler SLO condensate (worst burn + state per SLO, as
        shipped in the frames) and the cluster-wide breached union."""
        cluster = self._need_cluster()
        try:
            window = max(1.0, float(request.query.get("window", "600")))
        except ValueError:
            return web.Response(text="bad window value\n", status=400)
        return web.json_response(cluster.slo_report(window))

    async def _cluster_events(self, request: web.Request) -> web.Response:
        """The cluster event journal, newest first: keepalive lapse /
        return, slo_breach, straggler, quarantine_storm, admission_burst
        — filterable by ?kind= / ?scheduler= and bounded by ?since= /
        ?before= (wall seconds, half-open [since, before)). ?n= caps the
        page (hard cap 4096); ``truncated: true`` marks a capped page."""
        cluster = self._need_cluster()
        try:
            limit = min(max(int(request.query.get("n", "256")), 1), 4096)
            since = float(request.query.get("since", "0") or 0)
            before = float(request.query.get("before", "0") or 0)
        except ValueError:
            return web.Response(text="bad n/since/before value\n",
                                status=400)
        return web.json_response(cluster.journal.query(
            kind=request.query.get("kind", ""),
            scheduler=request.query.get("scheduler", ""),
            limit=limit, since=since, before=before))

    async def _heap(self, request: web.Request) -> web.Response:
        """Heap allocation snapshot via tracemalloc (armed on first call;
        subsequent calls show current top allocators) — the pprof
        /debug/pprof/heap analog."""
        import tracemalloc

        try:
            topn = min(int(request.query.get("topn", "30")), 200)
        except ValueError:
            return web.Response(text="bad topn value\n", status=400)
        if not tracemalloc.is_tracing():
            tracemalloc.start()
            return web.Response(
                text="tracemalloc armed; call again for a snapshot\n")
        snapshot = tracemalloc.take_snapshot()
        current, peak = tracemalloc.get_traced_memory()
        lines = [f"traced current={current / 1e6:.1f}MB "
                 f"peak={peak / 1e6:.1f}MB", ""]
        for stat in snapshot.statistics("lineno")[:topn]:
            lines.append(str(stat))
        return web.Response(text="\n".join(lines) + "\n")
