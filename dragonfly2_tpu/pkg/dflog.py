"""Structured, per-subsystem logging.

Reference: internal/dflog (zap loggers with per-concern rotating files —
logcore.go, logger.go:34-37). We use stdlib logging with a compact
structured formatter and optional per-subsystem rotating files.
"""

from __future__ import annotations

import logging
import logging.handlers
import os
import sys
import time
from typing import Any

_CONFIGURED = False
_LOG_DIR: str | None = None


class _KVFormatter(logging.Formatter):
    """``ts level subsystem msg key=value...`` single-line format."""

    def format(self, record: logging.LogRecord) -> str:
        ts = time.strftime("%Y-%m-%dT%H:%M:%S", time.localtime(record.created))
        base = f"{ts}.{int(record.msecs):03d} {record.levelname:<5} {record.name} {record.getMessage()}"
        extras = getattr(record, "df_kv", None)
        if extras:
            kv = " ".join(f"{k}={v}" for k, v in extras.items())
            base = f"{base} {kv}"
        if record.exc_info:
            base = f"{base}\n{self.formatException(record.exc_info)}"
        return base


def configure(log_dir: str | None = None, console: bool = True, level: str = "INFO") -> None:
    """Initialize (or re-initialize) root logging. A later call with a
    log_dir upgrades an earlier default console-only setup, so import-time
    loggers never freeze the config."""
    global _CONFIGURED, _LOG_DIR
    if _CONFIGURED and (log_dir is None or log_dir == _LOG_DIR):
        # Never downgrade: argless calls (e.g. from get()) keep whatever a
        # real configure(log_dir=...) already installed.
        return
    root = logging.getLogger("df")
    for h in list(root.handlers):
        root.removeHandler(h)
    root.setLevel(getattr(logging, level.upper(), logging.INFO))
    root.propagate = False
    if console:
        h = logging.StreamHandler(sys.stderr)
        h.setFormatter(_KVFormatter())
        root.addHandler(h)
    if log_dir:
        os.makedirs(log_dir, exist_ok=True)
        _LOG_DIR = log_dir
        fh = logging.handlers.RotatingFileHandler(
            os.path.join(log_dir, "core.log"), maxBytes=64 << 20, backupCount=3
        )
        fh.setFormatter(_KVFormatter())
        root.addHandler(fh)
    _CONFIGURED = True


class Logger:
    """Subsystem logger with bound key=value context, like zap's With()."""

    def __init__(self, subsystem: str, **ctx: Any):
        self._log = logging.getLogger(f"df.{subsystem}")
        self._ctx = ctx

    def with_values(self, **ctx: Any) -> "Logger":
        merged = dict(self._ctx)
        merged.update(ctx)
        out = Logger.__new__(Logger)
        out._log = self._log
        out._ctx = merged
        return out

    def _emit(self, level: int, msg: str, kv: dict[str, Any], exc_info=None) -> None:
        merged = dict(self._ctx)
        merged.update(kv)
        self._log.log(level, msg, extra={"df_kv": merged}, exc_info=exc_info)

    def debug(self, msg: str, **kv: Any) -> None:
        self._emit(logging.DEBUG, msg, kv)

    def info(self, msg: str, **kv: Any) -> None:
        self._emit(logging.INFO, msg, kv)

    def warning(self, msg: str, **kv: Any) -> None:
        self._emit(logging.WARNING, msg, kv)

    def error(self, msg: str, exc_info=None, **kv: Any) -> None:
        self._emit(logging.ERROR, msg, kv, exc_info=exc_info)


def get(subsystem: str, **ctx: Any) -> Logger:
    configure()
    return Logger(subsystem, **ctx)
