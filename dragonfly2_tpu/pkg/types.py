"""Common small types.

Reference: pkg/types/types.go:80-95 (HostType), pkg/types/constants.go:57-58
(affinity separator), pkg/dfnet/dfnet.go (NetAddr), pkg/unit.
"""

from __future__ import annotations

import enum
from dataclasses import dataclass

# Affinity strings ("a|b|c") — element-prefix matching in the evaluator and
# manager searcher (reference types/constants.go:57-58).
AFFINITY_SEPARATOR = "|"


class HostType(enum.IntEnum):
    """Host roles (reference types/types.go:80-95). Seed tiers let operators
    express upload-capacity classes; the evaluator scores them above normal
    peers."""

    NORMAL = 0
    SUPER_SEED = 1
    STRONG_SEED = 2
    WEAK_SEED = 3

    @property
    def name_str(self) -> str:
        return _HOST_TYPE_NAMES[self]

    @classmethod
    def parse(cls, name: str) -> "HostType":
        return _HOST_TYPE_BY_NAME[name.lower()]

    def is_seed(self) -> bool:
        return self != HostType.NORMAL


_HOST_TYPE_NAMES = {
    HostType.NORMAL: "normal",
    HostType.SUPER_SEED: "super",
    HostType.STRONG_SEED: "strong",
    HostType.WEAK_SEED: "weak",
}
_HOST_TYPE_BY_NAME = {v: k for k, v in _HOST_TYPE_NAMES.items()}


class Priority(enum.IntEnum):
    """Task priority levels (reference commonv2.Priority)."""

    LEVEL0 = 0  # forbidden
    LEVEL1 = 1  # background
    LEVEL2 = 2
    LEVEL3 = 3  # normal (default)
    LEVEL4 = 4
    LEVEL5 = 5
    LEVEL6 = 6  # critical (e.g. pod-wide weight broadcast)


class TaskType(enum.IntEnum):
    """Reference commonv2.TaskType."""

    STANDARD = 0           # normal P2P download task
    PERSISTENT = 1         # pinned replica task
    PERSISTENT_CACHE = 2   # replica-managed dataset cache


@dataclass(frozen=True)
class NetAddr:
    """tcp/unix/vsock network address (reference pkg/dfnet/dfnet.go;
    vsock listener pkg/rpc/vsock.go for VM-guest daemons)."""

    type: str  # "tcp" | "unix" | "vsock"
    addr: str  # "host:port", socket path, or "cid:port"

    @classmethod
    def tcp(cls, host: str, port: int) -> "NetAddr":
        return cls("tcp", f"{host}:{port}")

    @classmethod
    def unix(cls, path: str) -> "NetAddr":
        return cls("unix", path)

    @classmethod
    def vsock(cls, cid: int, port: int) -> "NetAddr":
        return cls("vsock", f"{cid}:{port}")

    def host_port(self) -> tuple[str, int]:
        if self.type != "tcp":
            raise ValueError(f"{self} is not tcp")
        host, _, port = self.addr.rpartition(":")
        return host, int(port)

    def cid_port(self) -> tuple[int, int]:
        if self.type != "vsock":
            raise ValueError(f"{self} is not vsock")
        cid, _, port = self.addr.partition(":")
        return int(cid), int(port)

    def __str__(self) -> str:
        return f"{self.type}://{self.addr}"


# Byte units (reference pkg/unit).
KB = 1024
MB = 1024 * KB
GB = 1024 * MB
TB = 1024 * GB


def parse_size(s: str | int | float) -> int:
    """Parse '4MiB' / '100M' / '1.5GB' / plain int."""
    if isinstance(s, (int, float)):
        return int(s)
    s = s.strip()
    units = [("TIB", TB), ("GIB", GB), ("MIB", MB), ("KIB", KB),
             ("TB", TB), ("GB", GB), ("MB", MB), ("KB", KB),
             ("T", TB), ("G", GB), ("M", MB), ("K", KB), ("B", 1)]
    upper = s.upper()
    for suffix, mult in units:
        if upper.endswith(suffix):
            return int(float(upper[: -len(suffix)]) * mult)
    return int(float(s))


def format_size(n: int) -> str:
    for suffix, mult in (("TiB", TB), ("GiB", GB), ("MiB", MB), ("KiB", KB)):
        if n >= mult:
            return f"{n / mult:.2f}{suffix}"
    return f"{n}B"
