"""Plugin loader: source clients, evaluators, searchers from outside the
package.

Reference: internal/dfplugin/dfplugin.go:53-55 — Go ``plugin.Open`` of
``d7y-{type}-plugin-{name}.so`` from the dfpath plugin dir, looked up by
a ``DragonflyPlugin`` symbol. The Python-native equivalent loads from two
places:

1. **Plugin directory** (``DRAGONFLY_PLUGIN_DIR`` env or an explicit
   path): every ``df_plugin_*.py`` file is imported and its ``register``
   hook called. This matches the reference's drop-a-file deployment
   model.
2. **Entry points** (group ``dragonfly2_tpu.plugins``): pip-installed
   plugin packages register the same way.

A plugin module/object exposes::

    PLUGIN_TYPE = "source" | "evaluator" | "searcher"
    PLUGIN_NAME = "myscheme"          # scheme for source, algo name else
    def create(**kwargs): ...         # returns the client/evaluator/...

or a single ``register(registry)`` function for full control.
"""

from __future__ import annotations

import importlib.util
import os
import sys
import threading

from dragonfly2_tpu.pkg import dflog

log = dflog.get("pkg.dfplugin")

ENTRY_POINT_GROUP = "dragonfly2_tpu.plugins"
PLUGIN_FILE_PREFIX = "df_plugin_"

TYPE_SOURCE = "source"
TYPE_EVALUATOR = "evaluator"
TYPE_SEARCHER = "searcher"


class PluginRegistry:
    def __init__(self):
        self._factories: dict[tuple[str, str], object] = {}
        self._loaded_dirs: set[str] = set()
        self._entry_points_loaded = False
        self._lock = threading.Lock()

    # -- registration (called by plugins) ----------------------------------

    def add(self, plugin_type: str, name: str, factory) -> None:
        if plugin_type not in (TYPE_SOURCE, TYPE_EVALUATOR, TYPE_SEARCHER):
            raise ValueError(f"unknown plugin type {plugin_type!r}")
        self._factories[(plugin_type, name.lower())] = factory
        log.info("plugin registered", type=plugin_type, name=name)

    # -- lookup (called by subsystems) -------------------------------------

    def get(self, plugin_type: str, name: str):
        """Factory for (type, name) or None. Loads plugin sources lazily."""
        self.load()
        return self._factories.get((plugin_type, name.lower()))

    def create(self, plugin_type: str, name: str, **kwargs):
        factory = self.get(plugin_type, name)
        if factory is None:
            raise LookupError(f"no {plugin_type} plugin named {name!r}")
        return factory(**kwargs) if callable(factory) else factory

    def names(self, plugin_type: str) -> list[str]:
        self.load()
        return sorted(n for t, n in self._factories if t == plugin_type)

    # -- loading -----------------------------------------------------------

    def load(self, plugin_dir: str | None = None) -> None:
        with self._lock:
            self._load_entry_points()
            for d in (plugin_dir, os.environ.get("DRAGONFLY_PLUGIN_DIR")):
                if d:
                    self._load_dir(d)

    def _load_entry_points(self) -> None:
        if self._entry_points_loaded:
            return
        self._entry_points_loaded = True
        try:
            from importlib.metadata import entry_points

            for ep in entry_points(group=ENTRY_POINT_GROUP):
                try:
                    self._register_module(ep.load())
                except Exception:
                    log.error("entry-point plugin failed", name=ep.name,
                              exc_info=True)
        except Exception:
            pass

    def _load_dir(self, plugin_dir: str) -> None:
        plugin_dir = os.path.abspath(plugin_dir)
        if plugin_dir in self._loaded_dirs or not os.path.isdir(plugin_dir):
            return
        self._loaded_dirs.add(plugin_dir)
        for fname in sorted(os.listdir(plugin_dir)):
            if not (fname.startswith(PLUGIN_FILE_PREFIX)
                    and fname.endswith(".py")):
                continue
            mod_name = f"_df_plugins.{fname[:-3]}"
            path = os.path.join(plugin_dir, fname)
            try:
                spec = importlib.util.spec_from_file_location(mod_name, path)
                module = importlib.util.module_from_spec(spec)
                sys.modules[mod_name] = module
                spec.loader.exec_module(module)
                self._register_module(module)
            except Exception:
                log.error("plugin file failed", path=path, exc_info=True)

    def _register_module(self, module) -> None:
        register = getattr(module, "register", None)
        if callable(register):
            register(self)
            return
        ptype = getattr(module, "PLUGIN_TYPE", None)
        name = getattr(module, "PLUGIN_NAME", None)
        create = getattr(module, "create", None)
        if ptype and name and create:
            self.add(ptype, name, create)
        else:
            log.warning("plugin exposes neither register() nor "
                        "PLUGIN_TYPE/PLUGIN_NAME/create",
                        module=getattr(module, "__name__", "?"))


_default = PluginRegistry()


def registry() -> PluginRegistry:
    return _default


def load(plugin_dir: str | None = None) -> PluginRegistry:
    _default.load(plugin_dir)
    return _default
