"""Accelerator-plugin environment scrub for hermetic subprocesses.

The sandbox ships a ``sitecustomize`` that dials a TPU relay whenever
``PALLAS_AXON_POOL_IPS`` is set, so any subprocess that must stay
device-free (CPU dryruns, bench daemons, E2E children) has to drop every
accelerator-plugin trigger var before spawning — inheriting even one makes
the "clean" child block on a wedged tunnel (round-3 failure:
MULTICHIP_r03 rc=124 with no diagnostic). This is the single shared scrub;
spawners must not carry private copies of the prefix list, because a new
trigger prefix added in one copy and missed in another silently regresses
hermeticity exactly where it is least observable.
"""

from __future__ import annotations

# Every env-var prefix that can cause an accelerator plugin (axon relay,
# libtpu) to initialize inside a subprocess that should never touch one.
ACCELERATOR_ENV_PREFIXES = ("PALLAS_AXON", "AXON_", "TPU_", "LIBTPU")

# Path substrings that mark accelerator-plugin site dirs (the dirs whose
# sitecustomize dials the relay). Shared for the same reason as the env
# prefixes: a marker added in one spawner's private copy and missed in
# another silently regresses hermeticity.
ACCELERATOR_PATH_MARKERS = ("axon_site",)


def scrub_accelerator_env(env: dict) -> dict:
    """Delete accelerator-plugin trigger vars from ``env`` in place.

    Returns the same mapping for call-chaining. Callers that also need a
    specific JAX platform or XLA flags set them after scrubbing.
    """
    for key in list(env):
        if key.startswith(ACCELERATOR_ENV_PREFIXES):
            del env[key]
    return env


def scrub_plugin_paths(paths) -> list:
    """Return ``paths`` minus accelerator-plugin site dirs (and empties)."""
    return [p for p in paths
            if p and not any(m in p for m in ACCELERATOR_PATH_MARKERS)]
