"""Shared kernel packages (reference: pkg/ and internal/)."""
